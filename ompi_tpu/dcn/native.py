"""Native DCN engine — the C++ host data plane behind the Python
control plane.

≈ SURVEY.md §2's native-path rule ("shared-memory & TCP transports,
progress engine, request engine … in C++"): :mod:`native/src/dcn.cc`
(``libtpudcn.so``) owns framing, sockets, shared-memory rings, the
coll-stream slots, and the p2p matching engine; this module is the
ctypes control plane — connection bring-up via the modex address,
rendezvous policy knobs, communicator bookkeeping, and the ULFM/
monitoring integration points all stay Python.

Blocked receives sleep INSIDE C (GIL released) on a condition variable
the C receiver thread notifies — no Python thread handoff on the
latency path.  Frames that need Python semantics (heartbeats, ULFM
gossip/revoke, OSC RMA envelopes, communicators whose pml is wrapped
by monitoring/vprotocol) arrive on a single dispatcher thread that
blocks in ``tdcn_ctrl_next`` and feeds the same
:meth:`DcnCollEngine._on_frame` router the Python transport used —
full behavioral compatibility at control-plane rates.

Engine classes mirror the Python trio: :class:`NativeDcnEngine` (root,
owns the C engine), :class:`NativeSubEngine` (cross-process
comm_split view), :class:`NativeJoinEngine` (spawn/join across
worlds).  All three share the root's C engine; sub/join views only
remap indices, exactly like their Python counterparts.
"""

from __future__ import annotations

import ctypes
import itertools
import json
import threading
import time
import weakref
from typing import Callable, Sequence

import numpy as np

from ompi_tpu.core.errors import (
    MPIInternalError,
    MPIProcFailedError,
)
from ompi_tpu.faultsim import core as _fsim
from ompi_tpu.metrics import core as _metrics
from ompi_tpu.trace import causal as _causal
from .collops import DcnCollEngine, DcnJoinEngine, DcnSubEngine

FK_COLL, FK_P2P, FK_PY = 0, 1, 2

_RC_TIMEOUT = 1
_RC_FAILED = -2
_RC_CLOSED = -3


class TdcnMsg(ctypes.Structure):
    _pack_ = 1
    _fields_ = [
        ("kind", ctypes.c_int32),
        ("src", ctypes.c_int32),
        ("dst", ctypes.c_int32),
        ("tag", ctypes.c_int32),
        ("seq", ctypes.c_int64),
        ("pyhandle", ctypes.c_uint64),
        ("data", ctypes.c_void_p),
        ("nbytes", ctypes.c_uint64),
        ("count", ctypes.c_int64),
        ("dtype", ctypes.c_char * 16),
        ("ndim", ctypes.c_int32),
        ("shape", ctypes.c_int64 * 8),
        ("cid", ctypes.c_char * 128),
        ("meta", ctypes.c_void_p),
        ("meta_len", ctypes.c_uint32),
    ]


_lib = None
_lib_lock = threading.Lock()

#: lazy-modex resolver callback shape (tdcn_set_resolver): C hands a
#: writable buffer and the Python side copies the NUL-terminated
#: address in, returning its length (-1 = unresolvable) — a
#: char*-returning callback would hand C memory whose Python owner can
#: be collected before the engine reads it
RESOLVER_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int,
                               ctypes.POINTER(ctypes.c_char),
                               ctypes.c_int)


def load_library():
    """Build (cached) and load libtpudcn.so with typed signatures."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from ompi_tpu import native as nat

        nat.build()
        path = nat.BUILD_DIR / "libtpudcn.so"
        lib = ctypes.CDLL(str(path))
        P, I, I64, U64, D, S = (ctypes.c_void_p, ctypes.c_int,
                                ctypes.c_int64, ctypes.c_uint64,
                                ctypes.c_double, ctypes.c_char_p)
        MSG = ctypes.POINTER(TdcnMsg)
        lib.tdcn_create.restype = P
        lib.tdcn_create.argtypes = [I, I, S, I64, I64, U64, I]
        lib.tdcn_address.restype = ctypes.c_char_p
        lib.tdcn_address.argtypes = [P]
        lib.tdcn_set_addresses.argtypes = [P, S]
        lib.tdcn_send_addr.restype = I
        lib.tdcn_send_addr.argtypes = [
            P, S, I, S, I64, I, I, I, S, I,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p, I,
            ctypes.c_void_p, U64]
        lib.tdcn_send_local.restype = I
        lib.tdcn_send_local.argtypes = [P, I, S, I64, I, I, I, U64, I64,
                                        U64]
        lib.tdcn_send_local_data.restype = I
        lib.tdcn_send_local_data.argtypes = [
            P, I, S, I64, I, I, I, S, I,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_void_p, U64]
        lib.tdcn_recv_coll.restype = I
        lib.tdcn_recv_coll.argtypes = [P, S, I64, I, I, D, MSG]
        lib.tdcn_post_recv.restype = U64
        lib.tdcn_post_recv.argtypes = [P, S, I, I, I]
        lib.tdcn_post_recv_into.restype = U64
        lib.tdcn_post_recv_into.argtypes = [P, S, I, I, I,
                                            ctypes.c_void_p, U64]
        lib.tdcn_req_wait.restype = I
        lib.tdcn_req_wait.argtypes = [P, U64, D, MSG]
        lib.tdcn_req_test.restype = I
        lib.tdcn_req_test.argtypes = [P, U64, MSG]
        lib.tdcn_req_cancel.restype = I
        lib.tdcn_req_cancel.argtypes = [P, U64]
        lib.tdcn_probe.restype = I
        lib.tdcn_probe.argtypes = [P, S, I, I, I, MSG]
        lib.tdcn_pending.restype = I
        lib.tdcn_pending.argtypes = [P, S, I, I]
        lib.tdcn_register_pycid.argtypes = [P, S]
        lib.tdcn_unregister_cid.argtypes = [P, S]
        lib.tdcn_ctrl_next.restype = I
        lib.tdcn_ctrl_next.argtypes = [P, D, MSG]
        lib.tdcn_note_failed.argtypes = [P, I]
        lib.tdcn_is_failed.restype = I
        lib.tdcn_is_failed.argtypes = [P, I]
        lib.tdcn_bytes_sent.restype = U64
        lib.tdcn_bytes_sent.argtypes = [P]
        lib.tdcn_stats.restype = I
        lib.tdcn_stats.argtypes = [P, ctypes.POINTER(ctypes.c_uint64), I]
        lib.tdcn_stats_names.restype = ctypes.c_char_p
        lib.tdcn_stats_names.argtypes = []
        lib.tdcn_waitinfo.restype = I
        lib.tdcn_waitinfo.argtypes = [P, ctypes.c_char_p, I]
        lib.tdcn_hang_diag.argtypes = [I]
        lib.tdcn_trace_ctx_version.restype = I
        lib.tdcn_trace_ctx_version.argtypes = []
        lib.tdcn_trace_ctx_fields.restype = ctypes.c_char_p
        lib.tdcn_trace_ctx_fields.argtypes = []
        lib.tdcn_fault_set.argtypes = [U64, U64, I64]
        lib.tdcn_fault_events.restype = U64
        lib.tdcn_fault_events.argtypes = []
        lib.tdcn_fault_set_conn.argtypes = [I64]
        lib.tdcn_fault_set_dup.argtypes = [I64]
        lib.tdcn_fault_set_recv.argtypes = [U64, U64]
        lib.tdcn_rx_watermark.restype = U64
        lib.tdcn_rx_watermark.argtypes = [P, I]
        lib.tdcn_chan_kill.argtypes = [P, U64]
        lib.tdcn_kill_peer.argtypes = [P, S]
        lib.tdcn_clear_failed.argtypes = [P, I]
        lib.tdcn_set_address_one.restype = I
        lib.tdcn_set_address_one.argtypes = [P, I, S, I]
        lib.tdcn_set_resolver.argtypes = [P, RESOLVER_FN]
        lib.tdcn_coll_revoke_cid.argtypes = [P, S]
        lib.tdcn_coll_optime.restype = I
        lib.tdcn_coll_optime.argtypes = [P, I,
                                         ctypes.POINTER(ctypes.c_uint64),
                                         I]
        # the C collective fast-path surface (normally driven by the
        # shim; declared here so in-process tests/tools can exercise
        # the coll recv_into + per-op timing legs with correct widths)
        lib.tdcn_coll_open.restype = U64
        lib.tdcn_coll_open.argtypes = [P, S, I, I,
                                       ctypes.POINTER(ctypes.c_char_p),
                                       U64]
        lib.tdcn_coll_plan.restype = U64
        lib.tdcn_coll_plan.argtypes = [P, U64, I, I, I, I64, I, I]
        lib.tdcn_coll_start.restype = I
        lib.tdcn_coll_start.argtypes = [P, U64, ctypes.c_void_p,
                                        ctypes.c_void_p]
        lib.tdcn_coll_close.argtypes = [P, U64]
        lib.tdcn_set_ring_timeout.argtypes = [P, D]
        lib.tdcn_set_connect_timeout.argtypes = [P, D]
        lib.tdcn_free.argtypes = [ctypes.c_void_p]
        lib.tdcn_close.argtypes = [P]
        lib.tdcn_destroy.argtypes = [P]
        lib.tdcn_chan_open.restype = U64
        lib.tdcn_chan_open.argtypes = [P, S, S]
        lib.tdcn_chan_close.argtypes = [P, U64]
        lib.tdcn_chan_send.restype = I
        lib.tdcn_chan_send.argtypes = [
            P, U64, I, I, I, I, S, I, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_void_p, U64]
        lib.tdcn_precv.restype = I
        lib.tdcn_precv.argtypes = [P, S, I, I, I, I, D, MSG]
        lib.tdcn_precv_into.restype = I
        lib.tdcn_precv_into.argtypes = [P, S, I, I, I, I, D,
                                        ctypes.c_void_p, U64, MSG]
        lib.tdcn_chan_send1.restype = I
        lib.tdcn_chan_send1.argtypes = [
            P, U64, I, I, I, I, S, I64, ctypes.c_void_p, U64]
        lib.tdcn_chan_isend1.restype = I64
        lib.tdcn_chan_isend1.argtypes = [
            P, U64, I, I, I, I, S, I64, ctypes.c_void_p, U64, I]
        lib.tdcn_send_wait.restype = I
        lib.tdcn_send_wait.argtypes = [P, I64, D]
        lib.tdcn_send_test.restype = I
        lib.tdcn_send_test.argtypes = [P, I64]
        lib.tdcn_send_done.restype = I
        lib.tdcn_send_done.argtypes = [P, I64]
        lib.tdcn_send_forget.argtypes = [P, I64]
        lib.tdcn_set_stream.argtypes = [P, U64, U64, I]
        _lib = lib
        return lib


_tls = threading.local()


def _tls_msg() -> TdcnMsg:
    """Reusable per-thread TdcnMsg: safe because every consumer copies
    or re-owns the payload before the next native call."""
    m = getattr(_tls, "msg", None)
    if m is None:
        m = TdcnMsg()
        _tls.msg = m
        _tls.msg_ref = ctypes.byref(m)
    return m


def available() -> bool:
    try:
        load_library()
        return True
    except Exception:  # noqa: BLE001 — no toolchain / unsupported OS
        return False


def transport_tuning() -> tuple[int, int, bool]:
    """Resolve the streaming-send-engine knobs (``dcn_chunk_bytes``,
    ``dcn_inflight_limit``, ``dcn_doorbell_coalesce``) against the
    default MCA context, falling back to the central TRANSPORT_VARS
    defaults (bare engines in unit tests)."""
    from ompi_tpu.core.var import TRANSPORT_VARS, full_var_name

    vals: dict[str, object] = {
        full_var_name(fw, comp, name): default
        for fw, comp, name, default, _typ, _h in TRANSPORT_VARS
    }
    try:
        from ompi_tpu.core import mca

        store = mca.default_context().store
        for full in vals:
            v = store.get(full)
            if v is not None:
                vals[full] = v
    except Exception:  # noqa: BLE001 — pre-init / teardown: defaults
        pass
    return (int(vals["dcn_chunk_bytes"]),
            int(vals["dcn_inflight_limit"]),
            bool(vals["dcn_doorbell_coalesce"]))


_dtype_cache: dict[bytes, np.dtype] = {}
_dtype_bytes: dict[object, bytes] = {}


def _dt_of(code: bytes) -> np.dtype:
    dt = _dtype_cache.get(code)
    if dt is None:
        dt = np.dtype(code.decode() or "u1")
        _dtype_cache[code] = dt
    return dt


def _dt_bytes(dt: np.dtype) -> bytes:
    b = _dtype_bytes.get(dt)
    if b is None:
        b = dt.str.encode()
        _dtype_bytes[dt] = b
    return b


#: below this, copying into a fresh numpy buffer and freeing the C
#: allocation immediately beats the zero-copy wrapper's finalizer cost
_COPY_LIMIT = 64 << 10


def _wrap_payload(lib, msg: TdcnMsg) -> np.ndarray:
    """Numpy array over the C-owned payload: small payloads are copied
    (and the native buffer freed now); large ones are wrapped zero-copy
    with a finalizer freeing the native allocation at GC."""
    dt = _dt_of(msg.dtype)
    shape = tuple(msg.shape[i] for i in range(msg.ndim))
    if not msg.nbytes:
        return np.empty(shape if msg.ndim else (0,), dt)
    if msg.nbytes <= _COPY_LIMIT:
        src = np.frombuffer(
            (ctypes.c_char * msg.nbytes).from_address(msg.data),
            dtype=np.uint8)
        arr = src.view(dt).reshape(shape).copy()
        lib.tdcn_free(msg.data)
        return arr
    buf = (ctypes.c_char * msg.nbytes).from_address(msg.data)
    weakref.finalize(buf, lib.tdcn_free, msg.data)
    arr = np.frombuffer(buf, dtype=np.uint8).view(dt)
    return arr.reshape(shape)


def _meta_of(lib, msg: TdcnMsg):
    if not msg.meta:
        return None
    raw = ctypes.string_at(msg.meta, msg.meta_len)
    lib.tdcn_free(msg.meta)
    msg.meta = None
    try:
        return json.loads(raw.decode())
    except ValueError:
        return None


class _NativeTransportView:
    """The ``engine.transport`` surface other layers read (address,
    bytes_sent, liveness) mapped onto the C engine."""

    def __init__(self, eng: "NativeDcnEngine"):
        self._eng = eng

    @property
    def address(self) -> str:
        return self._eng.address

    @property
    def bytes_sent(self) -> int:
        return int(self._eng._lib.tdcn_bytes_sent(self._eng._h))

    @property
    def _running(self) -> bool:
        return self._eng._running

    def close(self) -> None:
        self._eng.close()


class _NativeOpsMixin:
    """Byte-plane methods shared by root/sub/join native engines; all
    route through the ROOT engine's C handle with address-mapped
    peers (sub/join views only remap indices)."""

    def _native_root(self) -> "NativeDcnEngine":
        raise NotImplementedError

    def root_proc_of(self, local: int) -> int:
        """Map a LOCAL engine index to the root engine's proc index
        (-1 = unmapped, e.g. across spawn worlds)."""
        raise NotImplementedError

    # -- coll streams ---------------------------------------------------

    def _fsim_drop(self) -> bool:
        """Consult the fault plane for one native record-path send
        (site ``send`` — the same schedule the Python transports use).
        The native plane performs drop/delay only (connection faults
        belong to the C layer's ring hook and the Python transports);
        the kinds filter keeps unsupported rules out of the injected
        counts.  True → the record is 'lost on the wire'."""
        for act in _fsim.actions("send", kinds={"drop", "delay"}):
            if act.kind == "delay":
                _fsim.apply_delay(act)
            elif act.kind == "drop":
                return True
        return False

    def _raise_send_failed(self, dst: int, rc: int, what: str):
        """Map a C-plane send failure onto ULFM escalation: mark the
        peer failed (detector when attached) and raise
        MPIProcFailedError — a dead native peer must surface exactly
        like a dead Python-plane peer."""
        root = self._native_root()
        if rc == _RC_CLOSED or not root._running:
            raise MPIInternalError(f"native dcn {what}: engine closed")
        if rc != -1:  # addressing/shape misuse, not a transport fault
            raise MPIInternalError(
                f"native dcn {what} to proc {dst} failed (rc={rc})")
        from ompi_tpu.metrics import export as _mexport
        from ompi_tpu.metrics import flight as _flight

        _flight.record("peer_escalation", proc=int(dst), what=what)
        # crash-path export (once-latch): the native plane's escalation
        # must leave telemetry files behind like the Python plane's
        _mexport.crash_dump("peer_escalation")
        rp = self.root_proc_of(dst)
        if rp is not None and rp >= 0:
            det = root._detector
            if det is not None:
                det.mark_failed(rp)
            else:
                root.note_proc_failed(rp)
            raise MPIProcFailedError(
                f"native dcn {what}: peer proc {dst} failed (rc={rc})",
                failed=(dst,))
        raise ConnectionError(
            f"native dcn {what} to proc {dst} failed (rc={rc})")

    def _send(self, dst: int, cid, seq: int, payload: np.ndarray,
              meta=None) -> None:
        root = self._native_root()
        if _fsim._enabled and self._fsim_drop():
            return  # lost record: the receiver's deadline escalates
        # plane arbitration (dcn/device.py): a large contiguous payload
        # rides a device window; the C host plane carries only its
        # descriptor (in the meta JSON) — same protocol as the Python
        # engine, so mixed-size schedules interleave planes freely
        from . import device as _device

        msg_nbytes = payload.nbytes if isinstance(payload, np.ndarray) \
            else None
        desc = (_device.try_stage(root, payload, self.root_proc_of(dst))
                if meta is None or isinstance(meta, dict) else None)
        if desc is not None:
            meta = dict(meta) if meta else {}
            meta[_device.DESC_KEY] = desc
            payload = np.zeros(0, np.uint8)
        arr = np.ascontiguousarray(payload)
        if _metrics._enabled:
            # sample the MESSAGE size, not the wire record's: a
            # device-routed payload ships an empty descriptor frame
            # but the op still moved msg_nbytes
            _metrics.observe_size(
                "dcn_coll_send",
                msg_nbytes if msg_nbytes is not None else arr.nbytes)
            from ompi_tpu.metrics import flight as _flight

            _flight.check_watermarks()
        if _causal._enabled and (meta is None or isinstance(meta, dict)):
            # causal wire context on the native plane: rides the
            # frame's meta-JSON region (the device descriptor's
            # vehicle) — WireHdr stays frozen, disabled frames stay
            # byte-identical; TDCN_TRACE_CTX_FIELDS in dcn.cc mirrors
            # the field table (tpucheck wire-ctx-drift)
            tc = _causal.note_send(self.root_proc_of(dst))
            if tc is not None:
                meta = dict(meta) if meta else {}
                meta["tc"] = tc
        meta_b = json.dumps(meta).encode() if meta is not None else None
        rc = root._csend(
            self.addresses[dst], FK_COLL, str(cid), seq, self.proc, 0, 0,
            arr, meta_b)
        if rc != 0:
            self._raise_send_failed(dst, rc, f"send (cid={cid}, seq={seq})")

    def _recv_full(self, src: int, cid, seq: int,
                   timeout: float | None = None, into=None):
        # `into` (the Python transports' recv_into posting): the C
        # coll-slot delivery owns its payload (callers fall back to
        # their copy on non-identity), but a DEVICE-plane descriptor
        # frame materializes straight into it below
        from ompi_tpu.core.var import Deadline, dcn_timeout

        if timeout is None:
            timeout = dcn_timeout("recv")
        tw0 = time.perf_counter_ns() if _causal._enabled else 0
        root = self._native_root()
        lib, h = root._lib, root._h
        fail_idx = self.root_proc_of(src)
        msg = TdcnMsg()
        dl = Deadline(timeout)
        while True:
            rc = lib.tdcn_recv_coll(h, str(cid).encode(), seq, src,
                                    fail_idx, dl.slice(0.25),
                                    ctypes.byref(msg))
            if rc == 0:
                break
            if rc == _RC_CLOSED:
                raise MPIInternalError("DCN recv: engine closed")
            if (rc == _RC_FAILED or
                    (fail_idx >= 0 and root.proc_failed(fail_idx))):
                raise MPIProcFailedError(
                    f"DCN recv: peer proc {src} failed (cid={cid}, "
                    f"seq={seq})", failed=(src,))
            # revoke interrupt between C wait slices (same contract as
            # the Python plane's _check_revoked)
            self._check_revoked(cid, src, seq)
            if dl.expired():
                # flight-record the ring/rendezvous state BEFORE the
                # raise (a wedged windowed send dumps its counters
                # instead of vanishing with the process), then the one
                # shared escalation: mark failed + MPIProcFailedError,
                # never a bare internal error the job cannot survive
                from ompi_tpu.metrics import flight as _flight

                _flight.record("recv_timeout", cid=str(cid), seq=seq,
                               src=src, timeout_s=timeout)
                self._escalate_deadline(
                    "coll_recv", timeout,
                    f"DCN recv deadline (dcn_recv_timeout={timeout}s) "
                    f"expired: proc {self.proc} waiting for proc {src} "
                    f"(cid={cid}, seq={seq}) — peer dead, wedged, or "
                    f"collective order mismatch",
                    failed_rank=src, root_proc=fail_idx,
                    cid=str(cid), seq=int(seq))
        if fail_idx >= 0:
            det = root._detector
            note = getattr(det, "note_activity", None)
            if note is not None:
                note(fail_idx)  # a delivered frame proves the peer alive
        env = {"cid": cid, "seq": seq, "src": src}
        meta = _meta_of(lib, msg)
        payload = _wrap_payload(lib, msg)
        if isinstance(meta, dict) and "dev" in meta:
            # device-plane delivery: the C frame carried only the
            # window descriptor — recv-semaphore wait + materialize
            # (straight into the posted buffer when one matches)
            from . import device as _device

            desc = meta.pop("dev")
            payload = _device.materialize(
                root, desc, into=into,
                src_root=(fail_idx if fail_idx >= 0 else None))
        tc = None
        if isinstance(meta, dict):
            # "tc" is a reserved meta key like "dev": popped here
            # whether or not THIS rank records, so a consumer's meta
            # never grows a foreign field
            tc = meta.pop("tc", None)
            if not meta:
                meta = None
        if tw0:
            _causal.note_recv(self.root_proc_of(src), tc,
                              time.perf_counter_ns() - tw0)
        if meta is not None:
            env["meta"] = meta
        return env, payload

    # -- p2p / control --------------------------------------------------

    def send_p2p(self, dst_proc: int, envelope: dict, payload) -> None:
        root = self._native_root()
        arr = np.ascontiguousarray(np.asarray(payload))
        if _fsim._enabled and self._fsim_drop():
            return
        if _metrics._enabled:
            _metrics.observe_size("dcn_p2p_send", arr.nbytes)
        keys = set(envelope)
        cid = envelope.get("cid")
        if keys == {"cid", "src", "dst", "tag"} and root.is_native_cid(cid):
            rc = root._csend(
                self.addresses[dst_proc], FK_P2P, str(cid), 0,
                int(envelope["src"]), int(envelope["dst"]),
                int(envelope["tag"]), arr, None)
        else:
            env = dict(envelope)
            env["kind"] = "p2p"
            rc = root._csend(
                self.addresses[dst_proc], FK_PY, str(cid), 0, 0, 0, 0,
                arr, json.dumps(env).encode())
        if rc != 0:
            self._raise_send_failed(dst_proc, rc, "p2p send")

    def send_ctrl(self, dst: int, envelope: dict) -> None:
        # control traffic (heartbeats, gossip, revoke) is exempt from
        # fault injection and escalates nowhere here: the detector owns
        # interpreting its failures (in-band detection)
        root = self._native_root()
        rc = root._csend(
            self.addresses[dst], FK_PY, "", 0, 0, 0, 0,
            np.zeros(0, np.uint8), json.dumps(dict(envelope)).encode())
        if rc != 0:
            raise ConnectionError(
                f"native dcn ctrl send to proc {dst} failed (rc={rc})")

    # -- engine views ---------------------------------------------------

    def sub(self, procs: Sequence[int]) -> "NativeSubEngine":
        return NativeSubEngine(self, procs)

    def join(self, addresses: Sequence[str], proc: int) -> "NativeJoinEngine":
        return NativeJoinEngine(self, addresses, proc)


class NativeDcnEngine(_NativeOpsMixin, DcnCollEngine):
    """Root engine: owns the C engine, the dispatcher thread, and the
    local-payload handle table."""

    def __init__(
        self,
        proc: int,
        nprocs: int,
        addresses: Sequence[str] | None = None,
        eager_limit: int = 4 << 20,
        frag_size: int = 8 << 20,
        max_rndv: int = 4,
        ring_threshold: int = 64 << 10,
        ring_bytes: int = 64 << 20,
        **_ignored,
    ):
        # deliberately NOT calling DcnCollEngine.__init__ — no Python
        # transport; replicate the control-plane state it set up
        self.proc = proc
        self.nprocs = nprocs
        self.ring_threshold = int(ring_threshold)
        self.addresses = list(addresses) if addresses else []
        self._seq: dict = {}
        self._failed_procs: set[int] = set()
        self._detector = None
        self._comms: dict = {}
        self._p2p_handlers: dict[object, Callable] = {}
        self._p2p_pending: dict = {}
        self._p2p_closed: set = set()
        self._p2p_lock = threading.Lock()
        self._queues: dict = {}
        self._qlock = threading.Lock()

        self._lib = load_library()
        host_id = self._host_id()
        self._h = self._lib.tdcn_create(
            proc, nprocs, host_id.encode(), int(eager_limit),
            int(frag_size), int(ring_bytes), int(max_rndv))
        if not self._h:
            raise MPIInternalError("tdcn_create failed")
        self._running = True
        self._destroyed = False
        self.transport = _NativeTransportView(self)
        #: local-send payload table: handle → (payload, nbytes)
        self._handles: dict[int, object] = {}
        self._hnext = itertools.count(1)
        self._hlock = threading.Lock()
        #: cids whose p2p frames the C matcher owns (native pml comms)
        self._native_cids: set[str] = set()
        #: telemetry: the C engine's TdcnStats block, read via one
        #: ctypes call (ompi_tpu.metrics merges it into snapshots/pvars)
        self._stat_names = (
            self._lib.tdcn_stats_names().decode().split(","))
        self._stat_buf = (ctypes.c_uint64 * len(self._stat_names))()
        #: Python-plane robustness counters the C block cannot see
        #: (deadline escalations happen above the C boundary); merged
        #: over the C totals in stats_snapshot
        self._py_stats: dict[str, int] = {"deadline_expired": 0}
        # forward the unified ring deadline (dcn_ring_timeout) to the
        # C writer: a dead consumer's frozen tail must surface as a
        # send error, never an unbounded reserve() spin — and the
        # connect deadline (dcn_connect_timeout) to the C dialer, so
        # the redial+backoff round heals a restarting peer instead of
        # escalating a single failed connect() to MPIProcFailedError
        from ompi_tpu.core.var import dcn_timeout

        self._lib.tdcn_set_ring_timeout(self._h, float(dcn_timeout("ring")))
        self._lib.tdcn_set_connect_timeout(
            self._h, float(dcn_timeout("connect")))
        # streaming send engine knobs (TRANSPORT_VARS): pipelined chunk
        # granularity, the per-peer queued-bytes cap, and the doorbell
        # coalescing escape hatch — forwarded once; the C engine reads
        # them with relaxed atomics per send
        chunk, inflight, coalesce = transport_tuning()
        self._lib.tdcn_set_stream(self._h, chunk, inflight,
                                  1 if coalesce else 0)
        # the device-resident zero-copy plane (dcn/device.py): coll-
        # stream payloads arbitrate onto device windows exactly like
        # the Python engine's; the C p2p channel path keeps the host
        # plane (the streaming engine owns those lifetimes)
        from . import device as _device

        self._device_plane = _device.maybe_create(proc, nprocs)
        from ompi_tpu import metrics as _metrics

        _metrics.register_provider(self, self.stats_snapshot)
        # C-fast-path per-op timing rows → the straggler_<op> surfaces
        from ompi_tpu.metrics import straggler as _straggler

        _straggler.register_native(self, self.coll_optimes)
        # mesh doctor: arm/disarm the C blocked-wait registry to match
        # hang_diag_enable, and mirror it into blocked-state snapshots
        from ompi_tpu.trace import waitgraph as _waitgraph

        self._lib.tdcn_hang_diag(1 if _waitgraph._enabled else 0)
        _waitgraph.register_native(self, self.waitinfo)
        if _fsim._enabled:
            # arm the C fault hooks from the seeded plan: the ring
            # writer, the tcp-send connkill site, and the blocking-
            # receive delay site (native pml + C-ABI shim recv)
            stall_ns, every, fail_at = _fsim.native_ring_args()
            if stall_ns or fail_at >= 0:
                self._lib.tdcn_fault_set(stall_ns, every, fail_at)
            conn_at = _fsim.native_conn_args()
            if conn_at >= 0:
                self._lib.tdcn_fault_set_conn(conn_at)
            dup_at = _fsim.native_dup_args()
            if dup_at >= 0:
                self._lib.tdcn_fault_set_dup(dup_at)
            recv_ns, recv_every = _fsim.native_recv_args()
            if recv_ns:
                self._lib.tdcn_fault_set_recv(recv_ns, recv_every)
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="tdcn-dispatch")
        self._dispatcher.start()

    @staticmethod
    def _host_id() -> str:
        import os as _os
        import socket as _socket

        # test/dev override: distinct ids force the framed-TCP leg
        # (eager + RTS/CTS/FRAG rendezvous) between same-host peers —
        # the only way CI can exercise the cross-host path
        override = _os.environ.get("TDCN_HOST_ID")
        if override:
            return override
        hid = _socket.gethostname()
        try:
            with open("/proc/sys/kernel/random/boot_id") as f:
                hid += "/" + f.read().strip()
        except OSError:
            pass
        return hid

    # -- mixin hooks ----------------------------------------------------

    def _native_root(self) -> "NativeDcnEngine":
        return self

    def root_proc_of(self, local: int) -> int:
        return local if 0 <= local < self.nprocs else -1

    # -- C helpers ------------------------------------------------------

    @property
    def address(self) -> str:
        return self._lib.tdcn_address(self._h).decode()

    def set_addresses(self, addresses: Sequence[str]) -> None:
        if len(addresses) != self.nprocs:
            raise ValueError("address count != nprocs")
        from .collops import AddressTable

        if isinstance(addresses, AddressTable):
            # sharded native modex (PR 11's instant-on leg, now on the
            # C plane): install only the PRIMED slots eagerly (<= group
            # size), wrap the table's resolver so every lazy resolution
            # also lands in the C table (tdcn_set_address_one), and arm
            # the C-side resolver callback so a C-initiated send to an
            # unresolved peer pulls through the same table instead of
            # failing — np>=16 native boot does <= group-size eager
            # installs instead of P-1 (TS_ADDR_INSTALLS/TS_ADDR_LAZY
            # account it)
            inner = addresses._resolver

            def _resolve_install(p: int, _inner=inner) -> str:
                a = _inner(p)
                if a:
                    self._lib.tdcn_set_address_one(
                        self._h, int(p), str(a).encode(), 1)
                return a

            addresses._resolver = _resolve_install
            self.addresses = addresses
            joined = "\n".join(
                (list.__getitem__(addresses, i) or "")
                for i in range(self.nprocs))
            self._lib.tdcn_set_addresses(self._h, joined.encode())
            self._arm_resolver()
            return
        self.addresses = list(addresses)
        self._lib.tdcn_set_addresses(
            self._h, "\n".join(self.addresses).encode())

    def _arm_resolver(self) -> None:
        """C-side lazy-modex callback: writes the table-resolved
        address into the engine-provided buffer (NUL-terminated).  The
        CFUNCTYPE object is pinned on the engine — ctypes callbacks
        die with their last Python reference."""

        def _cb(proc: int, out, cap: int) -> int:
            try:
                a = self.addresses[int(proc)]  # resolves + installs
                b = str(a or "").encode()
                if not b or len(b) + 1 > int(cap):
                    return -1
                ctypes.memmove(out, b, len(b))
                out[len(b)] = b"\x00"
                return len(b)
            except Exception:  # noqa: BLE001 — C cannot unwind Python
                return -1

        self._resolver_cb = RESOLVER_FN(_cb)
        self._lib.tdcn_set_resolver(self._h, self._resolver_cb)

    def update_address(self, proc: int, address: str) -> None:
        """One-peer refresh (replace() installing a reborn endpoint):
        ``tdcn_set_address_one`` updates exactly that slot — the C
        engine prunes the corpse lineage's rx state and invalidates
        any C-coll views that resolved the dead address — without
        collapsing a sharded table's unresolved holes the way a
        full-table re-push would."""
        from .collops import AddressTable

        if isinstance(self.addresses, AddressTable):
            list.__setitem__(self.addresses, int(proc), address)
        else:
            self.addresses[int(proc)] = address
        self._lib.tdcn_set_address_one(
            self._h, int(proc), str(address).encode(), 0)

    def coll_revoke(self, cid) -> None:
        """ULFM revoke crossing into the C fast path: wake any parked
        ``cctx_recv_msg`` waits on this comm's private ``#cfp`` stream
        (they abort with the revoked code instead of waiting out the
        ~600 s give-up) and refuse new C schedules for it."""
        self._lib.tdcn_coll_revoke_cid(self._h, str(cid).encode())

    def _csend(self, address: str, kind: int, cid: str, seq: int,
               src: int, dst: int, tag: int, arr: np.ndarray,
               meta_b: bytes | None) -> int:
        shape = (ctypes.c_int64 * max(arr.ndim, 1))(*(arr.shape or (0,)))
        data = arr.ctypes.data_as(ctypes.c_void_p) if arr.nbytes else None
        return self._lib.tdcn_send_addr(
            self._h, address.encode(), kind, cid.encode(), seq, src, dst,
            tag, _dt_bytes(arr.dtype), arr.ndim, shape,
            meta_b, len(meta_b) if meta_b else 0, data, arr.nbytes)

    # -- channel fast path (per-(peer, cid), scalar-args-only sends) ----

    def chan_open(self, address: str, cid) -> int:
        chan = self._lib.tdcn_chan_open(
            self._h, address.encode(), str(cid).encode())
        if not chan:
            raise MPIInternalError(
                f"native dcn: cannot open channel to {address}")
        return chan

    def chan_close(self, chan: int) -> None:
        self._lib.tdcn_chan_close(self._h, chan)

    def chan_send(self, chan: int, kind: int, src: int, dst: int,
                  tag: int, arr: np.ndarray) -> None:
        if _fsim._enabled:
            # pml fast-path injection site (ROADMAP item c): the same
            # seeded "send" schedule the record path consumes; connkill
            # severs the channel's cached socket so the C redial round
            # is exercised from the fast path too
            for act in _fsim.actions("send",
                                     kinds={"drop", "delay", "connkill"}):
                if act.kind == "delay":
                    _fsim.apply_delay(act)
                elif act.kind == "drop":
                    return  # lost on the wire; the receiver's deadline
                    # escalation is the recovery path
                elif act.kind == "connkill":
                    self._lib.tdcn_chan_kill(self._h, chan)
        if _metrics._enabled:
            _metrics.observe_size("dcn_p2p_send", arr.nbytes)
            from ompi_tpu.metrics import flight as _flight

            _flight.check_watermarks()
        if arr.ndim == 1:
            rc = self._lib.tdcn_chan_send1(
                self._h, chan, kind, src, dst, tag, _dt_bytes(arr.dtype),
                arr.shape[0], arr.ctypes.data if arr.nbytes else None,
                arr.nbytes)
        else:
            rc = self._lib.tdcn_chan_send(
                self._h, chan, kind, src, dst, tag, _dt_bytes(arr.dtype),
                arr.ndim,
                arr.ctypes.shape_as(ctypes.c_int64) if arr.ndim else None,
                arr.ctypes.data if arr.nbytes else None, arr.nbytes)
        if rc != 0:
            raise ConnectionError(
                f"native dcn channel send failed (rc={rc})")

    def chan_isend(self, chan: int, kind: int, src: int, dst: int,
                   tag: int, arr: np.ndarray) -> None:
        """Detached (buffered) channel send — the streaming engine's
        isend fast path: larger-than-chunk payloads enqueue a send
        descriptor (the C engine owns a copy) and return immediately,
        so windowed bursts pipeline instead of serializing.  1-D
        contiguous payloads only (the MPI_Isend-dominant case); other
        shapes fall back to the blocking channel send."""
        if arr.ndim != 1:
            return self.chan_send(chan, kind, src, dst, tag, arr)
        if _fsim._enabled:
            # same seeded "send" schedule + connkill site as chan_send:
            # the pipelined path must not dodge the fault plane
            for act in _fsim.actions("send",
                                     kinds={"drop", "delay", "connkill"}):
                if act.kind == "delay":
                    _fsim.apply_delay(act)
                elif act.kind == "drop":
                    return
                elif act.kind == "connkill":
                    self._lib.tdcn_chan_kill(self._h, chan)
        if _metrics._enabled:
            _metrics.observe_size("dcn_p2p_send", arr.nbytes)
            from ompi_tpu.metrics import flight as _flight

            _flight.check_watermarks()
        rc = self._lib.tdcn_chan_isend1(
            self._h, chan, kind, src, dst, tag, _dt_bytes(arr.dtype),
            arr.shape[0], arr.ctypes.data if arr.nbytes else None,
            arr.nbytes, 1)  # buffered: numpy lifetimes can't be pinned
        if rc != 0:
            raise ConnectionError(
                f"native dcn channel isend failed (rc={rc})")

    # -- p2p registration (native vs Python delivery) -------------------

    def is_native_cid(self, cid) -> bool:
        return str(cid) in self._native_cids

    def register_native_p2p(self, cid) -> None:
        """Route this cid's p2p frames through the C matching engine
        (the fast path for comms with the default pml)."""
        self._native_cids.add(str(cid))

    def register_p2p(self, cid, fn: Callable) -> None:
        """Python delivery for this cid (OSC windows, monitored/
        logged pml): frames reach ``fn`` via the dispatcher thread."""
        with self._p2p_lock:
            self._p2p_handlers[cid] = fn
        self._lib.tdcn_register_pycid(self._h, str(cid).encode())

    def unregister_p2p(self, cid) -> None:
        with self._p2p_lock:
            self._p2p_handlers.pop(cid, None)
            self._p2p_closed.add(cid)
        self._native_cids.discard(str(cid))
        self._lib.tdcn_unregister_cid(self._h, str(cid).encode())

    # -- local (same-process) sends through the native matcher ----------

    def local_send(self, cid, src: int, dst: int, tag: int,
                   payload, count: int, nbytes: int) -> None:
        if (isinstance(payload, np.ndarray) and payload.ndim <= 8
                and not payload.dtype.hasobject):
            # bytes form: the C memcpy IS the buffered-eager copy, and
            # the message stays consumable by the shim's C fast path
            # (pyhandle messages can only be taken Python-side)
            arr = np.ascontiguousarray(payload)
            shape = (ctypes.c_int64 * max(arr.ndim, 1))(
                *(arr.shape or (0,)))
            rc = self._lib.tdcn_send_local_data(
                self._h, FK_P2P, str(cid).encode(), 0, src, dst, tag,
                _dt_bytes(arr.dtype), arr.ndim, shape,
                arr.ctypes.data if arr.nbytes else None, arr.nbytes)
        else:  # device arrays / objects: Python-side handle reference
            with self._hlock:
                h = next(self._hnext)
                self._handles[h] = payload
            rc = self._lib.tdcn_send_local(
                self._h, FK_P2P, str(cid).encode(), 0, src, dst, tag, h,
                count, nbytes)
            if rc != 0:
                with self._hlock:
                    self._handles.pop(h, None)
        if rc != 0:  # pragma: no cover — local enqueue cannot fail
            raise MPIInternalError("tdcn local send failed")

    def take_handle(self, h: int):
        with self._hlock:
            return self._handles.pop(h)

    # -- dispatcher (PY-kind frames → the Python frame router) ----------

    def _dispatch_loop(self) -> None:
        lib, h = self._lib, self._h
        msg = TdcnMsg()
        while self._running:
            rc = lib.tdcn_ctrl_next(h, 0.5, ctypes.byref(msg))
            if rc == _RC_CLOSED:
                return
            if rc != 0:
                continue
            env = _meta_of(lib, msg) or {}
            if msg.kind == FK_P2P and "kind" not in env:
                # raced: a native-matched cid was re-registered for
                # Python delivery; reconstruct the p2p envelope
                env = {"kind": "p2p", "cid": msg.cid.decode() or None,
                       "src": msg.src, "dst": msg.dst, "tag": msg.tag}
                try:
                    env["cid"] = int(env["cid"])
                except (TypeError, ValueError):
                    pass
            payload = (self.take_handle(msg.pyhandle) if msg.pyhandle
                       else _wrap_payload(lib, msg))
            try:
                self._on_frame(env, payload)
            except Exception as e:  # noqa: BLE001 — keep dispatching
                import sys

                print(f"[ompi_tpu tdcn] dispatcher error for {env}: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)

    # -- transport telemetry --------------------------------------------

    #: CK_* kind index → the straggler/pvar op name (shim CollKind)
    _COLL_KINDS = ("barrier", "bcast", "reduce", "allreduce",
                   "allgather")

    def coll_optimes(self) -> dict[str, dict] | None:
        """Per-op timing rows for the C collective fast path (PR 12's
        observability edge): {op: {count, wait_ns, max_wait_ns,
        lat_hist}} — merged by :mod:`ompi_tpu.metrics.straggler` into
        the ``straggler_<op>`` pvar/prom surfaces, which otherwise
        only see these collectives through the merged SPC counts."""
        if not self._running:
            return None
        buf = (ctypes.c_uint64 * 19)()
        out: dict[str, dict] = {}
        for kind, op in enumerate(self._COLL_KINDS):
            n = self._lib.tdcn_coll_optime(self._h, kind, buf, len(buf))
            if n < 3 or not buf[0]:
                continue
            out[op] = {
                "count": int(buf[0]),
                "wait_ns": int(buf[1]),
                "max_wait_ns": int(buf[2]),
                "lat_hist": [int(v) for v in buf[3:n]],
            }
        return out

    def stats_snapshot(self) -> dict[str, int] | None:
        """The C engine's telemetry block as {name: value} — relaxed
        snapshot (monotone per counter, not mutually consistent).
        Validates the layout version stamp; None once closed."""
        if not self._running:
            return None
        n = self._lib.tdcn_stats(self._h, self._stat_buf,
                                 len(self._stat_names))
        vals = list(self._stat_buf[:min(n, len(self._stat_names))])
        d = dict(zip(self._stat_names, vals))
        if d.pop("version", 0) != 1:
            return None  # layout drift: refuse to misattribute counters
        for k, v in self._py_stats.items():
            d[k] = d.get(k, 0) + v
        return d

    def waitinfo(self) -> list[dict]:
        """The C engine's registered blocked waits (tdcn_waitinfo),
        decoded into blocked-state snapshot rows — same relaxed-copy
        contract as stats_snapshot.  Empty when nothing is parked (the
        overwhelmingly common case: one ctypes call, no allocation
        C-side beyond the row scan)."""
        if not self._running:
            return []
        buf = ctypes.create_string_buffer(16384)
        n = self._lib.tdcn_waitinfo(self._h, buf, len(buf))
        if n <= 2:
            return []
        try:
            rows = json.loads(buf.value.decode("utf-8", "replace"))
        except ValueError:
            return []
        for r in rows:
            if r.get("peer", -1) is None or r.get("peer", -1) < 0:
                r["peer"] = None
            if not r.get("cid"):
                r["cid"] = None
        return rows

    # -- failure integration --------------------------------------------

    def note_proc_failed(self, proc: int) -> None:
        self._lib.tdcn_note_failed(self._h, proc)
        # the shared Python-side mark + device-window reclaim
        super().note_proc_failed(proc)

    def note_proc_recovered(self, proc: int,
                            incarnation: int | None = None) -> None:
        """replace(): a respawned incarnation re-published its endpoint
        — clear the C failure mark (blocked recvs naming it resume
        waiting instead of raising), then the shared Python-side
        recovery (detector clear + respawn accounting).  The rx dedup
        watermark deliberately SURVIVES the clear: a false-positive
        mark's sender is still the same lineage, and regressing its
        watermark would let a post-clear resend round re-deliver; the
        genuinely-dead corpse's state is pruned when set_addresses
        installs the reborn endpoint (address change = lineage proof)."""
        self._lib.tdcn_clear_failed(self._h, proc)
        super().note_proc_recovered(proc, incarnation)

    def note_proc_healed(self, proc: int) -> None:
        """False-positive heal (detector): same C-side clear, none of
        the respawn accounting."""
        self._lib.tdcn_clear_failed(self._h, proc)
        super().note_proc_healed(proc)

    def rx_watermark(self, proc: int) -> int:
        """Contiguous delivered-seq watermark for frames from ``proc``
        (max over its sender lineages; recovery observability + the
        watermark-continuity tests)."""
        return int(self._lib.tdcn_rx_watermark(self._h, int(proc)))

    def _bump_stat(self, name: str) -> None:
        self._py_stats[name] = self._py_stats.get(name, 0) + 1

    def close(self) -> None:
        if not self._running:
            return
        self._running = False
        if self._device_plane is not None:
            self._device_plane.close()
        self._lib.tdcn_close(self._h)
        if self._dispatcher.is_alive():
            self._dispatcher.join(timeout=2.0)

    def destroy(self) -> None:
        """FULL engine teardown (``tdcn_destroy``): close, then wait
        for the reader threads to drain and free every engine-owned
        allocation — the leak-free exit a resident worker's SIGTERM/
        orphan path takes so an operator ``kill`` never leaks shm
        rings or readers (the sanitizer soak's contract).  Terminal:
        the handle is gone afterwards; only call on the way out of the
        process."""
        if self._destroyed:
            return
        self.close()
        self._destroyed = True
        self._lib.tdcn_destroy(self._h)


class NativeSubEngine(_NativeOpsMixin, DcnSubEngine):
    """Cross-process split view over a native engine (index remap only;
    byte plane shared with the root)."""

    def __init__(self, parent, procs: Sequence[int]):
        DcnSubEngine.__init__(self, parent, procs)

    def _native_root(self) -> NativeDcnEngine:
        return self.parent._native_root()

    def root_proc_of(self, local: int) -> int:
        return self.parent.root_proc_of(self.procs[local])

    def is_native_cid(self, cid) -> bool:
        return self._native_root().is_native_cid(cid)

    def register_native_p2p(self, cid) -> None:
        self._native_root().register_native_p2p(cid)

    def local_send(self, *a, **k) -> None:
        self._native_root().local_send(*a, **k)

    def take_handle(self, h: int):
        return self._native_root().take_handle(h)

    @property
    def _lib(self):
        return self._native_root()._lib

    @property
    def _h(self):
        return self._native_root()._h


class NativeJoinEngine(_NativeOpsMixin, DcnJoinEngine):
    """Spawn/join view across worlds over the native byte plane."""

    def __init__(self, local, addresses: Sequence[str], proc: int):
        DcnJoinEngine.__init__(self, local, addresses, proc)

    def _native_root(self) -> NativeDcnEngine:
        return self.parent._native_root()

    def root_proc_of(self, local: int) -> int:
        return -1  # FT does not span spawn worlds

    def is_native_cid(self, cid) -> bool:
        return self._native_root().is_native_cid(cid)

    def register_native_p2p(self, cid) -> None:
        self._native_root().register_native_p2p(cid)

    def local_send(self, *a, **k) -> None:
        self._native_root().local_send(*a, **k)

    def take_handle(self, h: int):
        return self._native_root().take_handle(h)
