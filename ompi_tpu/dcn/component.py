"""``btl/tcp`` MCA component — the DCN transport's tunables.

≈ ``opal/mca/btl/tcp``'s component registration (SURVEY.md §2.3: the
btl framework row — "the slot where a DCN transport goes in the
rebuild").  The transport itself is :mod:`ompi_tpu.dcn.tcp`; this
component owns its MCA variables, mirroring the reference's
``btl_tcp_eager_limit`` / ``btl_tcp_max_send_size`` knob family and
the pml-level eager↔rendezvous switch (SURVEY.md §2.2 pml ob1).

Plane arbitration note: whichever host btl this framework selects,
the engines layer the **device-resident zero-copy plane**
(:mod:`ompi_tpu.dcn.device`) above it — the rendezvous protocol picks
the plane per message from (``dcn_device_min_size``, dtype
contiguity, host reachability), mirroring the reference's btl
priority/reachability selection across sm/tcp/ofi.  The device plane
is an overlay, not a btl of its own: it is never selected by
``--mca btl`` (its descriptor control frames always ride the selected
host transport), and its knobs live in the central ``DEVICE_VARS``
table (``core/var.py``) because both the Python and native engines
consume them.

Plane *health* note (the failover half of btl selection): the
reference excludes a failing btl component and re-routes traffic to
the next capable one; here the device plane carries a per-(peer,
plane) health table (:class:`ompi_tpu.dcn.device.PlaneHealth`) —
``dcn_plane_strikes`` consecutive failures demote a peer's traffic
back onto the selected host btl mid-job, and a heal probe after
``dcn_plane_heal_interval`` seconds re-promotes a recovered plane.
Because a demoted stage never ships a descriptor, the payload rides
the host btl's ordinary per-peer sequence space and the dedup
watermark keeps delivery exactly-once across the demotion boundary.
The ``dcn_plane_*`` knobs live in the central ``ROBUSTNESS_VARS``
table next to the deadline family they extend.
"""

from __future__ import annotations

from ompi_tpu.core.registry import Component, register_component


@register_component
class DcnTcpComponent(Component):
    FRAMEWORK = "btl"
    NAME = "tcp"
    PRIORITY = 50

    def register_params(self, store) -> None:
        super().register_params(store)
        store.register(
            "btl", "tcp", "eager_limit", 4 << 20, type="int",
            help="Largest payload (bytes) sent as a single EAGER frame; "
            "larger transfers use the RTS/CTS rendezvous protocol "
            "(≈ btl_tcp_eager_limit + ob1's rendezvous switch)",
        )
        store.register(
            "btl", "tcp", "frag_size", 8 << 20, type="int",
            help="Fragment size (bytes) for rendezvous streaming "
            "(≈ btl_tcp_max_send_size)",
        )
        store.register(
            "btl", "tcp", "max_rndv", 4, type="int",
            help="Max concurrent inbound rendezvous transfers a process "
            "grants CTS for (flow control on DCN ingress memory)",
        )
        store.register(
            "btl", "tcp", "ring_threshold", 64 << 10, type="int",
            help="Payload size (bytes) at which DCN allreduce switches "
            "from the ordered gather-to-root fold to the bandwidth-"
            "optimal ring reduce-scatter + allgather schedule "
            "(commutative ops only; ordered fold is kept for "
            "non-commutative/reproducible reductions)",
        )

    def params(self, store) -> dict:
        """Final knob dict for engine construction.  Subclasses extend
        :meth:`_collect_params`, NOT this method: the trace marker must
        fire once with the COMPLETE dict (shm_threshold/ring_bytes
        included), so it lives here at the outermost call."""
        p = self._collect_params(store)
        from ompi_tpu.trace import core as _tr

        if _tr._enabled:
            _tr.instant("dcn", "transport_params",
                        **dict(p, transport=self.NAME))
        return p

    def _collect_params(self, store) -> dict:
        self.register_params(store)
        return {
            "eager_limit": store.get("btl_tcp_eager_limit"),
            "frag_size": store.get("btl_tcp_frag_size"),
            "max_rndv": store.get("btl_tcp_max_rndv"),
            "ring_threshold": store.get("btl_tcp_ring_threshold"),
        }


@register_component
class DcnShmComponent(DcnTcpComponent):
    """``btl/sm`` — same-host shared-memory transport (single-copy bulk
    payloads over /dev/shm, abstract unix sockets for framing).

    Priority below tcp: the modex address only resolves on one host, so
    the reference's reachability logic collapses to explicit selection
    (``--mca btl sm``) until the multi-host launch leg exists.
    Inherits the tcp knob family; adds the copy-in threshold.
    """

    NAME = "sm"
    PRIORITY = 40

    def register_params(self, store) -> None:
        super().register_params(store)
        store.register(
            "btl", "sm", "shm_threshold", 2 << 20, type="int",
            help="Smallest payload (bytes) moved through the shared-"
            "memory ring instead of inline on the unix socket (measured "
            "crossover: kernel socket copies win below ~2 MiB)",
        )

    def _collect_params(self, store) -> dict:
        p = super()._collect_params(store)
        p["transport"] = "sm"
        p["shm_threshold"] = store.get("btl_sm_shm_threshold")
        return p


@register_component
class DcnNativeComponent(DcnTcpComponent):
    """``btl/native`` — the C++ host data plane (``libtpudcn.so``):
    native framing, shared-memory rings + TCP per peer (the bml role),
    and the C matching engine under blocked receives.  Highest
    priority: selected by default when the library builds; ``--mca btl
    tcp|sm|bml`` still forces a Python transport (the compat plane the
    interposed pmls use anyway).  SURVEY.md §2 native-path rule."""

    NAME = "native"
    PRIORITY = 60

    def register_params(self, store) -> None:
        super().register_params(store)
        store.register(
            "btl", "native", "ring_bytes", 64 << 20, type="int",
            help="Per-peer-direction shared-memory ring capacity "
            "(bytes); payloads beyond half of this stream as chunked "
            "records through the ring",
        )

    def _collect_params(self, store) -> dict:
        p = super()._collect_params(store)
        p["transport"] = "native"
        p["ring_bytes"] = store.get("btl_native_ring_bytes")
        return p


@register_component
class DcnBmlComponent(DcnShmComponent):
    """``btl/bml`` — the r2-style per-peer multiplexer: shared-memory
    rings for same-host peers, TCP for cross-host, chosen per SEND by
    the peer's advertised host identity (SURVEY.md §2.3 bml row).
    Select with ``--mca btl bml``; the default stays single-transport
    until mixed-host jobs are routinely launched (the rsh leg)."""

    NAME = "bml"
    PRIORITY = 45

    def _collect_params(self, store) -> dict:
        p = super()._collect_params(store)
        p["transport"] = "bml"
        return p
