"""DCN layer: inter-process/inter-slice transport + collectives
(≈ opal/mca/btl/tcp + the host side of coll/han, SURVEY.md §2.7)."""

from .collops import DcnCollEngine  # noqa: F401
from .tcp import TcpTransport  # noqa: F401
