"""Shared repo-scanning helpers for the tpucheck passes.

Everything here is **static**: the passes parse the repo's sources
(AST for Python, regex for C) and never import the modules under
analysis — a check must not depend on jax/toolchain availability, must
run against any tree state (including the seeded fixture trees in
``--selftest``), and must not execute the code it is judging.
"""

from __future__ import annotations

import ast
import re
from functools import lru_cache
from pathlib import Path

#: directories the file walk never descends into (hygiene: the linter
#: must not trip over bytecode caches or sanitizer build trees)
EXCLUDE_DIRS = frozenset({
    "__pycache__", ".git", ".pytest_cache", "build", "build-asan",
    "build-tsan", "node_modules", ".claude",
})


def walk(root: Path, suffixes: tuple[str, ...],
         subdirs: tuple[str, ...] = ()) -> list[Path]:
    """All files under ``root`` (or ``root/<subdir>``s) with one of the
    suffixes, sorted, skipping :data:`EXCLUDE_DIRS` at any depth."""
    roots = [root / s for s in subdirs] if subdirs else [root]
    out: list[Path] = []
    for r in roots:
        if not r.exists():
            continue
        if r.is_file():
            out.append(r)
            continue
        for p in sorted(r.rglob("*")):
            if not p.is_file() or p.suffix not in suffixes:
                continue
            if any(part in EXCLUDE_DIRS for part in p.relative_to(root).parts):
                continue
            out.append(p)
    return out


def rel(root: Path, path: Path) -> str:
    try:
        return str(path.relative_to(root))
    except ValueError:
        return str(path)


@lru_cache(maxsize=512)
def _parse_cached(path: str, mtime_ns: int) -> ast.Module | None:
    try:
        return ast.parse(Path(path).read_text(), filename=path)
    except SyntaxError:
        return None


def parse_py(path: Path) -> ast.Module | None:
    """Parse a Python file (cached on mtime); None on syntax error —
    callers surface that as a finding, not an exception."""
    try:
        mtime = path.stat().st_mtime_ns
    except OSError:
        return None
    return _parse_cached(str(path), mtime)


def const_str(node: ast.AST) -> str | None:
    return node.value if (isinstance(node, ast.Constant)
                          and isinstance(node.value, str)) else None


def full_var_name(fw: str, comp: str, name: str) -> str:
    return "_".join(p for p in (fw, comp, name) if p)


# -- registered MCA variable names, statically ---------------------------

#: the central registration tables in core/var.py the contracts name
CENTRAL_TABLES = ("OBSERVABILITY_VARS", "ROBUSTNESS_VARS", "SERVING_VARS",
                  "TRANSPORT_VARS", "SCHEDULE_VARS", "DEVICE_VARS")


def central_var_tables(root: Path) -> dict[str, list[str]]:
    """Parse core/var.py for the central tables → {table: [full_name]}."""
    out: dict[str, list[str]] = {t: [] for t in CENTRAL_TABLES}
    var_py = root / "ompi_tpu" / "core" / "var.py"
    tree = parse_py(var_py)
    if tree is None:
        return out
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Name) and tgt.id in CENTRAL_TABLES):
            continue
        if not isinstance(node.value, ast.Tuple):
            continue
        for row in node.value.elts:
            if isinstance(row, ast.Tuple) and len(row.elts) >= 3:
                fw = const_str(row.elts[0])
                comp = const_str(row.elts[1])
                name = const_str(row.elts[2])
                if fw is not None and comp is not None and name is not None:
                    out[tgt.id].append(full_var_name(fw, comp, name))
    return out


def registered_var_names(root: Path) -> set[str]:
    """Every MCA var full name the tree can register, statically:

    * the three central tables in ``core/var.py``;
    * literal ``store.register(fw, comp, name, …)`` calls anywhere
      (component/lazy registrations);
    * the structural vars the registry derives: ``<fw>_<comp>_priority``
      per Component subclass, the framework selection var ``<fw>``, and
      ``<fw>_base_verbose`` per framework;
    * the per-timeout family ``dcn_<name>_timeout`` is covered by the
      central table rows themselves.
    """
    names: set[str] = set()
    for rows in central_var_tables(root).values():
        names.update(rows)
    frameworks: set[str] = set()
    for path in walk(root, (".py",), subdirs=("ompi_tpu",)):
        tree = parse_py(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                fn = node.func
                if (isinstance(fn, ast.Attribute) and fn.attr == "register"
                        and len(node.args) >= 3):
                    fw = const_str(node.args[0])
                    comp = const_str(node.args[1])
                    vname = const_str(node.args[2])
                    if fw is not None and comp is not None and vname is not None:
                        names.add(full_var_name(fw, comp, vname))
                        frameworks.add(fw)
            elif isinstance(node, ast.ClassDef):
                fw = comp = None
                for stmt in node.body:
                    if (isinstance(stmt, ast.Assign)
                            and len(stmt.targets) == 1
                            and isinstance(stmt.targets[0], ast.Name)):
                        if stmt.targets[0].id == "FRAMEWORK":
                            fw = const_str(stmt.value)
                        elif stmt.targets[0].id == "NAME":
                            comp = const_str(stmt.value)
                if fw and comp:
                    names.add(full_var_name(fw, comp, "priority"))
                    frameworks.add(fw)
    for fw in frameworks:
        if fw:
            names.add(fw)                      # framework selection var
            names.add(f"{fw}_base_verbose")    # auto verbose-stream var
    # output.register_verbose_var(store, framework) literal call sites
    for path in walk(root, (".py",), subdirs=("ompi_tpu",)):
        tree = parse_py(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, (ast.Attribute, ast.Name))):
                attr = (node.func.attr if isinstance(node.func, ast.Attribute)
                        else node.func.id)
                if attr == "register_verbose_var" and len(node.args) >= 2:
                    fw = const_str(node.args[1])
                    if fw:
                        names.add(f"{fw}_base_verbose")
    return names


#: ``--mca <name>`` references in shell-ish text/argv lists, and the
#: env-var spelling.  The two argv forms: ``--mca name value`` in prose/
#: shell, and ``"--mca", "name"`` in Python lists.
_MCA_REF_RES = (
    re.compile(r"--mca[\s=]+([a-z][a-z0-9_]*)"),
    re.compile(r"""--mca['"]\s*,\s*['"]([a-z][a-z0-9_]*)"""),
    re.compile(r"OMPI(?:_TPU)?_MCA_([A-Za-z][A-Za-z0-9_]*)"),
)


def mca_references(text: str) -> list[tuple[str, int]]:
    """(var_name, 1-based line) for every ``--mca``/``OMPI_MCA_`` style
    reference in a text blob."""
    out: list[tuple[str, int]] = []
    for lineno, line in enumerate(text.splitlines(), 1):
        for rx in _MCA_REF_RES:
            for m in rx.finditer(line):
                out.append((m.group(1), lineno))
    return out
