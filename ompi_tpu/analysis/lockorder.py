"""Pass 2 — static lock-order / deadlock analysis over the threaded planes.

Builds a static lock-acquisition graph (lockdep/witness style, but at
analysis time): nodes are lock *sites* (``Class.attr`` for instance
locks, ``module.name`` for module-level locks — the moral equivalent
of lockdep's lock classes), and an edge A→B means "somewhere, B is
acquired while A is held", either directly in one function body or
through a (transitive, statically resolved) call made under A.

Findings:

``lock-cycle``
    A cycle in the acquisition graph — two threads taking the locks
    in opposite orders can deadlock.  Reported once per strongly-
    connected component, with example edges and sites.

``lock-self-cycle``
    A non-reentrant ``threading.Lock`` acquired while already held
    (directly or via a call chain) — self-deadlock on one thread.

``lock-held-blocking``
    A known-blocking call (socket accept/connect/recv, unbounded
    ``Event.wait``/``join``, ``time.sleep``, subprocess) made while a
    lock is held — the PR 3/PR 6 wedge class where one stalled peer
    freezes every thread that touches the lock.  ``Condition.wait`` on
    the *held* condition is exempt (wait releases it); bounded waits
    (an explicit timeout argument) are exempt — they stall, but they
    cannot wedge.

The companion **runtime** witness mode lives in :mod:`.lockdep`
(opt-in, used by tests): it records the observed acquisition order of
real lock instances and turns an order inversion into a test failure.

Static resolution is deliberately name-based and conservative: an
expression resolves to a lock node only when the attribute name is
unambiguous (declared by exactly one analyzed class, or by the
enclosing class).  Unresolvable expressions are skipped — this pass
prefers missed edges over phantom cycles.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from ompi_tpu.analysis.findings import SEV_ERROR, Finding
from ompi_tpu.analysis.repo import parse_py, rel, walk

PASS = "lockorder"

#: the threaded modules the tentpole names (engine, telemetry
#: publisher, detector, tpud workers) + the lock-heavy support planes
DEFAULT_SCOPE = (
    "ompi_tpu/dcn/tcp.py",
    "ompi_tpu/dcn/collops.py",
    "ompi_tpu/dcn/native.py",
    "ompi_tpu/metrics/live.py",
    "ompi_tpu/serve/daemon.py",
    "ompi_tpu/serve/worker.py",
    "ompi_tpu/serve/queue.py",
    "ompi_tpu/serve/agent.py",
    "ompi_tpu/ft/detector.py",
)

_LOCK_FACTORIES = {"Lock": "lock", "RLock": "rlock",
                   "Condition": "condition", "Semaphore": "semaphore",
                   "BoundedSemaphore": "semaphore"}

#: method names that block unboundedly when called without a timeout
_BLOCKING_BARE = {"accept", "connect", "recv", "recv_into", "recvfrom",
                  "sendall", "select", "communicate", "run",
                  "check_output", "_recv_full", "_recv_exact",
                  "recv_exact", "sleep"}
#: blocking only when called with NO timeout argument at all
_BLOCKING_IF_UNBOUNDED = {"wait", "join", "result"}


@dataclass
class LockDef:
    lock_id: str    # "Class.attr" | "<module-stem>.name"
    kind: str       # lock | rlock | condition | semaphore
    file: str
    line: int


@dataclass
class _FuncInfo:
    qualname: str
    file: str
    cls: str | None
    acquires: set[str] = field(default_factory=set)
    #: (held_tuple, new_lock, line) direct nesting events
    nest_events: list = field(default_factory=list)
    #: (held_tuple, callee_key, line)
    calls_under: list = field(default_factory=list)
    #: (held_tuple, call_desc, line) direct blocking calls under a lock
    blocking_under: list = field(default_factory=list)
    #: callee keys (for closure computation), held or not
    callees: set = field(default_factory=set)


def _lock_factory_kind(call: ast.Call) -> str | None:
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    return _LOCK_FACTORIES.get(name or "")


class _ModuleScan:
    """Collect lock definitions + function bodies for one file."""

    def __init__(self, root: Path, path: Path):
        self.root = root
        self.path = path
        self.relpath = rel(root, path)
        self.stem = path.stem
        self.locks: dict[str, LockDef] = {}
        self.functions: dict[str, tuple[ast.AST, str | None]] = {}
        tree = parse_py(path)
        if tree is None:
            return
        self._collect(tree)

    def _collect(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                kind = _lock_factory_kind(node.value)
                if kind and len(node.targets) == 1 and isinstance(
                        node.targets[0], ast.Name):
                    lid = f"{self.stem}.{node.targets[0].id}"
                    self.locks[lid] = LockDef(lid, kind, self.relpath,
                                              node.lineno)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = (node, None)
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self.functions[f"{node.name}.{sub.name}"] = (
                            sub, node.name)
                        for stmt in ast.walk(sub):
                            if (isinstance(stmt, ast.Assign)
                                    and isinstance(stmt.value, ast.Call)):
                                kind = _lock_factory_kind(stmt.value)
                                tgt = stmt.targets[0] if len(
                                    stmt.targets) == 1 else None
                                if (kind and isinstance(tgt, ast.Attribute)
                                        and isinstance(tgt.value, ast.Name)
                                        and tgt.value.id == "self"):
                                    lid = f"{node.name}.{tgt.attr}"
                                    self.locks[lid] = LockDef(
                                        lid, kind, self.relpath, stmt.lineno)


class Analyzer:
    def __init__(self, root: Path, scope: tuple[str, ...] = DEFAULT_SCOPE,
                 files: list[Path] | None = None):
        self.root = Path(root)
        if files is None:
            files = [self.root / s for s in scope
                     if (self.root / s).exists()]
        self.scans = [_ModuleScan(self.root, p) for p in files]
        self.locks: dict[str, LockDef] = {}
        self.attr_index: dict[str, list[str]] = {}
        for sc in self.scans:
            for lid, d in sc.locks.items():
                self.locks[lid] = d
                self.attr_index.setdefault(lid.rsplit(".", 1)[1],
                                           []).append(lid)
        self.funcs: dict[str, _FuncInfo] = {}
        for sc in self.scans:
            for qual, (node, cls) in sc.functions.items():
                key = f"{sc.stem}:{qual}"
                info = _FuncInfo(qual, sc.relpath, cls)
                self.funcs[key] = info
                self._walk_function(sc, node, info)

    # -- lock expression resolution ------------------------------------

    def _resolve(self, expr: ast.AST, cls: str | None) -> str | None:
        if isinstance(expr, ast.Name):
            cands = [lid for lid in self.attr_index.get(expr.id, ())
                     if lid in self.locks
                     and "." in lid]  # module-level locks keyed stem.name
            return cands[0] if len(cands) == 1 else None
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                if cls and f"{cls}.{attr}" in self.locks:
                    return f"{cls}.{attr}"
            cands = self.attr_index.get(attr, [])
            if len(cands) == 1:
                return cands[0]
        return None

    # -- ordered traversal with a held-lock stack ----------------------

    def _walk_function(self, sc: _ModuleScan, fn: ast.AST,
                       info: _FuncInfo) -> None:
        held: list[str] = []

        def visit_call(call: ast.Call) -> None:
            f = call.func
            # acquire()/release() on a resolvable lock expr
            if isinstance(f, ast.Attribute) and f.attr in ("acquire",
                                                           "release"):
                lid = self._resolve(f.value, info.cls)
                if lid is not None:
                    if f.attr == "acquire":
                        if held:
                            info.nest_events.append(
                                (tuple(held), lid, call.lineno))
                        info.acquires.add(lid)
                        held.append(lid)
                    elif lid in held:
                        held.remove(lid)
                    return
            # blocking-call detection
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            has_timeout = bool(call.args) or any(
                kw.arg in ("timeout", "timeout_s") for kw in call.keywords)
            # sendall/connect/... take args; "has args" ≠ bounded there,
            # so _BLOCKING_BARE names block regardless of has_timeout
            blocking = (name in _BLOCKING_BARE
                        or (name in _BLOCKING_IF_UNBOUNDED
                            and not has_timeout))
            if blocking and held:
                if name == "wait" and isinstance(f, ast.Attribute):
                    cond = self._resolve(f.value, info.cls)
                    if cond is not None and cond in held:
                        blocking = False  # Condition.wait releases it
                if blocking:
                    info.blocking_under.append(
                        (tuple(held), ast.unparse(call.func),
                         call.lineno))
            # call-graph edge for interprocedural propagation
            callee = self._callee_key(sc, f, info.cls)
            if callee is not None:
                info.callees.add(callee)
                if held:
                    info.calls_under.append(
                        (tuple(held), callee, call.lineno))

        def visit(node: ast.AST) -> None:
            if isinstance(node, ast.With):
                ids: list[str] = []
                for item in node.items:
                    expr = item.context_expr
                    for c in ast.walk(expr):
                        if isinstance(c, ast.Call):
                            visit_call(c)
                    lid = self._resolve(expr, info.cls)
                    if lid is not None:
                        if held:
                            info.nest_events.append(
                                (tuple(held), lid, node.lineno))
                        info.acquires.add(lid)
                        held.append(lid)
                        ids.append(lid)
                for stmt in node.body:
                    visit(stmt)
                for lid in reversed(ids):
                    if lid in held:
                        held.remove(lid)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return  # nested defs run on their own schedule
            if isinstance(node, ast.If):
                # branches are exclusive: walk each from the pre-branch
                # held state, then continue with the locks BOTH arms
                # agree on (common prefix) — an acquire in one arm must
                # not leak into its sibling (phantom self-cycles)
                for c in ast.walk(node.test):
                    if isinstance(c, ast.Call):
                        visit_call(c)
                base = list(held)
                for stmt in node.body:
                    visit(stmt)
                after_body = list(held)
                held[:] = base
                for stmt in node.orelse:
                    visit(stmt)
                merged: list[str] = []
                for a, b in zip(after_body, held):
                    if a != b:
                        break
                    merged.append(a)
                held[:] = merged
                return
            if isinstance(node, ast.Call):
                visit_call(node)
                for child in ast.iter_child_nodes(node):
                    visit(child)
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in getattr(fn, "body", []):
            visit(stmt)

    def _callee_key(self, sc: _ModuleScan, f: ast.AST,
                    cls: str | None) -> str | None:
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id == "self" and cls:
                key = f"{sc.stem}:{cls}.{f.attr}"
                if key in self.funcs or f"{cls}.{f.attr}" in sc.functions:
                    return f"{sc.stem}:{cls}.{f.attr}"
            return None
        if isinstance(f, ast.Name):
            if f.id in sc.functions:
                return f"{sc.stem}:{f.id}"
        return None

    # -- graph construction + findings ---------------------------------

    def build(self):
        """Returns (edges, blocking) where edges is
        {(A, B): (file, line, via)} and blocking is a list of
        (held, call, file, line, via)."""
        # transitive acquire closure per function
        closure: dict[str, set[str]] = {
            k: set(v.acquires) for k, v in self.funcs.items()}
        block_closure: dict[str, list] = {
            k: [(b[1], b[2], "") for b in v.blocking_under]
            for k, v in self.funcs.items()}
        changed = True
        rounds = 0
        while changed and rounds < 20:
            changed = False
            rounds += 1
            for k, v in self.funcs.items():
                for callee in v.callees:
                    extra = closure.get(callee, set()) - closure[k]
                    if extra:
                        closure[k] |= extra
                        changed = True
        edges: dict[tuple[str, str], tuple[str, int, str]] = {}
        blocking: list = []
        for k, v in self.funcs.items():
            for held, lid, line in v.nest_events:
                for h in held:
                    # h == lid is a self-edge; Tarjan reports it as a
                    # lock-self-cycle like any other cycle
                    edges.setdefault((h, lid), (v.file, line, v.qualname))
            for held, callee, line in v.calls_under:
                for m in closure.get(callee, ()):  # locks taken downstream
                    for h in held:
                        via = f"{v.qualname} → {callee.split(':', 1)[1]}"
                        edges.setdefault((h, m), (v.file, line, via))
            for held, call, line in v.blocking_under:
                blocking.append((held, call, v.file, line, v.qualname))
            # blocking through one call level
            for held, callee, line in v.calls_under:
                if callee not in self.funcs:
                    continue
                for bcall, bline, _ in block_closure.get(callee, []):
                    blocking.append(
                        (held, f"{callee.split(':', 1)[1]} → {bcall}",
                         v.file, line, v.qualname))
        return edges, blocking


def _sccs(nodes: set[str], edges: dict) -> list[list[str]]:
    """Tarjan strongly-connected components."""
    adj: dict[str, list[str]] = {n: [] for n in nodes}
    for (a, b) in edges:
        if a in adj and b in nodes and a != b:
            adj[a].append(b)
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]

    def strong(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in adj[v]:
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            out.append(comp)

    import sys
    old = sys.getrecursionlimit()
    sys.setrecursionlimit(max(old, 10000))
    try:
        for n in sorted(nodes):
            if n not in index:
                strong(n)
    finally:
        sys.setrecursionlimit(old)
    return out


def run(root: str | Path, files: list[Path] | None = None,
        scope: tuple[str, ...] = DEFAULT_SCOPE) -> list[Finding]:
    root = Path(root)
    an = Analyzer(root, scope=scope, files=files)
    edges, blocking = an.build()
    out: list[Finding] = []
    # cycles
    nodes = set(an.locks)
    for comp in _sccs(nodes, edges):
        if len(comp) < 2:
            continue
        comp_set = set(comp)
        examples = [f"{a} → {b} ({f}:{ln} in {via})"
                    for (a, b), (f, ln, via) in sorted(edges.items())
                    if a in comp_set and b in comp_set][:4]
        f0, l0 = "", 0
        for (a, b), (f, ln, via) in sorted(edges.items()):
            if a in comp_set and b in comp_set:
                f0, l0 = f, ln
                break
        out.append(Finding(
            PASS, "lock-cycle", f0, l0, " ⇄ ".join(sorted(comp)),
            "lock-order cycle: " + "; ".join(examples)
            + " — opposite-order acquisition can deadlock",
            SEV_ERROR))
    # self-cycles on non-reentrant locks
    for (a, b), (f, ln, via) in sorted(edges.items()):
        if a == b and an.locks.get(a) and an.locks[a].kind == "lock":
            out.append(Finding(
                PASS, "lock-self-cycle", f, ln, via,
                f"non-reentrant Lock {a} (re)acquired while already "
                "held — single-thread self-deadlock",
                SEV_ERROR))
    # blocking under lock
    seen: set[tuple] = set()
    for held, call, f, ln, via in blocking:
        key = (tuple(held), call.split(" → ")[-1], f, via)
        if key in seen:
            continue
        seen.add(key)
        out.append(Finding(
            PASS, "lock-held-blocking", f, ln, via,
            f"blocking call {call} while holding {', '.join(held)} — "
            "a stalled peer freezes every thread contending this lock",
            SEV_ERROR))
    return out
