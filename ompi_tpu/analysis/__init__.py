"""tpucheck — repo-native static analysis (the machine-checked contracts).

PRs 1–6 established cross-cutting invariants that, until this
subsystem, lived only in reviewer memory:

* every blocking DCN wait converges on :class:`ompi_tpu.core.var.
  Deadline` (no hard-coded timeouts, no unbounded spin loops);
* every ``--mca`` knob referenced anywhere (code, tests, docs) is
  centrally registered, and every central registration is alive;
* observability/robustness hooks are one-boolean off-path;
* transport escalation raises the typed ULFM errors, never a bare
  ``RuntimeError`` (and never hangs);
* the ``TdcnStats``/``NATIVE_COUNTERS`` schema and the ``tdcn_*``
  ctypes surface stay append-only/in-sync across the C ABI.

Four passes enforce them (in the spirit of MPI correctness tools like
MUST, and of TSan/lockdep-style order checking):

==========  ===========================================================
pass        checks
==========  ===========================================================
invariants  AST linter over ``ompi_tpu/``: Deadline discipline,
            MCA-var registration drift, hook gating, typed escalation
lockorder   static lock-acquisition graph across the threaded planes:
            cycles + lock-held-across-blocking-call sites; plus the
            opt-in runtime witness mode (:mod:`.lockdep`)
abidrift    C↔Python ABI: ``TDCN_STAT_NAMES`` vs ``NATIVE_COUNTERS``
            (names/order/append-only), exported ``tdcn_*`` symbols vs
            the ctypes declarations, README knob/endpoint catalogs vs
            the registered var/route sets
sanitize    native plane built under ASan/UBSan (TSan where the
            toolchain allows) and soaked via the Python-free
            ``native/src/dcn_sanity.cc`` harness, plus cppcheck when
            installed; skips log a reason, never silently pass
==========  ===========================================================

Driver: ``tools/check.py`` (``--selftest`` joins tier-1).  Intentional
exceptions live in the reviewed waiver file ``waivers.toml`` next to
this package — every waiver carries a one-line justification, so the
repo-wide contract is "zero unexplained findings".
"""

from __future__ import annotations

from ompi_tpu.analysis.findings import (  # noqa: F401
    Finding,
    Report,
    Waiver,
    apply_waivers,
    load_waivers,
)

#: pass name → callable(root: Path) -> list[Finding]; importers pull the
#: pass modules lazily so ``import ompi_tpu.analysis`` stays light
PASS_NAMES = ("invariants", "lockorder", "abidrift", "sanitize")


def run_pass(name: str, root, **kw):
    """Run one named pass against a repo root; returns list[Finding]."""
    if name == "invariants":
        from ompi_tpu.analysis import invariants

        return invariants.run(root, **kw)
    if name == "lockorder":
        from ompi_tpu.analysis import lockorder

        return lockorder.run(root, **kw)
    if name == "abidrift":
        from ompi_tpu.analysis import abidrift

        return abidrift.run(root, **kw)
    if name == "sanitize":
        from ompi_tpu.analysis import sanitize

        return sanitize.run(root, **kw)
    raise KeyError(f"unknown analysis pass {name!r}")
