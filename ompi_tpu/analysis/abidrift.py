"""Pass 3 — C↔Python ABI drift checking.

The native plane and the Python control plane share three contracts
that drift silently because no compiler sees both sides:

``stat-names-drift`` / ``stat-append-only``
    ``native/src/dcn.cc:TDCN_STAT_NAMES`` (the self-describing counter
    name table the C block exports) must equal ``"version"`` + the
    Python schema ``ompi_tpu/metrics/core.py:NATIVE_COUNTERS`` — same
    names, same order.  The v1 prefix (everything PR 2 shipped) is
    FROZEN: those names are live MPI_T pvar names and cached pvar
    indices; new counters append at the tail only, and the C version
    slot stays 1 while the schema is append-only.

``abi-missing-symbol`` / ``abi-arity`` / ``abi-type``
    Every ``lib.tdcn_*`` ctypes signature declared in
    ``ompi_tpu/dcn/native.py`` must match the ``extern "C"``
    definition in ``dcn.cc``: the symbol exists, the parameter count
    agrees, and each parameter/return slot agrees at machine-width
    granularity (ptr / int32 / int64 / uint64 / double).  A silent
    int-vs-int64 mismatch truncates on the call boundary — the
    classic ctypes failure mode.

``abi-undeclared-call``
    A ``tdcn_*`` symbol referenced from Python with NO ``argtypes``
    declaration — ctypes falls back to int-width guessing, which
    breaks on 64-bit handles and doubles.

``abi-shim-decl``
    ``native/src/shim.c`` re-declares a ``tdcn_*`` extern with a
    parameter count that disagrees with ``dcn.cc`` — C has no cross-TU
    checking for this; the linker happily binds the wrong arity.

``catalog-drift``
    The README operator surface: every centrally registered MCA var
    (the ``OBSERVABILITY_VARS``/``ROBUSTNESS_VARS``/``SERVING_VARS``
    tables), every ``NATIVE_COUNTERS`` entry, and every ops HTTP route
    (``add_route`` literals + the aggregator's built-in endpoints)
    must appear in README.md — and every ``/endpoint`` row in the
    README ops table must exist in code.

``wire-ctx-drift`` / ``wire-ctx-append-only``
    The causal-tracing wire context (``trace/causal.py:CTX_FIELDS``)
    vs its C mirror (``dcn.cc:TDCN_TRACE_CTX_FIELDS``): same fields,
    same order, both sides — and the v1 prefix is FROZEN with new
    fields appended at the tail only (the TdcnStats contract applied
    to the wire: peers parse contexts by position, so a reorder or
    rename inside the frozen prefix silently mis-decodes every frame
    between mixed builds).

``plane-catalog-drift``
    The plane-health family (PR 18), both directions: the ``plane_*``
    counters must agree between ``NATIVE_COUNTERS`` and the device
    plane's ``STATS_KEYS`` (the provider merge silently drops a key
    missing from either), every ``plane_*`` counter and ``dcn_plane_*``
    knob must appear in the README plane-health catalog, and every
    backticked ``plane_*`` name the README promises must exist in
    code — stale catalog entries are drift too.

``pvar-name-lint``
    The ``trace_causal_*`` pvar family (``causal.PVARS``): every name
    is a well-formed lowercase identifier, collides with no other
    trace-pvar namespace segment (``trace_span_`` would shadow the
    layer parser), and its full ``trace_causal_<name>`` form is
    documented in the README counter catalog.

Everything is parsed statically (AST for Python, regex over the
``extern "C"`` block for C) — the pass never imports or builds the
modules it is judging.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from ompi_tpu.analysis.findings import SEV_ERROR, SEV_WARN, Finding
from ompi_tpu.analysis.repo import central_var_tables, parse_py, rel

PASS = "abidrift"

DCN_CC = "native/src/dcn.cc"
SHIM_C = "native/src/shim.c"
NATIVE_PY = "ompi_tpu/dcn/native.py"
METRICS_CORE = "ompi_tpu/metrics/core.py"
README = "README.md"

#: the frozen v1 counter prefix (PR 2's shipped schema, version slot
#: excluded).  These are live MPI_T pvar names with cached indices —
#: renaming or reordering ANY of them is an ABI break even though the
#: tails behind them may grow.
V1_FROZEN_PREFIX = (
    "doorbells", "stall_ns", "ring_stall_ns", "ring_stalls", "ring_hwm",
    "cts_wait_ns", "cts_waits", "rndv_depth", "rndv_hwm", "slot_waits",
    "eager_msgs", "eager_bytes", "chunked_msgs", "chunked_bytes",
    "rndv_msgs", "rndv_bytes", "delivered", "unexpected_hwm",
)


# -- the two counter name tables ----------------------------------------

def c_stat_names(root: Path) -> tuple[list[str], int]:
    """(names, line) parsed from the TDCN_STAT_NAMES concatenated
    string literal in dcn.cc; ([], 0) when unparseable."""
    src = root / DCN_CC
    try:
        text = src.read_text()
    except OSError:
        return [], 0
    m = re.search(
        r"TDCN_STAT_NAMES\s*=\s*((?:\s*\"[^\"]*\")+)\s*;", text)
    if not m:
        return [], 0
    line = text[:m.start()].count("\n") + 1
    joined = "".join(re.findall(r'"([^"]*)"', m.group(1)))
    return [n for n in joined.split(",") if n], line


def py_native_counters(root: Path) -> tuple[list[str], int]:
    """(names, line) of metrics/core.py NATIVE_COUNTERS."""
    tree = parse_py(root / METRICS_CORE)
    if tree is None:
        return [], 0
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "NATIVE_COUNTERS"
                and isinstance(node.value, ast.Tuple)):
            names = [e.value for e in node.value.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str)]
            return names, node.lineno
    return [], 0


def check_stat_names(root: Path) -> list[Finding]:
    out: list[Finding] = []
    c_names, c_line = c_stat_names(root)
    py_names, py_line = py_native_counters(root)
    if not c_names:
        out.append(Finding(
            PASS, "stat-names-drift", DCN_CC, 0, "TDCN_STAT_NAMES",
            "cannot parse TDCN_STAT_NAMES from dcn.cc — the checker "
            "(and the Python schema reader) need the concatenated "
            "string-literal form", SEV_ERROR))
        return out
    if not py_names:
        out.append(Finding(
            PASS, "stat-names-drift", METRICS_CORE, 0, "NATIVE_COUNTERS",
            "cannot parse NATIVE_COUNTERS tuple from metrics/core.py",
            SEV_ERROR))
        return out
    expect = ["version"] + py_names
    if c_names != expect:
        # localize the first divergence for the message
        detail = ""
        for i, (a, b) in enumerate(zip(c_names, expect)):
            if a != b:
                detail = (f"first divergence at index {i}: "
                          f"C has {a!r}, Python has {b!r}")
                break
        else:
            longer = "C" if len(c_names) > len(expect) else "Python"
            extra = (c_names[len(expect):] if longer == "C"
                     else expect[len(c_names):])
            detail = f"{longer} side has extra tail entries {extra}"
        out.append(Finding(
            PASS, "stat-names-drift", DCN_CC, c_line, "TDCN_STAT_NAMES",
            "TDCN_STAT_NAMES != ['version'] + NATIVE_COUNTERS "
            f"(metrics/core.py:{py_line}) — {detail}; the name table "
            "is the single source of schema truth and both sides must "
            "agree exactly (names AND order)", SEV_ERROR))
    # append-only: the frozen v1 prefix must open both tables
    for side, names, f, ln in (("C", c_names[1:], DCN_CC, c_line),
                               ("Python", py_names, METRICS_CORE, py_line)):
        prefix = tuple(names[:len(V1_FROZEN_PREFIX)])
        if prefix != V1_FROZEN_PREFIX:
            bad = next((i for i, (a, b) in enumerate(
                zip(prefix, V1_FROZEN_PREFIX)) if a != b),
                len(prefix))
            out.append(Finding(
                PASS, "stat-append-only", f, ln,
                "TDCN_STAT_NAMES" if side == "C" else "NATIVE_COUNTERS",
                f"{side} counter table breaks the frozen v1 prefix at "
                f"index {bad} (have {list(prefix[bad:bad + 2])!r}, "
                f"frozen {list(V1_FROZEN_PREFIX[bad:bad + 2])!r}) — "
                "these are live MPI_T pvar names; the schema is "
                "append-only (new counters go at the tail, version "
                "stays 1)", SEV_ERROR))
    return out


# -- C prototypes vs ctypes signatures ----------------------------------

#: machine-width classes both sides collapse to
_C_TYPE_CLASS = (
    (re.compile(r"\*"), "ptr"),
    (re.compile(r"\bdouble\b"), "double"),
    (re.compile(r"\buint64_t\b|\bunsigned long long\b"), "uint64"),
    (re.compile(r"\bint64_t\b|\blong long\b"), "int64"),
    (re.compile(r"\buint32_t\b"), "uint32"),
    (re.compile(r"\bint\b"), "int32"),
    (re.compile(r"\bvoid\b"), "void"),
)

_CTYPES_CLASS = {
    "c_void_p": "ptr", "c_char_p": "ptr", "POINTER": "ptr",
    "c_double": "double", "c_uint64": "uint64", "c_int64": "int64",
    "c_uint32": "uint32", "c_int": "int32",
}


def _c_class(decl: str) -> str:
    for rx, cls in _C_TYPE_CLASS:
        if rx.search(decl):
            return cls
    return "unknown"


_C_FN_RE = re.compile(
    r"^[ \t]*((?:const[ \t]+)?[A-Za-z_][A-Za-z0-9_]*(?:[ \t]+[A-Za-z_]"
    r"[A-Za-z0-9_]*)?[ \t*]*?)\b(tdcn_[A-Za-z0-9_]*)\s*\(([^;{]*?)\)\s*\{",
    re.M | re.S)


def c_functions(text: str) -> dict[str, tuple[int, str, list[str]]]:
    """name → (line, return_decl, [param_decl]) for every tdcn_*
    definition in a C/C++ source blob."""
    out: dict[str, tuple[int, str, list[str]]] = {}
    for m in _C_FN_RE.finditer(text):
        ret, name, params = m.group(1), m.group(2), m.group(3)
        line = text[:m.start()].count("\n") + 1
        params = re.sub(r"\s+", " ", params).strip()
        plist = ([] if params in ("", "void")
                 else [p.strip() for p in params.split(",")])
        out[name] = (line, ret.strip(), plist)
    return out


_C_EXTERN_RE = re.compile(
    r"^[ \t]*extern[ \t]+((?:const[ \t]+)?[A-Za-z_][A-Za-z0-9_ ]*?[ \t*]+)"
    r"(tdcn_[A-Za-z0-9_]*)\s*\(([^;{]*?)\)\s*;",
    re.M | re.S)


def c_extern_decls(text: str) -> dict[str, tuple[int, list[str]]]:
    """name → (line, [param_decl]) for tdcn_* extern declarations."""
    out: dict[str, tuple[int, list[str]]] = {}
    for m in _C_EXTERN_RE.finditer(text):
        params = re.sub(r"\s+", " ", m.group(3)).strip()
        plist = ([] if params in ("", "void")
                 else [p.strip() for p in params.split(",")])
        out[m.group(2)] = (text[:m.start()].count("\n") + 1, plist)
    return out


def _ctypes_expr_class(node: ast.AST, aliases: dict[str, str]) -> str:
    """Collapse a ctypes expression (Name alias, ctypes.c_*, POINTER(…),
    c_T * N arrays) to a machine-width class."""
    if isinstance(node, ast.Name):
        base = aliases.get(node.id)
        if base is not None:
            return _CTYPES_CLASS.get(base, "unknown")
        return _CTYPES_CLASS.get(node.id, "unknown")
    if isinstance(node, ast.Attribute):
        return _CTYPES_CLASS.get(node.attr, "unknown")
    if isinstance(node, ast.Call):
        fname = (node.func.attr if isinstance(node.func, ast.Attribute)
                 else node.func.id if isinstance(node.func, ast.Name)
                 else "")
        if fname == "POINTER":
            return "ptr"
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
        return "ptr"  # ctypes array types decay to pointers at the ABI
    return "unknown"


class _CtypesDecls(ast.NodeVisitor):
    """Collect lib.tdcn_*.argtypes/.restype declarations, ctypes
    aliases, and every tdcn_* attribute reference in native.py."""

    def __init__(self) -> None:
        self.aliases: dict[str, str] = {}   # P -> c_void_p, MSG -> POINTER
        self.argtypes: dict[str, tuple[int, list[str]]] = {}
        self.restype: dict[str, tuple[int, str]] = {}
        self.referenced: dict[str, int] = {}

    def visit_Assign(self, node: ast.Assign) -> None:
        # tuple-unpacked aliases: P, I, … = (ctypes.c_void_p, …)
        if (len(node.targets) == 1 and isinstance(node.targets[0], ast.Tuple)
                and isinstance(node.value, ast.Tuple)
                and len(node.targets[0].elts) == len(node.value.elts)):
            for t, v in zip(node.targets[0].elts, node.value.elts):
                if isinstance(t, ast.Name) and isinstance(v, ast.Attribute):
                    self.aliases[t.id] = v.attr
        # single alias: MSG = ctypes.POINTER(TdcnMsg)
        if (len(node.targets) == 1 and isinstance(node.targets[0], ast.Name)):
            v = node.value
            if isinstance(v, ast.Attribute) and v.attr.startswith("c_"):
                self.aliases[node.targets[0].id] = v.attr
            elif (isinstance(v, ast.Call)
                  and isinstance(v.func, (ast.Attribute, ast.Name))
                  and (v.func.attr if isinstance(v.func, ast.Attribute)
                       else v.func.id) == "POINTER"):
                self.aliases[node.targets[0].id] = "POINTER"
        # lib.tdcn_X.argtypes / .restype
        tgt = node.targets[0] if len(node.targets) == 1 else None
        if (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Attribute)
                and tgt.value.attr.startswith("tdcn_")):
            sym = tgt.value.attr
            if tgt.attr == "argtypes" and isinstance(node.value, ast.List):
                self.argtypes[sym] = (node.lineno, list(
                    map(ast.dump, node.value.elts)))
                self._argtype_nodes = getattr(self, "_argtype_nodes", {})
                self._argtype_nodes[sym] = (node.lineno, node.value.elts)
            elif tgt.attr == "restype":
                self.restype[sym] = (node.lineno, ast.dump(node.value))
                self._restype_nodes = getattr(self, "_restype_nodes", {})
                self._restype_nodes[sym] = (node.lineno, node.value)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr.startswith("tdcn_"):
            self.referenced.setdefault(node.attr, node.lineno)
        self.generic_visit(node)


def check_ctypes(root: Path) -> list[Finding]:
    out: list[Finding] = []
    try:
        c_text = (root / DCN_CC).read_text()
    except OSError:
        return [Finding(PASS, "abi-missing-symbol", DCN_CC, 0, "",
                        "cannot read dcn.cc", SEV_ERROR)]
    cdefs = c_functions(c_text)
    tree = parse_py(root / NATIVE_PY)
    if tree is None:
        return [Finding(PASS, "abi-undeclared-call", NATIVE_PY, 0, "",
                        "cannot parse dcn/native.py", SEV_ERROR)]
    decls = _CtypesDecls()
    decls.visit(tree)
    argtype_nodes = getattr(decls, "_argtype_nodes", {})
    restype_nodes = getattr(decls, "_restype_nodes", {})

    for sym, (line, elts) in sorted(argtype_nodes.items()):
        if sym not in cdefs:
            out.append(Finding(
                PASS, "abi-missing-symbol", NATIVE_PY, line, sym,
                f"ctypes declares {sym} but dcn.cc exports no such "
                "function — renamed or removed on the C side",
                SEV_ERROR))
            continue
        c_line, c_ret, c_params = cdefs[sym]
        if len(elts) != len(c_params):
            out.append(Finding(
                PASS, "abi-arity", NATIVE_PY, line, sym,
                f"argtypes declares {len(elts)} parameters but "
                f"{DCN_CC}:{c_line} defines {len(c_params)} — ctypes "
                "will mis-marshal every call", SEV_ERROR))
            continue
        for i, (el, cp) in enumerate(zip(elts, c_params)):
            py_cls = _ctypes_expr_class(el, decls.aliases)
            c_cls = _c_class(cp)
            if py_cls == "unknown" or c_cls == "unknown":
                continue  # conservatively skip what we cannot classify
            if py_cls != c_cls and not (
                    # int32 passed for uint32 flags is ABI-identical
                    {py_cls, c_cls} == {"int32", "uint32"}):
                out.append(Finding(
                    PASS, "abi-type", NATIVE_PY, line, sym,
                    f"argtypes[{i}] is {py_cls} but the C parameter "
                    f"({cp!r} at {DCN_CC}:{c_line}) is {c_cls} — "
                    "width mismatch truncates/garbles at the call "
                    "boundary", SEV_ERROR))
    for sym, (line, node) in sorted(restype_nodes.items()):
        if sym not in cdefs:
            continue  # missing-symbol already reported via argtypes
        c_line, c_ret, _ = cdefs[sym]
        py_cls = _ctypes_expr_class(node, decls.aliases)
        c_cls = _c_class(c_ret)
        if py_cls in ("unknown",) or c_cls in ("unknown", "void"):
            continue
        if py_cls != c_cls and {py_cls, c_cls} != {"int32", "uint32"}:
            out.append(Finding(
                PASS, "abi-type", NATIVE_PY, line, sym,
                f"restype is {py_cls} but {sym} returns {c_ret!r} "
                f"({c_cls}) at {DCN_CC}:{c_line}", SEV_ERROR))
    # referenced but never given argtypes → ctypes guesses int widths
    for sym, line in sorted(decls.referenced.items()):
        if sym in argtype_nodes:
            continue
        if sym not in cdefs:
            out.append(Finding(
                PASS, "abi-missing-symbol", NATIVE_PY, line, sym,
                f"{sym} is referenced but dcn.cc exports no such "
                "function", SEV_ERROR))
            continue
        c_line, _ret, c_params = cdefs[sym]
        out.append(Finding(
            PASS, "abi-undeclared-call", NATIVE_PY, line, sym,
            f"{sym} ({DCN_CC}:{c_line}, {len(c_params)} params) is "
            "called with no argtypes declaration — ctypes falls back "
            "to int-width guessing, which breaks 64-bit handles and "
            "doubles", SEV_ERROR))
    # C-side extern re-declarations must agree on arity: shim.c (the
    # C ABI) and dcn_sanity.cc (the sanitizer soak) both restate the
    # tdcn_* prototypes, and C has no cross-TU checking — the linker
    # binds a wrong arity silently
    for c_rel in (SHIM_C, "native/src/dcn_sanity.cc"):
        try:
            c_decl_text = (root / c_rel).read_text()
        except OSError:
            c_decl_text = ""
        for sym, (line, plist) in sorted(c_extern_decls(c_decl_text).items()):
            if sym not in cdefs:
                out.append(Finding(
                    PASS, "abi-shim-decl", c_rel, line, sym,
                    f"{c_rel} declares extern {sym} but dcn.cc exports "
                    "no such function", SEV_ERROR))
                continue
            c_line, _ret, c_params = cdefs[sym]
            if len(plist) != len(c_params):
                out.append(Finding(
                    PASS, "abi-shim-decl", c_rel, line, sym,
                    f"{c_rel} extern declares {len(plist)} parameters "
                    f"but {DCN_CC}:{c_line} defines {len(c_params)} — "
                    "the linker binds this silently at the wrong arity",
                    SEV_ERROR))
    return out


# -- causal wire-context field table (C mirror) --------------------------

CAUSAL_PY = "ompi_tpu/trace/causal.py"

#: the frozen v1 wire-context prefix — live positional wire fields;
#: renaming or reordering ANY of them mis-decodes frames between
#: mixed builds even though the tail may grow
CTX_V1_FROZEN = ("v", "comm", "op", "seq", "hop")


def _py_tuple_of(root: Path, relpath: str,
                 name: str) -> tuple[list[str], int]:
    """(string elements, line) of a module-level tuple assignment."""
    tree = parse_py(root / relpath)
    if tree is None:
        return [], 0
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
                and isinstance(node.value, ast.Tuple)):
            return [e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)], node.lineno
    return [], 0


def c_trace_ctx_fields(root: Path) -> tuple[list[str], int]:
    """(fields, line) parsed from the TDCN_TRACE_CTX_FIELDS
    concatenated string literal in dcn.cc; ([], 0) when absent."""
    try:
        text = (root / DCN_CC).read_text()
    except OSError:
        return [], 0
    m = re.search(
        r"TDCN_TRACE_CTX_FIELDS\s*=\s*((?:\s*\"[^\"]*\")+)\s*;", text)
    if not m:
        return [], 0
    line = text[:m.start()].count("\n") + 1
    joined = "".join(re.findall(r'"([^"]*)"', m.group(1)))
    return [n for n in joined.split(",") if n], line


def check_trace_ctx(root: Path) -> list[Finding]:
    """``wire-ctx-drift``/``wire-ctx-append-only`` (docstring)."""
    c_fields, c_line = c_trace_ctx_fields(root)
    py_fields, py_line = _py_tuple_of(root, CAUSAL_PY, "CTX_FIELDS")
    if not c_fields and not py_fields:
        return []  # neither side exists (fixture trees): nothing owed
    out: list[Finding] = []
    if not c_fields:
        return [Finding(
            PASS, "wire-ctx-drift", DCN_CC, 0, "TDCN_TRACE_CTX_FIELDS",
            "trace/causal.py declares CTX_FIELDS but dcn.cc carries no "
            "TDCN_TRACE_CTX_FIELDS mirror — the wire-context schema "
            "needs both sides (single-source-of-truth contract)",
            SEV_ERROR)]
    if not py_fields:
        return [Finding(
            PASS, "wire-ctx-drift", CAUSAL_PY, 0, "CTX_FIELDS",
            "dcn.cc carries TDCN_TRACE_CTX_FIELDS but trace/causal.py "
            "declares no CTX_FIELDS tuple", SEV_ERROR)]
    if c_fields != py_fields:
        detail = ""
        for i, (a, b) in enumerate(zip(c_fields, py_fields)):
            if a != b:
                detail = (f"first divergence at index {i}: C has {a!r}, "
                          f"Python has {b!r}")
                break
        else:
            longer = "C" if len(c_fields) > len(py_fields) else "Python"
            extra = (c_fields[len(py_fields):] if longer == "C"
                     else py_fields[len(c_fields):])
            detail = f"{longer} side has extra tail entries {extra}"
        out.append(Finding(
            PASS, "wire-ctx-drift", DCN_CC, c_line,
            "TDCN_TRACE_CTX_FIELDS",
            "TDCN_TRACE_CTX_FIELDS != trace/causal.py CTX_FIELDS "
            f"({CAUSAL_PY}:{py_line}) — {detail}; contexts are parsed "
            "by position, so both sides must agree exactly",
            SEV_ERROR))
    for side, fields, f, ln in ((
            "C", c_fields, DCN_CC, c_line),
            ("Python", py_fields, CAUSAL_PY, py_line)):
        prefix = tuple(fields[:len(CTX_V1_FROZEN)])
        if prefix != CTX_V1_FROZEN:
            bad = next((i for i, (a, b) in enumerate(
                zip(prefix, CTX_V1_FROZEN)) if a != b), len(prefix))
            out.append(Finding(
                PASS, "wire-ctx-append-only", f, ln,
                "TDCN_TRACE_CTX_FIELDS" if side == "C" else "CTX_FIELDS",
                f"{side} wire-context table breaks the frozen v1 "
                f"prefix at index {bad} (have "
                f"{list(prefix[bad:bad + 2])!r}, frozen "
                f"{list(CTX_V1_FROZEN[bad:bad + 2])!r}) — fields are "
                "positional on the wire; the schema is append-only "
                "(new fields at the tail, version stays 1)", SEV_ERROR))
    return out


_PVAR_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def check_causal_pvars(root: Path) -> list[Finding]:
    """``pvar-name-lint`` over the ``trace_causal_*`` family."""
    names, line = _py_tuple_of(root, CAUSAL_PY, "PVARS")
    if not names:
        return []  # no causal module (fixture trees): nothing owed
    out: list[Finding] = []
    try:
        readme = (root / README).read_text()
    except OSError:
        readme = ""
    seen: set[str] = set()
    for n in names:
        full = f"trace_causal_{n}"
        if not _PVAR_NAME_RE.match(n):
            out.append(Finding(
                PASS, "pvar-name-lint", CAUSAL_PY, line, full,
                f"causal pvar segment {n!r} is not a lowercase "
                "identifier — prom/MPI_T names derive from it verbatim",
                SEV_ERROR))
        if n in seen:
            out.append(Finding(
                PASS, "pvar-name-lint", CAUSAL_PY, line, full,
                f"duplicate causal pvar segment {n!r}", SEV_ERROR))
        seen.add(n)
        if n.startswith("span_"):
            out.append(Finding(
                PASS, "pvar-name-lint", CAUSAL_PY, line, full,
                "causal pvar segment must not start with 'span_' — "
                "trace_span_* is the per-(layer, op) namespace and the "
                "name parser would shadow it", SEV_ERROR))
        if readme and full not in readme:
            out.append(Finding(
                PASS, "pvar-name-lint", README, 0, full,
                f"causal pvar {full!r} is missing from the README "
                "counter catalog — the catalog promises the full "
                "observability schema", SEV_ERROR))
    return out


# -- transport counters vs the provider merge ---------------------------

def _counter_keys(tree: ast.Module) -> list[tuple[str, int]]:
    """(key, line) for every counter name a transport initializes:
    string keys of ``…stats = { … }`` dict literals (Assign or
    AnnAssign) and elements of ``STATS_KEYS`` tuples."""
    out: list[tuple[str, int]] = []
    for node in ast.walk(tree):
        tgt = None
        val = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt, val = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            tgt, val = node.target, node.value
        if tgt is None:
            continue
        name = (tgt.attr if isinstance(tgt, ast.Attribute)
                else tgt.id if isinstance(tgt, ast.Name) else "")
        if name == "stats" and isinstance(val, ast.Dict):
            out += [(k.value, node.lineno) for k in val.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)]
        elif name == "STATS_KEYS" and isinstance(val, ast.Tuple):
            out += [(e.value, node.lineno) for e in val.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
    return out


def check_provider_merge(root: Path) -> list[Finding]:
    """``provider-merge-drift``: every counter key a DCN transport or
    plane initializes (the dicts its metrics provider snapshots) must
    appear in ``NATIVE_COUNTERS`` — a key outside the schema is
    silently DROPPED by the provider merge (``native_counters`` only
    sums known names), so the counter would exist in code yet never
    reach a pvar, the Prometheus export, the live scrape, or
    ``tools/top.py``."""
    counters, _ = py_native_counters(root)
    if not counters:
        return []  # stat-names-drift already reports the parse failure
    cset = set(counters)
    out: list[Finding] = []
    dcn_dir = root / "ompi_tpu" / "dcn"
    if not dcn_dir.is_dir():
        return []
    for path in sorted(dcn_dir.glob("*.py")):
        tree = parse_py(path)
        if tree is None:
            continue
        rel_p = rel(root, path)
        for key, line in _counter_keys(tree):
            if key not in cset:
                out.append(Finding(
                    PASS, "provider-merge-drift", rel_p, line, key,
                    f"transport counter {key!r} is initialized here but "
                    "missing from metrics/core.py NATIVE_COUNTERS — the "
                    "provider merge drops unknown names, so this counter "
                    "would never surface as a dcn_* pvar, in the "
                    "finalize/live exports, or in tools/top.py",
                    SEV_ERROR))
    return out


# -- README operator-surface catalogs -----------------------------------

def _served_routes(root: Path) -> dict[str, tuple[str, int]]:
    """route path → (file, line): add_route string literals plus the
    aggregator's built-in endpoints."""
    routes: dict[str, tuple[str, int]] = {}
    for relpath in ("ompi_tpu/serve/daemon.py", "ompi_tpu/metrics/live.py"):
        tree = parse_py(root / relpath)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_route"
                    and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                routes[node.args[1].value] = (relpath, node.lineno)
    # built-in aggregator endpoints: the literal `self.path.startswith`
    # dispatch in live.py
    live = root / "ompi_tpu/metrics/live.py"
    try:
        for lineno, line in enumerate(live.read_text().splitlines(), 1):
            m = re.search(r"self\.path\.startswith\(\"(/[a-z]+)\"\)", line)
            if m:
                routes.setdefault(m.group(1),
                                  ("ompi_tpu/metrics/live.py", lineno))
    except OSError:
        pass
    return routes


def check_catalogs(root: Path) -> list[Finding]:
    out: list[Finding] = []
    try:
        readme = (root / README).read_text()
    except OSError:
        return [Finding(PASS, "catalog-drift", README, 0, "",
                        "README.md missing", SEV_ERROR)]
    # every centrally registered var must appear in the README
    for table, names in central_var_tables(root).items():
        for name in names:
            if name not in readme:
                out.append(Finding(
                    PASS, "catalog-drift", README, 0, table,
                    f"centrally registered var {name!r} ({table}) is "
                    "not documented anywhere in README.md — operators "
                    "discover knobs there", SEV_ERROR))
    # every native counter must appear (the catalog merges families as
    # `eager_msgs/bytes`, so accept the family row form too)
    counters, _ = py_native_counters(root)
    for name in counters:
        ok = name in readme
        if not ok and name.endswith("_bytes"):
            ok = name[:-len("_bytes")] + "_msgs/bytes" in readme
        if not ok and name.endswith("_stalls"):
            ok = name[:-len("_stalls")] + "_stall_ns` / `" \
                + name in readme or f"/ `{name}`" in readme
        if not ok:
            out.append(Finding(
                PASS, "catalog-drift", README, 0, "NATIVE_COUNTERS",
                f"native counter {name!r} is missing from the README "
                "counter catalog — the catalog promises the full "
                "schema (MPI_T pvar names dcn_<name>)", SEV_ERROR))
    # ops endpoints: code routes ⊆ README (table row or backticked
    # prose both count as documentation) and table rows ⊆ code
    routes = _served_routes(root)
    doc_rows = set(re.findall(r"^\|\s*`(/[a-z]+)", readme, re.M))
    doc_any = doc_rows | {m.split("/<", 1)[0] for m in
                          re.findall(r"`(/[a-z]+)[^`]*`", readme)}
    for path, (f, ln) in sorted(routes.items()):
        if path not in doc_any and path.rstrip("/") not in doc_any:
            out.append(Finding(
                PASS, "catalog-drift", f, ln, path,
                f"ops endpoint {path!r} is served but documented "
                "nowhere in README (endpoint table or prose)",
                SEV_ERROR))
    for path in sorted(doc_rows):
        if path not in routes:
            out.append(Finding(
                PASS, "catalog-drift", README, 0, path,
                f"README endpoint table documents {path!r} but no "
                "add_route/dispatch site serves it", SEV_WARN))
    return out


DEVICE_PY = "ompi_tpu/dcn/device.py"


def check_plane_catalog(root: Path) -> list[Finding]:
    """``plane-catalog-drift``: the plane-health counter family
    (``plane_*``) and knob family (``dcn_plane_*``) must agree across
    code and the README "Plane health" catalog, BOTH directions —
    a counter/knob the code carries but the README omits is an
    undocumented operator surface; a name the README documents but
    the code lacks is a stale promise (rename/removal drift).  The
    code side is itself cross-checked: the device plane's STATS_KEYS
    plane family must equal the NATIVE_COUNTERS plane family (the
    provider merge would silently drop a key missing from either)."""
    out: list[Finding] = []
    native = [n for n in py_native_counters(root)[0]
              if n.startswith("plane_")]
    skeys, sline = _py_tuple_of(root, DEVICE_PY, "STATS_KEYS")
    dev = [n for n in skeys if n.startswith("plane_")]
    for name in native:
        if name not in dev:
            out.append(Finding(
                PASS, "plane-catalog-drift", DEVICE_PY, sline, name,
                f"plane-health counter {name!r} is in NATIVE_COUNTERS "
                "but missing from the device plane's STATS_KEYS — the "
                "provider would never populate it", SEV_ERROR))
    for name in dev:
        if name not in native:
            out.append(Finding(
                PASS, "plane-catalog-drift", DEVICE_PY, sline, name,
                f"plane-health counter {name!r} is in STATS_KEYS but "
                "missing from NATIVE_COUNTERS — the provider merge "
                "drops unknown keys", SEV_ERROR))
    try:
        readme = (root / README).read_text()
    except OSError:
        return out
    knobs = [n for names in central_var_tables(root).values()
             for n in names if n.startswith("dcn_plane_")]
    # code → README: every plane counter and knob is documented
    for name in native:
        if name not in readme:
            out.append(Finding(
                PASS, "plane-catalog-drift", README, 0, name,
                f"plane-health counter {name!r} is missing from the "
                "README plane-health catalog", SEV_ERROR))
    for name in knobs:
        if name not in readme:
            out.append(Finding(
                PASS, "plane-catalog-drift", README, 0, name,
                f"plane-health knob {name!r} is missing from the "
                "README plane-health catalog", SEV_ERROR))
    # README → code: every plane_* token the README promises exists
    # (dcn_plane_<x> resolves as a knob or the counter pvar form)
    doc = set(re.findall(r"`(?:dcn_)?(plane_[a-z_]+)`", readme))
    known = set(native) | {k[len("dcn_"):] for k in knobs}
    for name in sorted(doc - known):
        out.append(Finding(
            PASS, "plane-catalog-drift", README, 0, name,
            f"README documents plane-health name {name!r} but neither "
            "a plane_* counter nor a dcn_plane_* knob carries it — "
            "stale catalog entry", SEV_ERROR))
    return out


def run(root: str | Path, files=None) -> list[Finding]:
    """Run the ABI drift pass.  ``files`` is accepted for driver
    symmetry; the pass's inputs are the fixed contract files."""
    root = Path(root)
    out: list[Finding] = []
    out += check_stat_names(root)
    out += check_ctypes(root)
    out += check_provider_merge(root)
    out += check_trace_ctx(root)
    out += check_causal_pvars(root)
    out += check_catalogs(root)
    out += check_plane_catalog(root)
    return out
