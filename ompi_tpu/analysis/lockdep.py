"""Runtime lock-order witness (the dynamic half of the lockorder pass).

Opt-in lockdep/witness-style order recording over *real* lock
instances: while enabled, every ``threading.Lock``/``RLock``/
``Condition`` allocation returns a wrapped lock tagged with its
allocation site ("<file>:<line>" — the runtime analog of lockdep's
lock class), and every acquire records the edge *held → acquired* in
one global order graph.  Observing both ``A → B`` and ``B → A`` is an
**order inversion**: two threads interleaving those paths can
deadlock, even if this run happened not to.  :func:`assert_clean`
turns any recorded inversion into a test failure.

Usage (tests; also wired session-wide by ``tests/conftest.py`` under
``OMPI_TPU_LOCKDEP=1``)::

    from ompi_tpu.analysis import lockdep
    lockdep.enable()
    try:
        ... exercise threaded code; locks it allocates are witnessed ...
        lockdep.assert_clean()
    finally:
        lockdep.disable()

Scope and honesty notes:

* Only locks **allocated while enabled** are witnessed — the witness
  patches the ``threading`` factories, so module-level locks created
  at import time are invisible.  That matches the intended use: the
  threaded planes (transports, detector, publisher, tpud workers)
  allocate their locks per instance, in ``__init__``.
* ``Condition.wait`` releases the underlying lock; the held-stack
  drops it for the duration so wait-side edges are not fabricated.
* Self-deadlock (re-acquiring a held non-reentrant Lock with no
  timeout) is recorded as a violation too — that is a wedge today,
  not a maybe.
* The witness never *prevents* deadlock; it records the order
  evidence.  Overhead is a dict update per acquire, so it stays
  test-only (enable/disable, never on by default).
"""

from __future__ import annotations

import threading
import traceback
from dataclasses import dataclass

__all__ = [
    "enable", "disable", "enabled", "reset", "violations",
    "assert_clean", "LockOrderInversion", "current_edges",
]


class LockOrderInversion(AssertionError):
    """Raised by :func:`assert_clean` when an inversion was observed."""


@dataclass
class Violation:
    kind: str        # "inversion" | "self-deadlock"
    a: str           # lock class (allocation site) acquired first
    b: str           # lock class acquired under a
    where: str       # "file:line" of the acquire completing the cycle
    detail: str

    def render(self) -> str:
        return f"{self.kind}: {self.detail} (at {self.where})"


# one global witness state; guarded by a PRISTINE lock captured before
# any patching so the witness never witnesses itself
_true_lock_factory = threading.Lock
_true_rlock_factory = threading.RLock
_true_condition = threading.Condition

_state_lock = _true_lock_factory()
_enabled = False
_enable_depth = 0   # nested enable()s (session witness + test fixture)
_edges: dict[tuple[str, str], str] = {}   # (held, acquired) -> site
_violations: list[Violation] = []
_tls = threading.local()


def _held() -> list[str]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = []
        _tls.held = h
    return h


def _alloc_site() -> str:
    """file:line of the frame allocating the lock, skipping this module
    and threading.py itself (Condition allocates an RLock)."""
    for frame in traceback.extract_stack()[-8:][::-1]:
        fn = frame.filename
        if fn.endswith(("analysis/lockdep.py", "threading.py")):
            continue
        return f"{fn.rsplit('/', 1)[-1]}:{frame.lineno}"
    return "<unknown>"


def _call_site() -> str:
    for frame in traceback.extract_stack()[-8:][::-1]:
        fn = frame.filename
        if fn.endswith(("analysis/lockdep.py", "threading.py")):
            continue
        return f"{fn.rsplit('/', 1)[-1]}:{frame.lineno}"
    return "<unknown>"


def _record_acquire(key: str, reentrant: bool, blocking: bool,
                    timeout: float) -> None:
    held = _held()
    site = _call_site()
    with _state_lock:
        if (key in held and not reentrant and blocking and timeout < 0
                and not any(v.kind == "self-deadlock" and v.a == key
                            for v in _violations)):
            _violations.append(Violation(
                "self-deadlock", key, key, site,
                f"non-reentrant lock {key} re-acquired while already "
                f"held by this thread"))
        # a try-acquire never waits, so it cannot participate in a
        # deadlock cycle: record no order edge for it (Linux lockdep
        # excludes trylocks for the same reason).  If it succeeds the
        # lock still joins the held stack below — edges taken while
        # HOLDING it are real regardless of how it was acquired.
        if blocking:
            for h in held:
                if h == key:
                    continue
                fwd = (h, key)
                rev = (key, h)
                if fwd not in _edges:
                    _edges[fwd] = site
                if rev in _edges and not any(
                        v.kind == "inversion" and {v.a, v.b} == {h, key}
                        for v in _violations):
                    _violations.append(Violation(
                        "inversion", h, key, site,
                        f"lock order inversion: {h} → {key} here, but "
                        f"{key} → {h} was recorded at {_edges[rev]}"))
    held.append(key)


def _record_release(key: str) -> None:
    held = _held()
    # remove the most recent acquisition of this class (LIFO-ish; out
    # of order release is legal for locks, so scan from the tail)
    for i in range(len(held) - 1, -1, -1):
        if held[i] == key:
            del held[i]
            return


class _WitnessedLock:
    """Wraps a real lock primitive with order recording.  Mimics the
    Lock/RLock duck type (incl. the private hooks Condition uses)."""

    def __init__(self, inner, key: str, reentrant: bool):
        self._inner = inner
        self._key = key
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if _enabled:
            _record_acquire(self._key, self._reentrant, blocking, timeout)
        got = self._inner.acquire(blocking, timeout)
        if not got and _enabled:
            _record_release(self._key)  # failed try-acquire: not held
        return got

    def release(self):
        self._inner.release()
        if _enabled:
            _record_release(self._key)

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition(lock=witnessed) support: Condition calls these if
    # present, and releases/reacquires around wait()
    def _release_save(self):
        if _enabled:
            _record_release(self._key)
        if hasattr(self._inner, "_release_save"):
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state):
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        if _enabled:
            _held().append(self._key)

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return f"<witnessed {self._key} {self._inner!r}>"


def _make_lock():
    return _WitnessedLock(_true_lock_factory(), _alloc_site(),
                          reentrant=False)


def _make_rlock():
    return _WitnessedLock(_true_rlock_factory(), _alloc_site(),
                          reentrant=True)


def _make_condition(lock=None):
    return _true_condition(lock if lock is not None else _make_rlock())


def enable() -> None:
    """Patch the ``threading`` lock factories; locks allocated from now
    on are witnessed.  Nestable: a test-local witness inside a
    session-wide ``OMPI_TPU_LOCKDEP=1`` run must not disarm the outer
    one — each ``enable()`` needs a matching ``disable()``, and only
    the last restores the real factories."""
    global _enabled, _enable_depth
    with _state_lock:
        _enable_depth += 1
        if _enabled:
            return
        _enabled = True
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    threading.Condition = _make_condition


def disable() -> None:
    """Undo one :func:`enable`; the real factories come back when the
    outermost enabler disables.  Already-witnessed locks keep working
    (recording stops — ``_enabled`` gates every hook)."""
    global _enabled, _enable_depth
    with _state_lock:
        _enable_depth = max(0, _enable_depth - 1)
        if _enable_depth > 0:
            return
        _enabled = False
    threading.Lock = _true_lock_factory
    threading.RLock = _true_rlock_factory
    threading.Condition = _true_condition


def enabled() -> bool:
    return _enabled


def reset() -> None:
    """Forget recorded edges and violations (between tests)."""
    with _state_lock:
        _edges.clear()
        _violations.clear()


def violations() -> list[Violation]:
    with _state_lock:
        return list(_violations)


def current_edges() -> dict[tuple[str, str], str]:
    with _state_lock:
        return dict(_edges)


def assert_clean() -> None:
    """Raise :class:`LockOrderInversion` if any inversion (or
    self-deadlock) was observed since the last :func:`reset`."""
    vs = violations()
    if vs:
        raise LockOrderInversion(
            "lockdep witnessed %d violation(s):\n  " % len(vs)
            + "\n  ".join(v.render() for v in vs))
