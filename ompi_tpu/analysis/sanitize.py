"""Pass 4 — native plane under sanitizers (ASan/UBSan, TSan, cppcheck).

Builds ``native/src/dcn_sanity.cc`` — a standalone, Python-free soak
driver covering the shm-ring and framed-tcp transports (eager /
chunked / rendezvous, concurrent senders, the coll stream, the p2p
matcher, stats read-back) — against ``libtpudcn`` with the sanitizer
flags appended (``make SAN=… BUILD=build-<leg>``), then runs it.

Legs:

``asan``   ``-fsanitize=address,undefined`` — heap/stack corruption,
           UB (misaligned loads, signed overflow) in the ring codecs.
``tsan``   ``-fsanitize=thread`` — the lock/atomic discipline of the
           multi-threaded engine (reader thread + senders).  Not every
           toolchain ships libtsan; a missing one is a **logged skip**
           (an ``info`` finding with the reason), never a silent pass.
``cppcheck`` static C analysis of ``dcn.cc``/``shim.c`` when the
           ``cppcheck`` binary exists (config: ``native/cppcheck.cfg``
           suppressions); otherwise a logged skip.  The clang-tidy
           config (``native/.clang-tidy``) rides along for dev boxes
           with clang — tidy is NOT run here (needs a compile DB).

Findings: a failed build or a sanitizer report is ``error``; an
unavailable toolchain leg is ``info`` (visible in the report and the
human output, excluded from the pass/fail verdict).  Each leg's skip
reason quotes the probe failure so "it skipped" is diagnosable.
"""

from __future__ import annotations

import os
import re
import shutil
import subprocess
import tempfile
from pathlib import Path

from ompi_tpu.analysis.findings import SEV_ERROR, SEV_INFO, Finding

PASS = "sanitize"

#: leg name → SAN flags handed to the Makefile
LEGS = (
    ("asan", "-fsanitize=address,undefined -fno-sanitize-recover=all"),
    ("tsan", "-fsanitize=thread"),
)

#: sanitizer runtime knobs: abort on first report, no odr noise from
#: the duplicate-register probe pattern
_RUN_ENV = {
    "ASAN_OPTIONS": "halt_on_error=1:abort_on_error=0:exitcode=99",
    "UBSAN_OPTIONS": "halt_on_error=1:print_stacktrace=1",
    "TSAN_OPTIONS": "halt_on_error=1:exitcode=99:second_deadlock_stack=1",
}


def _run(cmd: list[str], cwd: Path, timeout: float,
         env: dict | None = None) -> tuple[int, str]:
    e = dict(os.environ)
    if env:
        e.update(env)
    try:
        p = subprocess.run(cmd, cwd=str(cwd), env=e, timeout=timeout,
                           stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                           text=True, errors="replace")
        return p.returncode, p.stdout or ""
    except subprocess.TimeoutExpired as te:
        out = te.stdout or b""
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        return 124, out + f"\n[timeout after {timeout}s]"
    except FileNotFoundError as fe:
        return 127, str(fe)


def _probe_flag(cxx: str, flags: str, tmpdir: Path) -> tuple[bool, str]:
    """Can this toolchain compile AND link a trivial program with the
    sanitizer flags?  (The compile succeeds but the link fails when the
    runtime lib — e.g. libtsan — is not installed.)"""
    probe = tmpdir / "san_probe.cc"
    probe.write_text("int main() { return 0; }\n")
    rc, out = _run([cxx, *flags.split(), "-o", str(tmpdir / "san_probe"),
                    str(probe)], tmpdir, timeout=60)
    if rc != 0:
        tail = "; ".join(out.strip().splitlines()[-2:]) or f"rc={rc}"
        return False, tail
    return True, ""


def _excerpt(out: str, limit: int = 700) -> str:
    """The interesting tail of a sanitizer/build log: from the first
    ERROR/WARNING marker if present, else the last lines."""
    m = re.search(r"(==\d+==\s*(ERROR|WARNING).*|runtime error:.*|"
                  r"dcn_sanity FAIL.*)", out)
    text = out[m.start():] if m else out
    text = text.strip()
    return text[-limit:] if len(text) > limit else text


def run(root: str | Path, files=None, legs=LEGS,
        timeout: float = 420.0) -> list[Finding]:
    """Build+run the sanitizer legs.  ``files`` accepted for driver
    symmetry.  Returns error findings for real failures and info
    findings for logged skips — a toolchain hole must be visible."""
    root = Path(root)
    native = root / "native"
    out: list[Finding] = []
    if not (native / "src" / "dcn_sanity.cc").exists():
        return [Finding(PASS, "sanitize-setup", "native/src/dcn_sanity.cc",
                        0, "", "sanity driver source missing", SEV_ERROR)]
    cxx = os.environ.get("CXX", "c++")
    make = shutil.which("make")
    if make is None:
        return [Finding(PASS, "sanitize-skip", "native/Makefile", 0, "",
                        "skipped: no `make` on PATH — cannot drive the "
                        "sanitizer builds", SEV_INFO)]
    build_root = native
    probe_dir = Path(tempfile.mkdtemp(prefix="tpucheck_san_"))
    for leg, flags in legs:
        build = f"build-{leg}"
        ok, why = _probe_flag(cxx, flags, probe_dir)
        if not ok:
            out.append(Finding(
                PASS, "sanitize-skip", "native/Makefile", 0, leg,
                f"{leg} leg skipped: toolchain cannot link {flags!r} "
                f"({why})", SEV_INFO))
            continue
        rc, log = _run([make, f"BUILD={build}", f"SAN={flags}",
                        f"{build}/dcn_sanity"], build_root, timeout)
        if rc != 0:
            out.append(Finding(
                PASS, "sanitize-build", "native/src/dcn.cc", 0, leg,
                f"{leg} build failed (rc={rc}): {_excerpt(log)}",
                SEV_ERROR))
            continue
        rc, log = _run([str(native / build / "dcn_sanity")], native,
                       timeout, env=_RUN_ENV)
        if rc != 0:
            out.append(Finding(
                PASS, "sanitize-report", "native/src/dcn.cc", 0, leg,
                f"{leg} run failed (rc={rc}): {_excerpt(log)}",
                SEV_ERROR))
        else:
            out.append(Finding(
                PASS, "sanitize-ok", "native/src/dcn.cc", 0, leg,
                f"{leg} leg clean ({flags}): dcn_sanity OK", SEV_INFO))
    # cppcheck leg (static, no build needed)
    cppcheck = shutil.which("cppcheck")
    if cppcheck is None:
        out.append(Finding(
            PASS, "sanitize-skip", "native/src/dcn.cc", 0, "cppcheck",
            "cppcheck leg skipped: no `cppcheck` binary on PATH",
            SEV_INFO))
    else:
        cfg = native / "cppcheck.cfg"
        cmd = [cppcheck, "--std=c++17", "--language=c++", "--quiet",
               "--enable=warning,portability",
               "--inline-suppr", "--error-exitcode=2",
               f"-I{native / 'include'}",
               str(native / "src" / "dcn.cc"),
               str(native / "src" / "shim.c")]
        if cfg.exists():
            cmd.insert(1, f"--suppressions-list={cfg}")
        rc, log = _run(cmd, native, timeout)
        if rc != 0:
            out.append(Finding(
                PASS, "sanitize-cppcheck", "native/src/dcn.cc", 0,
                "cppcheck", f"cppcheck reported (rc={rc}): {_excerpt(log)}",
                SEV_ERROR))
        else:
            out.append(Finding(
                PASS, "sanitize-ok", "native/src/dcn.cc", 0, "cppcheck",
                "cppcheck leg clean (warning,portability)", SEV_INFO))
    shutil.rmtree(probe_dir, ignore_errors=True)
    return out
