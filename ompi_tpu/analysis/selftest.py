"""tpucheck --selftest: seeded fixtures + the live-repo contract gate.

Two halves, mirroring ``tools/chaos.py --selftest``/``top.py
--selftest``:

1. **Seeded fixtures** — a throwaway mini-tree per pass carrying one
   known violation (unbounded spin without Deadline, an unregistered
   ``--mca`` var, a two-lock order cycle, a renamed
   ``TDCN_STAT_NAMES`` counter) next to a clean twin; each pass must
   flag exactly the seeded site and stay quiet on the twin.  The
   waiver round-trip (a matching waiver suppresses the finding; a
   stale waiver is itself reported) and the runtime lockdep witness
   (an observed order inversion raises) prove the reporting plumbing.
2. **The live repo** — the three static passes run against the real
   tree with the reviewed waivers applied; any unwaived error fails
   the selftest.  This is the line that makes tier-1 enforce the
   PR 1–6 contracts from PR 7 onward.

The fixture builders are importable (``tests/test_analysis.py`` uses
them directly); :func:`run_selftest` is the driver entry.
"""

from __future__ import annotations

import tempfile
import threading
from pathlib import Path

from ompi_tpu.analysis import findings as F
from ompi_tpu.analysis import invariants, lockorder, abidrift, lockdep

# -- fixture builders ----------------------------------------------------

_FIXTURE_VAR_PY = '''\
OBSERVABILITY_VARS = (
    ("trace", "", "enable", False, "fixture knob"),
)
ROBUSTNESS_VARS = ()
SERVING_VARS = ()
'''

_FIXTURE_SPIN_BAD = '''\
import time


def pump(ring):
    """Seeded violation: unbounded spin with no wait policy."""
    while True:
        if ring.poll():
            return ring.take()
        time.sleep(0.01)
'''

_FIXTURE_SPIN_GOOD = '''\
import time


def pump_bounded(ring, deadline):
    """Clean twin: the enclosing function consults a Deadline."""
    while True:
        if ring.poll():
            return ring.take()
        deadline.check()
        time.sleep(0.01)
'''

_FIXTURE_LOCK_CYCLE = '''\
import threading


class Engine:
    def __init__(self):
        self.lock_a = threading.Lock()
        self.lock_b = threading.Lock()

    def fwd(self):
        with self.lock_a:
            with self.lock_b:
                return 1

    def rev(self):
        with self.lock_b:
            with self.lock_a:
                return 2
'''

_FIXTURE_LOCK_CLEAN = '''\
import threading


class Orderly:
    def __init__(self):
        self.lock_x = threading.Lock()
        self.lock_y = threading.Lock()

    def one(self):
        with self.lock_x:
            with self.lock_y:
                return 1

    def two(self):
        with self.lock_x:
            with self.lock_y:
                return 2
'''

#: the real v1 counter tail (metrics/core.py order) — fixtures carry
#: the full frozen prefix so append-only checks behave as on head
_COUNTERS = ("doorbells", "stall_ns", "ring_stall_ns", "ring_stalls",
             "ring_hwm", "cts_wait_ns", "cts_waits", "rndv_depth",
             "rndv_hwm", "slot_waits", "eager_msgs", "eager_bytes",
             "chunked_msgs", "chunked_bytes", "rndv_msgs", "rndv_bytes",
             "delivered", "unexpected_hwm")


def _fixture_dcn_cc(names: tuple[str, ...]) -> str:
    joined = ",".join(("version",) + names)
    quoted = "\n    ".join(f'"{part},"' for part in joined.split(",")[:-1])
    return (f'static const char *TDCN_STAT_NAMES =\n    {quoted}\n'
            f'    "{joined.rsplit(",", 1)[1]}";\n')


def _fixture_metrics_core(names: tuple[str, ...]) -> str:
    rows = "\n".join(f'    "{n}",' for n in names)
    return f"NATIVE_COUNTERS = (\n{rows}\n)\n"


#: the real v1 wire-context field table (trace/causal.py order)
_CTX_FIELDS = ("v", "comm", "op", "seq", "hop")


def _fixture_causal_py(fields: tuple[str, ...],
                       pvars: tuple[str, ...]) -> str:
    frows = ", ".join(f'"{f}"' for f in fields)
    prows = ", ".join(f'"{p}"' for p in pvars)
    return (f"CTX_VERSION = 1\nCTX_FIELDS = ({frows})\n"
            f"PVARS = ({prows})\n")


def _fixture_ctx_cc(fields: tuple[str, ...]) -> str:
    joined = ",".join(fields)
    return ('static const char *TDCN_TRACE_CTX_FIELDS =\n'
            f'    "{joined}";\n')


def build_fixture_tree(root: Path, *, spin: str = "bad",
                       mca_ref: str = "trace_enable",
                       locks: str = "cycle",
                       rename_counter: str | None = None,
                       stats_key: str | None = None,
                       ctx_fields: tuple[str, ...] | None = None,
                       ctx_c_fields: tuple[str, ...] | None = None,
                       causal_pvars: tuple[str, ...] | None = None) -> Path:
    """Materialize a seeded mini-repo under ``root``.  Knobs select the
    violation (or its clean twin) per pass:

    * ``spin``: "bad" → unbounded spin in dcn scope; "good" → Deadline.
    * ``mca_ref``: the var name the fixture README references.
    * ``locks``: "cycle" → opposite-order pair; "clean" → same order.
    * ``rename_counter``: rename this NATIVE_COUNTERS name on the C
      side only (ABI drift); None → both sides agree.
    * ``stats_key``: write a dcn/device.py whose STATS_KEYS carries
      this counter name (provider-merge-drift when it is not in
      NATIVE_COUNTERS); None → no device.py.
    * ``ctx_fields``/``ctx_c_fields``: write a trace/causal.py (and a
      TDCN_TRACE_CTX_FIELDS block in the fixture dcn.cc) carrying
      these wire-context field tables — disagree/reorder to seed
      wire-ctx-drift/append-only; None → no causal fixture.
    * ``causal_pvars``: PVARS tuple for the causal fixture (the
      pvar-name-lint input); defaults to a clean set.
    """
    (root / "ompi_tpu" / "core").mkdir(parents=True, exist_ok=True)
    (root / "ompi_tpu" / "dcn").mkdir(parents=True, exist_ok=True)
    (root / "ompi_tpu" / "metrics").mkdir(parents=True, exist_ok=True)
    (root / "native" / "src").mkdir(parents=True, exist_ok=True)
    (root / "ompi_tpu" / "core" / "var.py").write_text(_FIXTURE_VAR_PY)
    (root / "ompi_tpu" / "dcn" / "pump.py").write_text(
        _FIXTURE_SPIN_BAD if spin == "bad" else _FIXTURE_SPIN_GOOD)
    (root / "ompi_tpu" / "dcn" / "tcp.py").write_text(
        _FIXTURE_LOCK_CYCLE if locks == "cycle" else _FIXTURE_LOCK_CLEAN)
    (root / "ompi_tpu" / "metrics" / "core.py").write_text(
        _fixture_metrics_core(_COUNTERS))
    c_names = _COUNTERS
    if rename_counter:
        c_names = tuple(f"{n}_v2" if n == rename_counter else n
                        for n in _COUNTERS)
    cc_text = _fixture_dcn_cc(c_names)
    if ctx_fields is not None or ctx_c_fields is not None:
        cc_text += _fixture_ctx_cc(ctx_c_fields or ctx_fields
                                   or _CTX_FIELDS)
        (root / "ompi_tpu" / "trace").mkdir(parents=True, exist_ok=True)
        (root / "ompi_tpu" / "trace" / "causal.py").write_text(
            _fixture_causal_py(ctx_fields or _CTX_FIELDS,
                               causal_pvars or ("records", "sends")))
    (root / "native" / "src" / "dcn.cc").write_text(cc_text)
    if stats_key is not None:
        (root / "ompi_tpu" / "dcn" / "device.py").write_text(
            f'STATS_KEYS = ("{stats_key}",)\n\n\n'
            "class Plane:\n"
            "    def __init__(self):\n"
            "        self.stats = {k: 0 for k in STATS_KEYS}\n")
    readme = (f"Fixture repo.  Enable with ``--mca {mca_ref} 1``.\n"
              "Counters: " + ", ".join(f"`{n}`" for n in _COUNTERS)
              + "\n")
    if ctx_fields is not None or ctx_c_fields is not None:
        # document the DEFAULT pvar set so only a seeded odd name
        # trips the README half of pvar-name-lint
        readme += ("Causal pvars: `trace_causal_records`, "
                   "`trace_causal_sends`\n")
    (root / "README.md").write_text(readme)
    return root


# -- selftest legs -------------------------------------------------------

def _expect(log: list[str], ok, what: str) -> bool:
    ok = bool(ok)
    log.append(f"  {'ok' if ok else 'FAIL'}: {what}")
    return ok


def _leg_invariants(tmp: Path, log: list[str]) -> bool:
    bad = build_fixture_tree(tmp / "inv_bad")
    fs = invariants.run(bad)
    rules = {f.rule for f in fs}
    ok = _expect(log, "unbounded-spin" in rules,
                 "seeded Deadline-less spin detected")
    spin = [f for f in fs if f.rule == "unbounded-spin"]
    ok &= _expect(log, any(f.file == "ompi_tpu/dcn/pump.py"
                           and f.symbol == "pump" for f in spin),
                  "spin finding anchored at pump()")
    good = build_fixture_tree(tmp / "inv_good", spin="good")
    fs2 = invariants.run(good)
    ok &= _expect(log, not any(f.rule == "unbounded-spin" for f in fs2),
                  "Deadline twin stays clean")
    mca = build_fixture_tree(tmp / "inv_mca", spin="good",
                             mca_ref="bogus_fixture_knob")
    fs3 = invariants.run(mca)
    ok &= _expect(log,
                  any(f.rule == "mca-unregistered"
                      and "bogus_fixture_knob" in f.message for f in fs3),
                  "unregistered --mca reference detected")
    return ok


def _leg_lockorder(tmp: Path, log: list[str]) -> bool:
    bad = build_fixture_tree(tmp / "lk_bad", spin="good")
    fs = lockorder.run(bad)
    cyc = [f for f in fs if f.rule == "lock-cycle"]
    ok = _expect(log, len(cyc) == 1, "seeded two-lock cycle detected")
    if cyc:
        ok &= _expect(log, "Engine.lock_a" in cyc[0].symbol
                      and "Engine.lock_b" in cyc[0].symbol,
                      "cycle names both lock classes")
    clean = build_fixture_tree(tmp / "lk_clean", spin="good",
                               locks="clean")
    fs2 = lockorder.run(clean)
    ok &= _expect(log, not any(f.rule == "lock-cycle" for f in fs2),
                  "consistent-order twin stays clean")
    return ok


def _leg_abidrift(tmp: Path, log: list[str]) -> bool:
    bad = build_fixture_tree(tmp / "abi_bad", spin="good",
                             rename_counter="delivered")
    fs = abidrift.check_stat_names(bad)
    rules = {f.rule for f in fs}
    ok = _expect(log, "stat-names-drift" in rules,
                 "renamed TDCN_STAT_NAMES entry detected as drift")
    ok &= _expect(log, "stat-append-only" in rules,
                  "rename inside the frozen v1 prefix flagged append-only")
    good = build_fixture_tree(tmp / "abi_good", spin="good")
    fs2 = abidrift.check_stat_names(good)
    ok &= _expect(log, not fs2, "agreeing tables stay clean")
    # provider-merge drift: a transport counter outside NATIVE_COUNTERS
    # would be silently dropped by the merge — seeded bad + clean twin
    pm_bad = build_fixture_tree(tmp / "abi_pm_bad", spin="good",
                                stats_key="bogus_counter")
    fs3 = abidrift.check_provider_merge(pm_bad)
    ok &= _expect(log,
                  any(f.rule == "provider-merge-drift"
                      and f.symbol == "bogus_counter" for f in fs3),
                  "unmerged transport counter detected")
    pm_good = build_fixture_tree(tmp / "abi_pm_good", spin="good",
                                 stats_key="delivered")
    fs4 = abidrift.check_provider_merge(pm_good)
    ok &= _expect(log, not fs4, "schema-covered counter stays clean")
    # causal wire-context mirror: a field renamed on the C side only
    # is drift; a reorder inside the frozen v1 prefix is append-only
    # breakage; agreeing tables stay clean
    cx_bad = build_fixture_tree(
        tmp / "abi_cx_bad", spin="good",
        ctx_fields=("v", "comm", "op", "seq", "hop"),
        ctx_c_fields=("v", "comm", "op", "seq", "hopidx"))
    fs5 = abidrift.check_trace_ctx(cx_bad)
    rules5 = {f.rule for f in fs5}
    ok &= _expect(log, "wire-ctx-drift" in rules5,
                  "renamed C ctx field detected as wire-ctx drift")
    ok &= _expect(log, "wire-ctx-append-only" in rules5,
                  "rename inside the frozen ctx prefix flagged "
                  "append-only")
    cx_good = build_fixture_tree(
        tmp / "abi_cx_good", spin="good",
        ctx_fields=("v", "comm", "op", "seq", "hop", "extra"),
        ctx_c_fields=("v", "comm", "op", "seq", "hop", "extra"))
    fs6 = abidrift.check_trace_ctx(cx_good)
    ok &= _expect(log, not fs6,
                  "agreeing ctx tables (appended tail) stay clean")
    # pvar name lint: a malformed causal pvar segment + one missing
    # from the README catalog; the default set stays clean
    pv_bad = build_fixture_tree(
        tmp / "abi_pv_bad", spin="good",
        ctx_fields=("v", "comm", "op", "seq", "hop"),
        causal_pvars=("records", "Bad-Name"))
    fs7 = abidrift.check_causal_pvars(pv_bad)
    ok &= _expect(log,
                  any(f.rule == "pvar-name-lint"
                      and "Bad-Name" in f.symbol for f in fs7),
                  "malformed trace_causal_* pvar name flagged")
    pv_good = build_fixture_tree(
        tmp / "abi_pv_good", spin="good",
        ctx_fields=("v", "comm", "op", "seq", "hop"))
    fs8 = abidrift.check_causal_pvars(pv_good)
    ok &= _expect(log, not fs8, "default causal pvar set stays clean")
    return ok


def _leg_waivers(tmp: Path, log: list[str]) -> bool:
    bad = build_fixture_tree(tmp / "wv", )
    fs = invariants.run(bad)
    wv_text = (
        '[[waiver]]\npass = "invariants"\nrule = "unbounded-spin"\n'
        'file = "ompi_tpu/dcn/pump.py"\nreason = "fixture: waived"\n\n'
        '[[waiver]]\npass = "invariants"\nrule = "hardcoded-timeout"\n'
        'file = "ompi_tpu/dcn/nothere.py"\nreason = "fixture: stale"\n')
    wpath = tmp / "wv" / "waivers.toml"
    wpath.write_text(wv_text)
    waivers = F.load_waivers(wpath)
    merged = F.apply_waivers(fs, waivers)
    spin = [f for f in merged if f.rule == "unbounded-spin"]
    ok = _expect(log, spin and all(f.waived for f in spin),
                 "matching waiver suppresses the finding")
    ok &= _expect(log,
                  any(f.rule == "stale-waiver" for f in merged),
                  "no-match waiver reported stale")
    return ok


def _leg_lockdep(log: list[str]) -> bool:
    lockdep.enable()
    try:
        lockdep.reset()
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        vs = lockdep.violations()
        ok = _expect(log, any(v.kind == "inversion" for v in vs),
                     "runtime witness records the AB/BA inversion")
        raised = False
        try:
            lockdep.assert_clean()
        except lockdep.LockOrderInversion:
            raised = True
        ok &= _expect(log, raised, "assert_clean raises on inversion")
        lockdep.reset()
        with a:
            with b:
                pass
        with a:
            with b:
                pass
        ok &= _expect(log, not lockdep.violations(),
                      "consistent order stays clean")
    finally:
        lockdep.disable()
        lockdep.reset()
    return ok


def _leg_live_repo(repo: Path, log: list[str]) -> bool:
    report = F.Report(str(repo))
    for name in ("invariants", "lockorder", "abidrift"):
        mod = {"invariants": invariants, "lockorder": lockorder,
               "abidrift": abidrift}[name]
        report.extend(name, mod.run(repo))
    waivers = F.load_waivers(repo / "ompi_tpu" / "analysis" / "waivers.toml")
    report.findings = F.apply_waivers(report.findings, waivers)
    bad = report.unwaived(F.SEV_ERROR)
    ok = _expect(log, not bad,
                 f"live repo: 3 static passes, {len(report.findings)} "
                 f"findings, {sum(1 for f in report.findings if f.waived)} "
                 "waived, 0 unwaived errors")
    for f in bad[:10]:
        log.append("    " + f.render()[:160])
    return ok


def run_selftest(repo_root: str | Path) -> tuple[bool, list[str]]:
    """All selftest legs; returns (ok, human-readable log lines)."""
    repo = Path(repo_root)
    log: list[str] = []
    ok = True
    with tempfile.TemporaryDirectory(prefix="tpucheck_selftest_") as td:
        tmp = Path(td)
        log.append("fixture: invariant linter")
        ok &= _leg_invariants(tmp, log)
        log.append("fixture: lock-order analyzer")
        ok &= _leg_lockorder(tmp, log)
        log.append("fixture: ABI drift checker")
        ok &= _leg_abidrift(tmp, log)
        log.append("fixture: waiver round-trip")
        ok &= _leg_waivers(tmp, log)
        log.append("runtime: lockdep witness")
        ok &= _leg_lockdep(log)
        log.append("live repo: contract gate")
        ok &= _leg_live_repo(repo, log)
    return ok, log
