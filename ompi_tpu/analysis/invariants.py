"""Pass 1 — the invariant linter (AST checks over ``ompi_tpu/``).

Encodes the cross-cutting contracts PRs 1–6 shipped, so they are
machine-checked instead of reviewer-remembered:

``unbounded-spin``
    A ``while True``-style loop in a transport/threaded module that
    sleeps/polls without the enclosing function consulting a
    :class:`~ompi_tpu.core.var.Deadline` (or an Event/Condition wait
    that carries its own bound).  The exact failure class PR 3's chaos
    soak had to find dynamically: a dead peer turns the spin into a
    permanent wedge.

``hardcoded-timeout``
    A numeric literal ≥ ``LONG_WAIT_S`` used as a blocking-wait bound
    in the DCN/p2p paths.  Long waits must come from the registered
    ``dcn_*_timeout``/``ft_*`` vars (``Deadline.for_timeout``) so
    operators can tune them; short literals (poll quanta, control-
    frame fail-fast bounds) are fine.

``mca-unregistered``
    A ``--mca <name>``/``OMPI_MCA_<name>`` reference in code, tests,
    docs, or examples whose name no registration site defines.

``mca-dead-registration``
    A var in the central ``core/var.py`` tables that nothing outside
    ``core/var.py`` references — a knob nobody can discover a use for.

``ungated-hook``
    A call from a hot-path module into a gated subsystem (trace /
    metrics / faultsim) that neither tests the subsystem's module
    bool at the call site nor targets a self-gated hook function.
    The one-bool-off-path contract: observability must cost one
    boolean test when disabled.

``untyped-escalation``
    ``raise RuntimeError``/``raise Exception`` in the transport
    escalation paths (``dcn/tcp.py``, ``dcn/native.py``,
    ``dcn/collops.py``) — failures there must raise the typed errors
    (``MPIProcFailedError`` etc.) that ULFM recovery dispatches on.
"""

from __future__ import annotations

import ast
from pathlib import Path

from ompi_tpu.analysis.findings import SEV_ERROR, Finding
from ompi_tpu.analysis.repo import (
    const_str,
    mca_references,
    parse_py,
    registered_var_names,
    central_var_tables,
    rel,
    walk,
)

PASS = "invariants"

#: modules whose blocking waits must ride Deadline (the transport and
#: threaded planes)
SPIN_SCOPE = (
    "ompi_tpu/dcn", "ompi_tpu/p2p", "ompi_tpu/serve", "ompi_tpu/ft",
    "ompi_tpu/metrics/live.py", "ompi_tpu/coll/sync.py",
    "ompi_tpu/boot/kvs.py",
)

#: modules where long literal timeouts are contract violations
TIMEOUT_SCOPE = ("ompi_tpu/dcn", "ompi_tpu/p2p")

#: seconds at which a literal bound stops being a poll quantum and
#: becomes a policy decision that belongs in a registered var
LONG_WAIT_S = 60

#: the named escalation paths (tentpole list) — device.py joined at
#: PR 18 when its waits gained ULFM escalation (plane-health failover)
ESCALATION_FILES = (
    "ompi_tpu/dcn/tcp.py", "ompi_tpu/dcn/native.py",
    "ompi_tpu/dcn/collops.py", "ompi_tpu/dcn/device.py",
)

#: hot-path packages whose calls into gated subsystems are checked
HOT_SCOPE = ("ompi_tpu/dcn", "ompi_tpu/p2p", "ompi_tpu/coll",
             "ompi_tpu/api", "ompi_tpu/mesh", "ompi_tpu/serve")

#: gated subsystem → package path fragment.  A module inside one of
#: these packages carries the one-bool gate (``_enabled``).
GATED_SUBSYSTEMS = {
    "trace": "ompi_tpu/trace",
    "metrics": "ompi_tpu/metrics",
    "faultsim": "ompi_tpu/faultsim",
}

#: subsystem functions that are lifecycle/config surface, not hot-path
#: hooks — callable ungated (init/finalize/job boundaries/tests, never
#: per-message).  start_publisher/stop_publisher gate themselves on the
#: telemetry var+env; set_proc/set_job/reset_crash_latch are one global
#: store each, called once per init/job.
LIFECYCLE_FNS = frozenset({
    "enable", "disable", "enabled", "sync_from_store", "register_vars",
    "install", "reset", "configure", "start", "stop", "shutdown",
    "set_proc", "start_publisher", "stop_publisher", "reset_crash_latch",
    "set_job",
})

_GATE_TOKENS = ("_enabled", "enabled()")


class _Parented(ast.NodeVisitor):
    """Annotate nodes with parents + enclosing function qualname."""

    def __init__(self, tree: ast.Module):
        self.parents: dict[ast.AST, ast.AST] = {}
        stack: list[ast.AST] = [tree]
        while stack:
            node = stack.pop()
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
                stack.append(child)

    def qualname(self, node: ast.AST) -> str:
        parts: list[str] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts))

    def enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None


def _in_scope(relpath: str, scope: tuple[str, ...]) -> bool:
    return any(relpath == s or relpath.startswith(s.rstrip("/") + "/")
               for s in scope)


def _mentions_gate(node: ast.AST) -> bool:
    try:
        src = ast.unparse(node)
    except Exception:  # pragma: no cover - unparse failure
        return False
    return any(tok in src for tok in _GATE_TOKENS)


def _loop_is_unbounded(node: ast.While) -> bool:
    """``while True`` / ``while 1`` (constant-true) loops only; a
    conditioned loop carries its own exit."""
    t = node.test
    return isinstance(t, ast.Constant) and bool(t.value)


def _calls_in(node: ast.AST) -> list[ast.Call]:
    return [n for n in ast.walk(node) if isinstance(n, ast.Call)]


def _call_name(call: ast.Call) -> str:
    """Dotted best-effort name of the callee."""
    f = call.func
    parts: list[str] = []
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    return ".".join(reversed(parts))


# -- rule: unbounded-spin -----------------------------------------------

_SLEEPY = ("sleep",)


def check_spins(root: Path, files: list[Path]) -> list[Finding]:
    out: list[Finding] = []
    for path in files:
        relpath = rel(root, path)
        if not _in_scope(relpath, SPIN_SCOPE):
            continue
        tree = parse_py(path)
        if tree is None:
            continue
        par = _Parented(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.While) or not _loop_is_unbounded(node):
                continue
            sleeps = [c for c in _calls_in(node)
                      if _call_name(c).split(".")[-1] in _SLEEPY]
            if not sleeps:
                continue
            fn = par.enclosing_function(node)
            ctx = fn if fn is not None else node
            src = ast.unparse(ctx)
            if "Deadline" in src or "deadline" in src:
                continue  # bounded: the function consults the policy
            out.append(Finding(
                PASS, "unbounded-spin", relpath, node.lineno,
                par.qualname(node),
                "`while True` + sleep with no Deadline in the enclosing "
                "function — a dead peer turns this into a permanent wedge "
                "(every blocking DCN wait must ride core.var.Deadline)",
                SEV_ERROR))
    return out


# -- rule: hardcoded-timeout --------------------------------------------

_TIMEOUT_KWARGS = ("timeout", "timeout_s", "seconds")
_TIMEOUT_CALLS = ("settimeout", "Deadline", "wait", "join", "acquire")


def check_hardcoded_timeouts(root: Path, files: list[Path]) -> list[Finding]:
    out: list[Finding] = []
    for path in files:
        relpath = rel(root, path)
        if not _in_scope(relpath, TIMEOUT_SCOPE):
            continue
        tree = parse_py(path)
        if tree is None:
            continue
        par = _Parented(tree)
        for call in (n for n in ast.walk(tree) if isinstance(n, ast.Call)):
            name = _call_name(call).split(".")[-1]
            suspects: list[ast.AST] = []
            for kw in call.keywords:
                if kw.arg in _TIMEOUT_KWARGS:
                    suspects.append(kw.value)
            if name in _TIMEOUT_CALLS and call.args:
                suspects.append(call.args[0])
            for s in suspects:
                if (isinstance(s, ast.Constant)
                        and isinstance(s.value, (int, float))
                        and not isinstance(s.value, bool)
                        and s.value >= LONG_WAIT_S):
                    out.append(Finding(
                        PASS, "hardcoded-timeout", relpath, call.lineno,
                        par.qualname(call),
                        f"literal {s.value}s bound on a blocking wait "
                        f"({name}) — long waits must come from the "
                        "registered dcn_*_timeout vars "
                        "(Deadline.for_timeout), not constants",
                        SEV_ERROR))
    return out


# -- rules: mca-unregistered / mca-dead-registration --------------------

def _local_registrations(tree: ast.Module) -> set[str]:
    """Var names a file registers itself via literal ``*.register(fw,
    comp, name, …)`` calls — tests/tools register scratch vars and then
    reference them; those are not drift."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "register" and len(node.args) >= 3):
            parts = [const_str(a) for a in node.args[:3]]
            if all(p is not None for p in parts):
                names.add("_".join(p for p in parts if p))
    return names


def _plausible_var_name(name: str) -> bool:
    """Heuristic separating real knob references from prose/placeholder
    matches ("--mca var listings", "--mca k v", "btl_tcp_*"): every
    registered knob family here is multi-word snake_case, so a name
    must carry an internal underscore and end on an alnum."""
    return "_" in name.strip("_") and not name.endswith("_")


def check_mca_vars(root: Path, files: list[Path] | None = None,
                   doc_files: list[Path] | None = None,
                   check_dead: bool = True) -> list[Finding]:
    out: list[Finding] = []
    known = registered_var_names(root)
    scan = list(files or [])
    scan += doc_files if doc_files is not None else walk(
        root, (".md",)) + walk(root, (".py",), subdirs=("tests", "tools",
                                                        "examples"))
    # de-dup (files may overlap the doc walk)
    seen_paths: set[Path] = set()
    ref_text: list[str] = []
    for path in scan:
        if path in seen_paths:
            continue
        seen_paths.add(path)
        relpath = rel(root, path)
        if _in_scope(relpath, ("ompi_tpu/analysis",)):
            continue  # the checker's own docstrings/regex sources
        try:
            text = path.read_text()
        except (OSError, UnicodeDecodeError):
            continue
        ref_text.append(text)
        local = known
        if path.suffix == ".py":
            tree = parse_py(path)
            if tree is not None:
                extra = _local_registrations(tree) - known
                if extra:
                    local = known | extra
        for name, lineno in mca_references(text):
            if name not in local and _plausible_var_name(name):
                out.append(Finding(
                    PASS, "mca-unregistered", relpath, lineno, "",
                    f"--mca var {name!r} is referenced here but no "
                    "registration site defines it (central tables, "
                    "store.register literals, component priority/"
                    "selection vars)",
                    SEV_ERROR))
    # dead registrations: central-table vars nothing references
    if not check_dead:
        return out
    blob = "\n".join(ref_text)
    for table, names in central_var_tables(root).items():
        for name in names:
            if name not in blob:
                out.append(Finding(
                    PASS, "mca-dead-registration",
                    "ompi_tpu/core/var.py", 0, table,
                    f"central registration {name!r} ({table}) is "
                    "referenced nowhere outside core/var.py — dead knob "
                    "or missing docs",
                    SEV_ERROR))
    return out


# -- rule: ungated-hook -------------------------------------------------

def _subsystem_of(relpath: str) -> str | None:
    for name, frag in GATED_SUBSYSTEMS.items():
        if _in_scope(relpath, (frag,)):
            return name
    return None


def _collect_gated_functions(root: Path) -> dict[str, dict[str, bool]]:
    """subsystem → {function name: self_gated?} over its modules."""
    table: dict[str, dict[str, bool]] = {k: {} for k in GATED_SUBSYSTEMS}
    for name, frag in GATED_SUBSYSTEMS.items():
        for path in walk(root, (".py",), subdirs=(frag,)):
            tree = parse_py(path)
            if tree is None:
                continue
            for node in tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    gated = _mentions_gate(node)
                    prev = table[name].get(node.name)
                    table[name][node.name] = bool(prev) or gated
    return table


def _import_aliases(tree: ast.Module) -> dict[str, str]:
    """local alias → subsystem name, for ompi_tpu.{trace,metrics,
    faultsim} imports (module-level and function-local)."""
    aliases: dict[str, str] = {}
    sub_by_pkg = {f"ompi_tpu.{k}": k for k in GATED_SUBSYSTEMS}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                for pkg, sub in sub_by_pkg.items():
                    if a.name == pkg or a.name.startswith(pkg + "."):
                        aliases[(a.asname or a.name).split(".")[0]] = sub
        elif isinstance(node, ast.ImportFrom) and node.module:
            mod = node.module
            if mod == "ompi_tpu":
                for a in node.names:
                    if a.name in GATED_SUBSYSTEMS:
                        aliases[a.asname or a.name] = a.name
                continue
            for pkg, sub in sub_by_pkg.items():
                if mod == pkg or mod.startswith(pkg + "."):
                    for a in node.names:
                        aliases[a.asname or a.name] = sub
    return aliases


def _latch_names(fn: ast.AST | None) -> set[str]:
    """Names assigned the t0-latch idiom in this function:
    ``t0 = trace.now() if _trace._enabled else 0`` — a later ``if t0:``
    then dominates the hook call with the gate, one hop removed."""
    out: set[str] = set()
    if fn is None:
        return out
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.IfExp)
                and _mentions_gate(node.value.test)):
            out.add(node.targets[0].id)
    return out


def _test_is_latch(test: ast.AST, latches: set[str]) -> bool:
    return isinstance(test, ast.Name) and test.id in latches


def _guarded(node: ast.AST, par: _Parented) -> bool:
    """Is this call dominated by a gate test (if/ifexp/and-chain), or
    by an ``if <latch>:`` where the latch variable was assigned from a
    gate-conditioned IfExp (the hot-path t0-latch idiom)?"""
    latches = _latch_names(par.enclosing_function(node))
    cur: ast.AST | None = node
    while cur is not None:
        parent = par.parents.get(cur)
        if isinstance(parent, ast.If) and (
                _mentions_gate(parent.test)
                or _test_is_latch(parent.test, latches)):
            return True
        if isinstance(parent, ast.IfExp):
            if cur is not parent.orelse and _mentions_gate(parent.test):
                return True
        if isinstance(parent, ast.BoolOp) and isinstance(parent.op, ast.And):
            idx = parent.values.index(cur) if cur in parent.values else 0
            if any(_mentions_gate(v) for v in parent.values[:idx]):
                return True
        cur = parent
    return False


def _caller_early_gated(fn: ast.AST | None) -> bool:
    """The enclosing function itself starts with an `if not <gate>:
    return` bail-out — everything after is implicitly gated."""
    if fn is None:
        return False
    body = getattr(fn, "body", [])
    for stmt in body[:4]:
        if (isinstance(stmt, ast.If) and _mentions_gate(stmt.test)
                and any(isinstance(s, ast.Return) for s in stmt.body)):
            return True
    return False


def check_gated_hooks(root: Path, files: list[Path]) -> list[Finding]:
    out: list[Finding] = []
    gated_fns = _collect_gated_functions(root)
    for path in files:
        relpath = rel(root, path)
        if not _in_scope(relpath, HOT_SCOPE) or _subsystem_of(relpath):
            continue
        tree = parse_py(path)
        if tree is None:
            continue
        par = _Parented(tree)
        aliases = _import_aliases(tree)
        if not aliases:
            continue
        for call in (n for n in ast.walk(tree) if isinstance(n, ast.Call)):
            f = call.func
            sub = fname = None
            if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                    and f.value.id in aliases):
                sub, fname = aliases[f.value.id], f.attr
            elif isinstance(f, ast.Name) and f.id in aliases:
                # direct `from ompi_tpu.trace.core import emit` style
                sub, fname = aliases[f.id], f.id
            if sub is None or fname is None:
                continue
            if fname in LIFECYCLE_FNS or fname.startswith("register"):
                continue
            known = gated_fns.get(sub, {})
            if fname in known and known[fname]:
                continue  # self-gated hook: tests the bool inside
            if _guarded(call, par):
                continue
            if _caller_early_gated(par.enclosing_function(call)):
                continue
            if fname not in known:
                continue  # not a function we can classify (class/attr)
            out.append(Finding(
                PASS, "ungated-hook", relpath, call.lineno,
                par.qualname(call),
                f"call into gated subsystem '{sub}' ({fname}) with no "
                "module-bool test at the call site and no gate inside "
                "the hook — breaks the one-bool-off-path contract",
                SEV_ERROR))
    return out


# -- rule: untyped-escalation -------------------------------------------

_BARE_RAISES = ("RuntimeError", "Exception")


def check_escalations(root: Path, files: list[Path]) -> list[Finding]:
    out: list[Finding] = []
    for path in files:
        relpath = rel(root, path)
        if relpath not in ESCALATION_FILES:
            continue
        tree = parse_py(path)
        if tree is None:
            continue
        par = _Parented(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            name = None
            if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
                name = exc.func.id
            elif isinstance(exc, ast.Name):
                name = exc.id
            if name in _BARE_RAISES:
                out.append(Finding(
                    PASS, "untyped-escalation", relpath, node.lineno,
                    par.qualname(node),
                    f"raise {name} in a transport escalation path — must "
                    "raise the typed errors (MPIProcFailedError / "
                    "DeadlineExpiredError …) ULFM recovery dispatches on",
                    SEV_ERROR))
    return out


def run(root: str | Path, files: list[Path] | None = None,
        mca_docs: bool = True) -> list[Finding]:
    """Run the invariant linter.  ``files`` overrides the walk (fixture
    trees in --selftest); ``mca_docs=False`` skips the docs/tests var
    scan (the --fast pre-commit path)."""
    root = Path(root)
    files = files if files is not None else walk(root, (".py",),
                                                subdirs=("ompi_tpu",))
    out: list[Finding] = []
    out += check_spins(root, files)
    out += check_hardcoded_timeouts(root, files)
    out += check_gated_hooks(root, files)
    out += check_escalations(root, files)
    if mca_docs:
        out += check_mca_vars(root, files)
    else:
        # --fast: no docs/tests walk, and without it the "referenced
        # nowhere" dead-registration evidence is incomplete — skip both
        out += check_mca_vars(root, files, doc_files=[], check_dead=False)
    return out
