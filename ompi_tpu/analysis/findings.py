"""Findings, the machine-readable report, and the reviewed waiver file.

A :class:`Finding` is one contract violation located at (file, line,
symbol).  Every pass returns a flat list; the driver merges them, maps
the reviewed waivers over them (:func:`apply_waivers`), and emits one
JSON report — the single machine-readable artifact CI and pre-commit
consume.

Waivers live in ``ompi_tpu/analysis/waivers.toml``.  The file is TOML
(array-of-tables ``[[waiver]]``), parsed here by a dependency-free
subset reader because the box's Python (3.10) predates ``tomllib`` —
the subset (tables, string/int/bool scalars, comments) is exactly what
the waiver grammar needs.  Each waiver must name the pass, the rule,
the file, and a one-line ``reason``; ``symbol``/``contains`` narrow
the match.  Line numbers are deliberately NOT part of the match key —
they drift with every edit and would rot the file.

A waiver that matches nothing is itself reported (``stale-waiver``):
the reviewed-exception file must not accrete dead entries.
"""

from __future__ import annotations

import json
import re
from dataclasses import asdict, dataclass, field
from pathlib import Path

#: severities, in escalation order
SEV_INFO = "info"      # logged context (e.g. a sanitizer leg skipped)
SEV_WARN = "warn"      # suspicious but not contract-breaking
SEV_ERROR = "error"    # contract violation — fails the check unless waived


@dataclass
class Finding:
    """One located contract violation (or logged note)."""

    pass_name: str          # invariants | lockorder | abidrift | sanitize
    rule: str               # kebab-case rule slug, stable across releases
    file: str               # repo-relative path ("" for repo-wide findings)
    line: int               # 1-based; 0 when the finding is not line-anchored
    symbol: str             # enclosing function/class qualname ("" if none)
    message: str
    severity: str = SEV_ERROR
    waived: bool = False
    waiver_reason: str = ""

    def key(self) -> str:
        return f"{self.pass_name}:{self.rule}:{self.file}:{self.symbol or self.line}"

    def render(self) -> str:
        loc = self.file or "<repo>"
        if self.line:
            loc += f":{self.line}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        tag = " (waived: " + self.waiver_reason + ")" if self.waived else ""
        return f"{self.severity:<5} {self.pass_name}/{self.rule} {loc}{sym}: {self.message}{tag}"


@dataclass
class Waiver:
    """One reviewed exception.  ``pass_name``+``rule``+``file`` are the
    match key; ``symbol``/``contains`` narrow it; ``reason`` is the
    mandatory one-line justification."""

    pass_name: str
    rule: str
    file: str
    reason: str
    symbol: str = ""
    contains: str = ""
    hits: int = field(default=0, compare=False)

    def matches(self, f: Finding) -> bool:
        if f.pass_name != self.pass_name or f.rule != self.rule:
            return False
        if self.file and f.file != self.file:
            return False
        if self.symbol and self.symbol not in (f.symbol or ""):
            return False
        if self.contains and self.contains not in f.message:
            return False
        return True


# -- minimal TOML subset reader -----------------------------------------

_KV_RE = re.compile(r"^([A-Za-z_][A-Za-z0-9_-]*)\s*=\s*(.+)$")


def _parse_scalar(raw: str, path: str, lineno: int):
    raw = raw.strip()
    if raw.startswith('"'):
        m = re.match(r'^"((?:[^"\\]|\\.)*)"\s*(?:#.*)?$', raw)
        if not m:
            raise ValueError(f"{path}:{lineno}: unterminated string")
        return m.group(1).replace('\\"', '"').replace("\\\\", "\\")
    if raw.startswith("'"):
        m = re.match(r"^'([^']*)'\s*(?:#.*)?$", raw)
        if not m:
            raise ValueError(f"{path}:{lineno}: unterminated string")
        return m.group(1)
    raw = raw.split("#", 1)[0].strip()
    if raw in ("true", "false"):
        return raw == "true"
    try:
        return int(raw, 0)
    except ValueError:
        raise ValueError(
            f"{path}:{lineno}: unsupported TOML value {raw!r} "
            "(waiver grammar: quoted strings, ints, booleans)") from None


def parse_toml_tables(text: str, path: str = "waivers.toml") -> list[dict]:
    """Parse ``[[waiver]]`` array-of-tables; returns the table dicts."""
    tables: list[dict] = []
    current: dict | None = None
    for lineno, line in enumerate(text.splitlines(), 1):
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        if stripped.startswith("[["):
            if not re.match(r"^\[\[\s*waiver\s*\]\]\s*(#.*)?$", stripped):
                raise ValueError(
                    f"{path}:{lineno}: only [[waiver]] tables are supported")
            current = {}
            tables.append(current)
            continue
        m = _KV_RE.match(stripped)
        if not m:
            raise ValueError(f"{path}:{lineno}: cannot parse {stripped!r}")
        if current is None:
            raise ValueError(
                f"{path}:{lineno}: key outside a [[waiver]] table")
        current[m.group(1)] = _parse_scalar(m.group(2), path, lineno)
    return tables


def load_waivers(path: str | Path) -> list[Waiver]:
    """Read the reviewed waiver file; missing file → no waivers."""
    p = Path(path)
    if not p.exists():
        return []
    waivers = []
    for t in parse_toml_tables(p.read_text(), str(p)):
        missing = [k for k in ("pass", "rule", "file", "reason") if not t.get(k)]
        if missing:
            raise ValueError(
                f"{p}: waiver {t!r} missing required key(s): "
                f"{', '.join(missing)} (every waiver needs pass/rule/file "
                "and a one-line reason)")
        waivers.append(Waiver(
            pass_name=str(t["pass"]), rule=str(t["rule"]),
            file=str(t["file"]), reason=str(t["reason"]),
            symbol=str(t.get("symbol", "")),
            contains=str(t.get("contains", "")),
        ))
    return waivers


def apply_waivers(findings: list[Finding], waivers: list[Waiver],
                  waiver_file: str = "",
                  passes_run: list[str] | None = None) -> list[Finding]:
    """Mark waived findings in place; append a ``stale-waiver`` finding
    for every waiver that matched nothing (the file stays reviewed).
    ``passes_run`` limits staleness reporting to waivers whose pass
    actually ran — a ``--pass abidrift`` run must not call the
    lockorder waivers stale."""
    for w in waivers:
        w.hits = 0
    for f in findings:
        for w in waivers:
            if w.matches(f):
                f.waived = True
                f.waiver_reason = w.reason
                w.hits += 1
                break
    out = list(findings)
    for w in waivers:
        if passes_run is not None and w.pass_name not in passes_run:
            continue
        if w.hits == 0:
            out.append(Finding(
                pass_name="waivers", rule="stale-waiver",
                file=waiver_file or "ompi_tpu/analysis/waivers.toml", line=0,
                symbol=f"{w.pass_name}/{w.rule}:{w.file}",
                message=(f"waiver for {w.pass_name}/{w.rule} at {w.file}"
                         f"{' [' + w.symbol + ']' if w.symbol else ''} "
                         "matched no finding — delete it or fix the match key"),
                severity=SEV_WARN))
    return out


class Report:
    """The one machine-readable findings artifact (JSON schema v1)."""

    VERSION = 1

    def __init__(self, root: str):
        self.root = root
        self.findings: list[Finding] = []
        self.passes_run: list[str] = []
        self.notes: list[str] = []

    def extend(self, pass_name: str, findings: list[Finding]) -> None:
        self.passes_run.append(pass_name)
        self.findings.extend(findings)

    def unwaived(self, min_severity: str = SEV_ERROR) -> list[Finding]:
        sevs = {SEV_ERROR: (SEV_ERROR,),
                SEV_WARN: (SEV_ERROR, SEV_WARN),
                SEV_INFO: (SEV_ERROR, SEV_WARN, SEV_INFO)}[min_severity]
        return [f for f in self.findings
                if not f.waived and f.severity in sevs]

    def to_dict(self) -> dict:
        by_pass: dict[str, int] = {}
        for f in self.findings:
            if not f.waived and f.severity == SEV_ERROR:
                by_pass[f.pass_name] = by_pass.get(f.pass_name, 0) + 1
        return {
            "version": self.VERSION,
            "root": self.root,
            "passes": self.passes_run,
            "notes": self.notes,
            "findings": [asdict(f) for f in self.findings],
            "summary": {
                "total": len(self.findings),
                "waived": sum(1 for f in self.findings if f.waived),
                "unwaived_errors": len(self.unwaived(SEV_ERROR)),
                "by_pass": by_pass,
            },
        }

    def write_json(self, path: str | Path) -> None:
        Path(path).write_text(json.dumps(self.to_dict(), indent=2) + "\n")
