"""C-API bridge — the Python half of libtpumpi (native/src/shim.c).

≈ the internal engine under the reference's ``ompi/mpi/c`` bindings:
the shim marshals raw C buffer addresses + handle/datatype/op codes
into these functions, which wrap the memory as numpy views (zero-copy)
and drive the same communicator/coll/pml machinery as the Python API.

Execution model: **one OS process = one MPI rank** (the mpirun model,
SURVEY.md §3.1).  Under ``tpurun`` each process must own exactly one
local device (``--cpu-devices 1`` or the single real TPU chip);
standalone C programs get a size-1 world.  Constants here mirror
``native/include/mpi.h`` — keep the two in sync.

Every entry point returns an int MPI error class, or a tuple whose
first element is the error class (the shim copies the remaining ints
out before releasing the GIL).
"""

from __future__ import annotations

import ctypes
import traceback

import numpy as np

from ompi_tpu.core import errors as err
from ompi_tpu.op import op as opmod
from ompi_tpu.request import CompletedRequest, Request

# -- error classes (mpi.h) ---------------------------------------------
MPI_SUCCESS = 0
MPI_ERR_COUNT = 2
MPI_ERR_TYPE = 3
MPI_ERR_TAG = 4
MPI_ERR_COMM = 5
MPI_ERR_RANK = 6
MPI_ERR_REQUEST = 7
MPI_ERR_ROOT = 8
MPI_ERR_OP = 9
MPI_ERR_ARG = 12
MPI_ERR_TRUNCATE = 14
MPI_ERR_OTHER = 15
MPI_ERR_INTERN = 16

_IN_PLACE = (1 << 64) - 1  # (void*)-1 seen as unsigned long long

# -- datatype codes (mpi.h) --------------------------------------------
DTYPES: dict[int, np.dtype] = {
    1: np.dtype(np.int8),      # MPI_CHAR
    2: np.dtype(np.int8),      # MPI_SIGNED_CHAR
    3: np.dtype(np.uint8),     # MPI_UNSIGNED_CHAR
    4: np.dtype(np.uint8),     # MPI_BYTE
    5: np.dtype(np.int16),     # MPI_SHORT
    6: np.dtype(np.uint16),    # MPI_UNSIGNED_SHORT
    7: np.dtype(np.int32),     # MPI_INT
    8: np.dtype(np.uint32),    # MPI_UNSIGNED
    9: np.dtype(np.int64),     # MPI_LONG (LP64)
    10: np.dtype(np.uint64),   # MPI_UNSIGNED_LONG
    11: np.dtype(np.int64),    # MPI_LONG_LONG
    12: np.dtype(np.uint64),   # MPI_UNSIGNED_LONG_LONG
    13: np.dtype(np.float32),  # MPI_FLOAT
    14: np.dtype(np.float64),  # MPI_DOUBLE
    16: np.dtype(np.bool_),    # MPI_C_BOOL
    17: np.dtype(np.int8),
    18: np.dtype(np.int16),
    19: np.dtype(np.int32),
    20: np.dtype(np.int64),
    21: np.dtype(np.uint8),
    22: np.dtype(np.uint16),
    23: np.dtype(np.uint32),
    24: np.dtype(np.uint64),
    25: np.dtype(np.complex64),   # MPI_C_FLOAT_COMPLEX
    26: np.dtype(np.complex128),  # MPI_C_DOUBLE_COMPLEX
    27: np.dtype(np.int32),       # MPI_WCHAR
}

# -- op codes (mpi.h) ---------------------------------------------------
OPS: dict[int, opmod.Op] = {
    1: opmod.SUM,
    2: opmod.MAX,
    3: opmod.MIN,
    4: opmod.PROD,
    5: opmod.LAND,
    6: opmod.LOR,
    7: opmod.LXOR,
    8: opmod.BAND,
    9: opmod.BOR,
    10: opmod.BXOR,
    11: opmod.MAXLOC,
    12: opmod.MINLOC,
    13: opmod.REPLACE,
    14: opmod.NO_OP,
}

_comms: dict[int, object] = {}
_requests: dict[int, tuple] = {}
_next_handle = 3  # 1 = MPI_COMM_WORLD, 2 = MPI_COMM_SELF
_next_req = 1
_rank = 0
_size = 1


def _fail(e: BaseException) -> int:
    """Map a framework exception to an MPI error class (printing the
    traceback — the C caller only sees the class, ≈ MPI_ERRORS_RETURN)."""
    if isinstance(e, err.MPIError):
        return int(e.error_class)
    traceback.print_exc()
    return MPI_ERR_OTHER


def _view(ptr: int, count: int, dtcode: int) -> np.ndarray:
    """Zero-copy numpy view over a raw C buffer."""
    dt = DTYPES.get(dtcode)
    if dt is None:
        raise err.MPIArgError(f"unsupported C datatype code {dtcode}")
    nbytes = count * dt.itemsize
    if nbytes == 0:
        return np.empty(0, dt)
    raw = (ctypes.c_ubyte * nbytes).from_address(ptr)
    return np.frombuffer(raw, dtype=dt)


def _comm(h: int):
    c = _comms.get(h)
    if c is None:
        raise err.MPICommError(f"invalid communicator handle {h}")
    return c


def _store_comm(c) -> int:
    global _next_handle
    h = _next_handle
    _next_handle += 1
    _comms[h] = c
    return h


def _store_req(entry: tuple) -> int:
    global _next_req
    h = _next_req
    _next_req += 1
    _requests[h] = entry
    return h


# -- init / finalize ----------------------------------------------------


def init() -> int:
    global _rank, _size
    try:
        import os

        import jax

        # honor JAX_PLATFORMS in the embedded interpreter: some PJRT
        # plugins (axon) register regardless of the env var, so the
        # config must be set explicitly before first device use
        plat = os.environ.get("JAX_PLATFORMS")
        if plat:
            try:
                jax.config.update("jax_platforms", plat)
            except Exception:  # noqa: BLE001 — already-initialized backends
                pass

        import ompi_tpu.api as api
        from ompi_tpu.boot.proc import launched_by_tpurun

        world = api.init()
        if launched_by_tpurun():
            if world.local_size != 1:
                raise err.MPIArgError(
                    "the C API maps one process to one MPI rank; launch "
                    "with exactly one local device per process "
                    "(tpurun --cpu-devices 1, or one TPU chip)"
                )
            _comms[1] = world
            _rank = world.local_offset
            _size = world.size
        else:
            # standalone C program: a size-1 world (the mpirun -np 1 case)
            _comms[1] = api.comm_self()
            _rank, _size = 0, 1
        _comms[2] = api.comm_self()
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001 — C boundary
        return _fail(e)


def finalize() -> int:
    try:
        import ompi_tpu.api as api

        _comms.clear()
        _requests.clear()
        api.finalize()
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


# -- env ----------------------------------------------------------------


def comm_size(h: int):
    try:
        c = _comm(h)
        return (MPI_SUCCESS, int(getattr(c, "size", 1)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def comm_rank(h: int):
    try:
        c = _comm(h)
        if h == 2 or getattr(c, "size", 1) == 1:
            return (MPI_SUCCESS, 0)
        return (MPI_SUCCESS, int(getattr(c, "local_offset", 0)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def comm_dup(h: int):
    try:
        return (MPI_SUCCESS, _store_comm(_comm(h).dup()))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def comm_split(h: int, color: int, key: int):
    try:
        c = _comm(h)
        if not hasattr(c, "split"):
            # MultiProcComm split lands with cross-process sub-groups
            import sys

            print("tpumpi: MPI_Comm_split on a multi-process communicator "
                  "is not yet supported", file=sys.stderr)
            return (MPI_ERR_OTHER, 0)
        # Comm.split takes per-local-rank color/key sequences; with the
        # C process=rank model each process contributes exactly one.
        sub = c.split([color], [key])
        if isinstance(sub, list):
            sub = sub[0]
        if sub is None:  # MPI_UNDEFINED color → MPI_COMM_NULL
            return (MPI_SUCCESS, 0)
        return (MPI_SUCCESS, _store_comm(sub))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def comm_free(h: int) -> int:
    try:
        if h > 2:  # WORLD/SELF are persistent
            _comm(h).free()
            _comms.pop(h, None)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def comm_set_name(h: int, name: str) -> int:
    try:
        _comm(h).name = name
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def type_size(dtcode: int):
    dt = DTYPES.get(dtcode)
    if dt is None:
        return (MPI_ERR_TYPE, 0)
    return (MPI_SUCCESS, int(dt.itemsize))


# -- collectives --------------------------------------------------------


def _coll_in(sptr: int, rptr: int, count: int, dtcode: int) -> np.ndarray:
    """Sendbuf view honoring MPI_IN_PLACE (input taken from recvbuf)."""
    if sptr == _IN_PLACE:
        return _view(rptr, count, dtcode)
    return _view(sptr, count, dtcode)


def allreduce(sptr, rptr, count, dtcode, opcode, h) -> int:
    try:
        c = _comm(h)
        x = _coll_in(sptr, rptr, count, dtcode)[None, :]  # (1 local rank, n)
        out = np.asarray(c.allreduce(x, OPS[opcode]))
        _view(rptr, count, dtcode)[:] = out.reshape(-1)[:count]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def reduce(sptr, rptr, count, dtcode, opcode, root, h) -> int:
    try:
        c = _comm(h)
        x = _coll_in(sptr, rptr, count, dtcode)[None, :]
        out = np.asarray(c.reduce(x, OPS[opcode], root=root))
        me = comm_rank(h)[1]
        if me == root and rptr not in (0, _IN_PLACE):
            _view(rptr, count, dtcode)[:] = out.reshape(-1)[:count]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def bcast(ptr, count, dtcode, root, h) -> int:
    try:
        c = _comm(h)
        buf = _view(ptr, count, dtcode)
        out = np.asarray(c.bcast(buf[None, :], root=root))
        buf[:] = out.reshape(-1)[:count]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def allgather(sptr, scount, sdt, rptr, rcount, rdt, h) -> int:
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        if sptr == _IN_PLACE:
            # input is this rank's block of recvbuf
            me = comm_rank(h)[1]
            full = _view(rptr, rcount * n, rdt)
            x = full[me * rcount : (me + 1) * rcount].copy()
            scount, sdt = rcount, rdt
        else:
            x = _view(sptr, scount, sdt)
        out = np.asarray(c.allgather(x[None, :]))  # (1, n, scount)
        _view(rptr, rcount * n, rdt)[:] = out.reshape(-1)[: rcount * n]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def gather(sptr, scount, sdt, rptr, rcount, rdt, root, h) -> int:
    # rooted gather rides the allgather path (wire cost is acceptable on
    # the fabric; the dedicated rooted schedule is a coll/base variant)
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        me = comm_rank(h)[1]
        if sptr == _IN_PLACE:
            # root's contribution is already in place in recvbuf
            full = _view(rptr, rcount * n, rdt)
            x = full[me * rcount : (me + 1) * rcount].copy()
            scount, sdt = rcount, rdt
        else:
            x = _view(sptr, scount, sdt)
        out = np.asarray(c.allgather(x[None, :]))
        if me == root:
            _view(rptr, rcount * n, rdt)[:] = out.reshape(-1)[: rcount * n]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def scatter(sptr, scount, sdt, rptr, rcount, rdt, root, h) -> int:
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        me = comm_rank(h)[1]
        if me == root:
            full = _view(sptr, scount * n, sdt).reshape(n, scount)
            if rptr == _IN_PLACE:
                # MPI_IN_PLACE recvbuf at root: its block stays in sendbuf
                rcount = 0
        else:
            full = np.zeros((n, max(scount, rcount)), DTYPES[rdt])
        out = np.asarray(c.scatter(full, root=root))
        if rcount:
            _view(rptr, rcount, rdt)[:] = out.reshape(-1)[:rcount]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def alltoall(sptr, scount, sdt, rptr, rcount, rdt, h) -> int:
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        if sptr == _IN_PLACE:
            x = _view(rptr, rcount * n, rdt).reshape(1, n, rcount).copy()
        else:
            x = _view(sptr, scount * n, sdt).reshape(1, n, scount)
        out = np.asarray(c.alltoall(x))
        _view(rptr, rcount * n, rdt)[:] = out.reshape(-1)[: rcount * n]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def reduce_scatter_block(sptr, rptr, rcount, dtcode, opcode, h) -> int:
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        if sptr == _IN_PLACE:
            x = _view(rptr, rcount * n, dtcode).reshape(1, n, rcount).copy()
        else:
            x = _view(sptr, rcount * n, dtcode).reshape(1, n, rcount)
        out = np.asarray(c.reduce_scatter_block(x, OPS[opcode]))
        _view(rptr, rcount, dtcode)[:] = out.reshape(-1)[:rcount]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def scan(sptr, rptr, count, dtcode, opcode, h) -> int:
    try:
        c = _comm(h)
        x = _coll_in(sptr, rptr, count, dtcode)[None, :]
        out = np.asarray(c.scan(x, OPS[opcode]))
        _view(rptr, count, dtcode)[:] = out.reshape(-1)[:count]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def exscan(sptr, rptr, count, dtcode, opcode, h) -> int:
    try:
        c = _comm(h)
        x = _coll_in(sptr, rptr, count, dtcode)[None, :]
        out = np.asarray(c.exscan(x, OPS[opcode]))
        me = comm_rank(h)[1]
        if me != 0:  # rank 0's recvbuf is undefined in MPI_Exscan
            _view(rptr, count, dtcode)[:] = out.reshape(-1)[:count]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def barrier(h) -> int:
    try:
        _comm(h).barrier()
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


# -- pt2pt --------------------------------------------------------------


def send(ptr, count, dtcode, dest, tag, h) -> int:
    try:
        c = _comm(h)
        me = comm_rank(h)[1]
        payload = _view(ptr, count, dtcode).copy()
        c.send(payload, source=me, dest=dest, tag=tag)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def recv(ptr, count, dtcode, source, tag, h):
    try:
        c = _comm(h)
        me = comm_rank(h)[1]
        payload, st = c.recv(
            dest=me,
            source=None if source == -1 else source,
            tag=None if tag == -1 else tag,
        )
        flat = np.asarray(payload).reshape(-1).view(DTYPES[dtcode])
        got = min(flat.size, count)
        _view(ptr, got, dtcode)[:] = flat[:got]
        return (MPI_SUCCESS, int(st.source), int(st.tag), got)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), -1, -1, 0)


def isend(ptr, count, dtcode, dest, tag, h):
    # sends are buffered-eager (pml): local completion is immediate
    rc = send(ptr, count, dtcode, dest, tag, h)
    if rc != MPI_SUCCESS:
        return (rc, 0)
    return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, 0))))


def irecv(ptr, count, dtcode, source, tag, h):
    try:
        c = _comm(h)
        me = comm_rank(h)[1]
        req = c.irecv(
            dest=me,
            source=None if source == -1 else source,
            tag=None if tag == -1 else tag,
        )
        return (MPI_SUCCESS, _store_req(("recv", req, ptr, count, dtcode)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


# -- requests -----------------------------------------------------------


def _complete(entry) -> tuple[int, int, int]:
    """Finish a request entry; returns (source, tag, count)."""
    kind, req, ptr, count, dtcode = entry
    if kind == "done":
        return entry[4] if isinstance(entry[4], tuple) else (0, 0, 0)
    if kind == "recv":
        payload = req.wait()
        st = req.status
        flat = np.asarray(payload).reshape(-1).view(DTYPES[dtcode])
        got = min(flat.size, count)
        _view(ptr, got, dtcode)[:] = flat[:got]
        return (int(st.source), int(st.tag), got)
    if kind == "coll":
        out = req.wait()
        if ptr not in (0, _IN_PLACE) and count:
            flat = np.asarray(out).reshape(-1)[:count]
            _view(ptr, count, dtcode)[:] = flat
        return (0, 0, count)
    raise err.MPIInternalError(f"bad request kind {kind}")


def wait(rh: int):
    try:
        entry = _requests.pop(rh, None)
        if entry is None:
            raise err.MPIArgError(f"invalid request handle {rh}")
        source, tag, count = _complete(entry)
        return (MPI_SUCCESS, source, tag, count)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), -1, -1, 0)


def test(rh: int):
    try:
        entry = _requests.get(rh)
        if entry is None:
            raise err.MPIArgError(f"invalid request handle {rh}")
        kind, req = entry[0], entry[1]
        ready = kind == "done" or (req is not None and req.test())
        if not ready:
            return (MPI_SUCCESS, 0, -1, -1, 0)
        _requests.pop(rh, None)
        source, tag, count = _complete(entry)
        return (MPI_SUCCESS, 1, source, tag, count)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0, -1, -1, 0)


# -- non-blocking collectives ------------------------------------------


def iallreduce(sptr, rptr, count, dtcode, opcode, h):
    try:
        c = _comm(h)
        x = _coll_in(sptr, rptr, count, dtcode)[None, :].copy()
        req = c.iallreduce(x, OPS[opcode])
        return (MPI_SUCCESS, _store_req(("coll", req, rptr, count, dtcode)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def _eager_coll(fn) -> tuple[int, int]:
    """Blocking execution + completed handle: MPI-legal (completion at
    wait is a superset of completion before wait); overlap comes from
    the fabric-side async dispatch underneath where available."""
    rc = fn()
    if rc not in (None, MPI_SUCCESS):
        return (int(rc), 0)
    return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, 0))))


def ibarrier(h):
    try:
        return _eager_coll(lambda: _comm(h).barrier())
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def ibcast(ptr, count, dtcode, root, h):
    try:
        return _eager_coll(lambda: bcast(ptr, count, dtcode, root, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def iallgather(sptr, scount, sdt, rptr, rcount, rdt, h):
    try:
        return _eager_coll(
            lambda: allgather(sptr, scount, sdt, rptr, rcount, rdt, h)
        )
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def ialltoall(sptr, scount, sdt, rptr, rcount, rdt, h):
    try:
        return _eager_coll(
            lambda: alltoall(sptr, scount, sdt, rptr, rcount, rdt, h)
        )
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)
