"""C-API bridge — the Python half of libtpumpi (native/src/shim.c).

≈ the internal engine under the reference's ``ompi/mpi/c`` bindings:
the shim marshals raw C buffer addresses + handle/datatype/op codes
into these functions, which wrap the memory as numpy views (zero-copy)
and drive the same communicator/coll/pml machinery as the Python API.

Execution model: **one OS process = one MPI rank** (the mpirun model,
SURVEY.md §3.1).  Under ``tpurun`` each process must own exactly one
local device (``--cpu-devices 1`` or the single real TPU chip);
standalone C programs get a size-1 world.  Constants here mirror
``native/include/mpi.h`` — keep the two in sync.

Every entry point returns an int MPI error class, or a tuple whose
first element is the error class (the shim copies the remaining ints
out before releasing the GIL).
"""

from __future__ import annotations

import ctypes
import time
import traceback

import numpy as np

from ompi_tpu.core import errors as err
from ompi_tpu.op import op as opmod
from ompi_tpu.request import CompletedRequest, Request

# -- error classes (mpi.h) ---------------------------------------------
MPI_SUCCESS = 0
MPI_ERR_COUNT = 2
MPI_ERR_TYPE = 3
MPI_ERR_TAG = 4
MPI_ERR_COMM = 5
MPI_ERR_RANK = 6
MPI_ERR_REQUEST = 7
MPI_ERR_ROOT = 8
MPI_ERR_OP = 9
MPI_ERR_ARG = 12
MPI_ERR_TRUNCATE = 14
MPI_ERR_OTHER = 15
MPI_ERR_INTERN = 16

_IN_PLACE = (1 << 64) - 1  # (void*)-1 seen as unsigned long long

# -- datatype codes (mpi.h) --------------------------------------------
DTYPES: dict[int, np.dtype] = {
    1: np.dtype(np.int8),      # MPI_CHAR
    2: np.dtype(np.int8),      # MPI_SIGNED_CHAR
    3: np.dtype(np.uint8),     # MPI_UNSIGNED_CHAR
    4: np.dtype(np.uint8),     # MPI_BYTE
    5: np.dtype(np.int16),     # MPI_SHORT
    6: np.dtype(np.uint16),    # MPI_UNSIGNED_SHORT
    7: np.dtype(np.int32),     # MPI_INT
    8: np.dtype(np.uint32),    # MPI_UNSIGNED
    9: np.dtype(np.int64),     # MPI_LONG (LP64)
    10: np.dtype(np.uint64),   # MPI_UNSIGNED_LONG
    11: np.dtype(np.int64),    # MPI_LONG_LONG
    12: np.dtype(np.uint64),   # MPI_UNSIGNED_LONG_LONG
    13: np.dtype(np.float32),  # MPI_FLOAT
    14: np.dtype(np.float64),  # MPI_DOUBLE
    16: np.dtype(np.bool_),    # MPI_C_BOOL
    17: np.dtype(np.int8),
    18: np.dtype(np.int16),
    19: np.dtype(np.int32),
    20: np.dtype(np.int64),
    21: np.dtype(np.uint8),
    22: np.dtype(np.uint16),
    23: np.dtype(np.uint32),
    24: np.dtype(np.uint64),
    25: np.dtype(np.complex64),   # MPI_C_FLOAT_COMPLEX
    26: np.dtype(np.complex128),  # MPI_C_DOUBLE_COMPLEX
    27: np.dtype(np.int32),       # MPI_WCHAR
}

# -- op codes (mpi.h) ---------------------------------------------------
OPS: dict[int, opmod.Op] = {
    1: opmod.SUM,
    2: opmod.MAX,
    3: opmod.MIN,
    4: opmod.PROD,
    5: opmod.LAND,
    6: opmod.LOR,
    7: opmod.LXOR,
    8: opmod.BAND,
    9: opmod.BOR,
    10: opmod.BXOR,
    11: opmod.MAXLOC,
    12: opmod.MINLOC,
    13: opmod.REPLACE,
    14: opmod.NO_OP,
}

_comms: dict[int, object] = {}
_requests: dict[int, tuple] = {}
_groups: dict[int, object] = {}
_dtypes: dict[int, object] = {}  # derived datatype handle → ddt.Datatype
_errhandlers: dict[int, int] = {}  # comm handle → 1 (FATAL) | 2 (RETURN)
_next_handle = 3  # 1 = MPI_COMM_WORLD, 2 = MPI_COMM_SELF
_next_req = 1
_next_group = 2   # 1 = MPI_GROUP_EMPTY
_next_dtype = 64  # predefined codes stay below

# Predefined pair types (MAXLOC/MINLOC operands) are DERIVED-shaped:
# register them as ddt Datatypes so size/extent/leaf-count/pack queries
# see their 2-entry typemaps (MPI_Get_elements on MPI_DOUBLE_INT must
# report 2 basic elements per pair).
def _register_pair_types() -> None:
    from ompi_tpu.ddt import datatype as _ddt

    _dtypes[28] = _ddt.FLOAT_INT
    _dtypes[29] = _ddt.DOUBLE_INT
    _dtypes[30] = _ddt.LONG_INT
    _dtypes[31] = _ddt.TWO_INT
    _dtypes[32] = _ddt.SHORT_INT


_register_pair_types()
_rank = 0
_size = 1

ERRH_FATAL, ERRH_RETURN = 1, 2


def _fail(e: BaseException, h: int | None = None) -> int:
    """Map a framework exception to an MPI error class.  Honors the
    communicator's errhandler: MPI_ERRORS_ARE_FATAL (the standard's
    default for conforming C programs) aborts the process; otherwise
    the class is returned to the caller (MPI_ERRORS_RETURN)."""
    if isinstance(e, err.MPIError):
        cls = int(e.error_class)
    else:
        traceback.print_exc()
        cls = MPI_ERR_OTHER
    # errors not attached to a communicator use WORLD's errhandler
    eh = _errhandlers.get(h if h is not None else 1, ERRH_FATAL)
    if eh == ERRH_FATAL:
        import os
        import sys

        print(f"tpumpi: MPI_ERRORS_ARE_FATAL: {type(e).__name__}: {e}",
              file=sys.stderr)
        sys.stderr.flush()
        os._exit(cls if 0 < cls < 126 else 1)
    return cls


def _t_fail(e: BaseException) -> int:
    """MPI_T error mapping: the tools interface returns error codes and
    NEVER invokes communicator error handlers (MPI-3 §14.3.4) — no
    abort even under ERRORS_ARE_FATAL."""
    if isinstance(e, err.MPIError):
        return int(e.error_class)
    traceback.print_exc()
    return MPI_ERR_OTHER


def _unit_nbytes(dtcode: int) -> int:
    """Packed byte size of ONE instance of a datatype code — the unit
    the C status's byte count (``_nbytes``) is denominated in.  MPI
    Get_count semantics divide by SIZE (packed), not extent."""
    d = _dtypes.get(dtcode)
    if d is not None:
        return int(d.size)
    dt = DTYPES.get(dtcode)
    return int(dt.itemsize) if dt is not None else 1


_ctype_arrays: dict[int, type] = {}  # nbytes → ctypes array type


def _ctype_arr(nbytes: int) -> type:
    """Cached ``c_ubyte * n`` array types: ctypes type creation is the
    measurable part of the view path, and benchmark/app loops reuse a
    handful of sizes (VERDICT r3 next #6)."""
    t = _ctype_arrays.get(nbytes)
    if t is None:
        if len(_ctype_arrays) > 4096:  # unbounded-size-mix backstop
            _ctype_arrays.clear()
        t = ctypes.c_ubyte * nbytes
        _ctype_arrays[nbytes] = t
    return t


def _view(ptr: int, count: int, dtcode: int) -> np.ndarray:
    """Zero-copy numpy view over a raw C buffer."""
    dt = DTYPES.get(dtcode)
    if dt is None:
        raise err.MPIArgError(f"unsupported C datatype code {dtcode}")
    nbytes = count * dt.itemsize
    if nbytes == 0:
        return np.empty(0, dt)
    raw = _ctype_arr(nbytes).from_address(ptr)
    return np.frombuffer(raw, dtype=dt)


def _comm(h: int):
    c = _comms.get(h)
    if c is None:
        raise err.MPICommError(f"invalid communicator handle {h}")
    if _freed_active:  # opportunistic progress for detached requests
        _reap_freed_active()
    return c


def _store_comm(c, parent_h: int | None = None) -> int:
    global _next_handle
    h = _next_handle
    _next_handle += 1
    _comms[h] = c
    if parent_h is not None:
        # MPI: dup/split/create propagate the parent's errhandler
        _errhandlers[h] = _errhandlers.get(parent_h, ERRH_FATAL)
    return h


def _store_req(entry: tuple) -> int:
    global _next_req
    h = _next_req
    _next_req += 1
    _requests[h] = entry
    return h


# -- init / finalize ----------------------------------------------------


def init() -> int:
    global _rank, _size
    try:
        import os

        import jax

        # honor JAX_PLATFORMS in the embedded interpreter: some PJRT
        # plugins (axon) register regardless of the env var, so the
        # config must be set explicitly before first device use
        plat = os.environ.get("JAX_PLATFORMS")
        if plat:
            try:
                jax.config.update("jax_platforms", plat)
            except Exception:  # noqa: BLE001 — already-initialized backends
                pass

        import ompi_tpu.api as api
        from ompi_tpu.boot.proc import launched_by_tpurun

        world = api.init()
        if launched_by_tpurun():
            if world.local_size != 1:
                raise err.MPIArgError(
                    "the C API maps one process to one MPI rank; launch "
                    "with exactly one local device per process "
                    "(tpurun --cpu-devices 1, or one TPU chip)"
                )
            _comms[1] = world
            _rank = world.local_offset
            _size = world.size
        else:
            # standalone C program: a size-1 world (the mpirun -np 1 case)
            _comms[1] = api.comm_self()
            _rank, _size = 0, 1
        _comms[2] = api.comm_self()
        from ompi_tpu.trace import core as _trace

        if _trace._enabled:
            _trace.instant("api", "MPI_Init", rank=_rank, size=_size)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001 — C boundary
        return _fail(e)


def finalize() -> int:
    try:
        import ompi_tpu.api as api
        from ompi_tpu.trace import core as _trace

        if _trace._enabled:
            _trace.instant("api", "MPI_Finalize", rank=_rank)
        _comms.clear()
        _requests.clear()
        # deliver any freed-but-completed requests before teardown;
        # still-pending ones can never complete now (their peers are
        # finalizing too) and are dropped per MPI's freed-handle liberty
        _reap_freed_active()
        _freed_active.clear()
        api.finalize()
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


# -- env ----------------------------------------------------------------


def comm_size(h: int):
    try:
        c = _comm(h)
        return (MPI_SUCCESS, int(getattr(c, "size", 1)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def comm_rank(h: int):
    try:
        c = _comm(h)
        if h == 2 or getattr(c, "size", 1) == 1:
            return (MPI_SUCCESS, 0)
        return (MPI_SUCCESS, int(getattr(c, "local_offset", 0)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def fast_error(h: int, code: int):
    """The shim's C fast path hit an MPI error (truncation, engine
    failure): honor the communicator's errhandler exactly like
    ``_fail`` — abort under MPI_ERRORS_ARE_FATAL (the conforming-C
    default), hand the class back under MPI_ERRORS_RETURN."""
    eh = _errhandlers.get(h, ERRH_FATAL)
    if eh == ERRH_FATAL:
        import os
        import sys

        print(f"tpumpi: MPI_ERRORS_ARE_FATAL: fast-path error class "
              f"{int(code)}", file=sys.stderr)
        sys.stderr.flush()
        os._exit(int(code) if 0 < int(code) < 126 else 1)
    return (MPI_SUCCESS, int(code))


def native_fastpath_info(h: int):
    """(err, info_string) for the shim's C p2p fast path.

    Non-empty only for multi-process comms whose p2p plane is the C
    matching engine (native transport + the default ``eager`` pml);
    the shim then drives MPI_Send/Recv straight into libtpudcn — no
    embedded-Python crossing on the hot path.  Encoding: fields
    ``engine_ptr, cid, my_rank, nranks, offsets_csv, addresses``
    joined with ``\\x1f`` (addresses joined with ``\\x1e`` — the
    composite transport addresses contain ``|`` and ``;``, so those
    are not usable as separators; offsets = the comm's rank→process
    boundaries)."""
    try:
        c = _comm(h)
        if not getattr(c, "_pml_native", False):
            return (MPI_SUCCESS, "")
        root = c.dcn._native_root()
        c.pml  # force native pml construction (keeps one engine owner)
        # \x1f (unit sep) between fields, \x1e between addresses — the
        # composite transport addresses themselves contain '|' and ';'
        info = "\x1f".join([
            str(int(root._h)),
            str(c.cid),
            str(int(getattr(c, "local_offset", 0))),
            str(int(c.size)),
            ",".join(str(int(o)) for o in c.offsets),
            # indexed access on purpose: a sharded-modex AddressTable
            # resolves its holes here — the C-ABI fast path's cctx is
            # eager by design (fail_idx mapping needs every address)
            "\x1e".join(_fp_addrs(c.dcn)),
            # trailing field (appended — older parsers stop early): the
            # DCN ring-allreduce crossover, so the shim's C collective
            # schedules pick the SAME algorithm the Python plane would
            # (bit-exact MPI_SUM across both paths); reproducible mode
            # pins the process-ordered linear fold on both planes
            str(_coll_ring_threshold(c)),
        ])
        return (MPI_SUCCESS, info)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), "")


def _fp_addrs(eng) -> list[str]:
    """The engine's member addresses, fully resolved: indexed access
    forces a sharded-modex AddressTable to fill its lazy holes (the
    C-ABI fast path needs every address eagerly for its fail-index
    mapping; sub-engine address views resolve through the parent)."""
    addrs = eng.addresses
    return [addrs[i] for i in range(len(addrs))]


def _coll_ring_threshold(c) -> int:
    """The comm's DCN ring-allreduce crossover in bytes; a huge
    sentinel when ``coll_han_reproducible`` pins the ordered fold."""
    from ompi_tpu.core import mca

    store = mca.default_context().store
    if bool(store.get("coll_han_reproducible", False)):
        return 1 << 62  # never ring: ordered linear on both planes
    return int(getattr(c.dcn, "ring_threshold", 64 << 10))


def coll_sched_decision(h: int, coll: str, nbytes: int, opcode: int):
    """(err, algo) — the algorithm a persistent collective's compiled
    schedule should replay: 0 = process-ordered linear, 1 = ring.  The
    decision layer's verdict resolved ONCE at ``*_init`` time (the
    libnbc compile step) and memoized in the process-wide schedule
    cache, so a resident worker's later inits of the same signature
    never re-derive it."""
    try:
        from ompi_tpu.coll import sched as _sched
        from ompi_tpu.coll.tuned import dcn_fixed_decision
        from ompi_tpu.core import mca

        c = _comm(h)
        store = mca.default_context().store

        def build() -> int:
            return dcn_fixed_decision(
                coll, int(getattr(c, "nprocs", 1)), int(nbytes),
                OPS.get(opcode),
                int(getattr(c.dcn, "ring_threshold", 64 << 10)),
                reproducible=bool(
                    store.get("coll_han_reproducible", False)))

        algo = _sched.lookup(
            ("capi_decision", int(getattr(c, "nprocs", 1)), coll,
             int(opcode), int(nbytes),
             store.version),  # var-change coherence
            build,
        )
        return (MPI_SUCCESS, int(algo))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def coll_handle_agree(h: int, kind: int, root: int, nbytes: int,
                      pre: int):
    """(err, verdict) — the schedule-build handle-homogeneity guard
    for the C collective fast path.  Routing keys on the LOCAL
    datatype handle, but MPI only requires SIGNATURE equality across
    ranks: a predefined handle on one rank with a same-signature
    derived handle on another is legal yet would silently split the
    ranks across planes (deadlock).  At schedule-build time every
    rank publishes its handle class for the (comm, kind, root,
    nbytes) signature on the job KVS; predefined ranks wait for all
    peers and the verdict (1 = all predefined → C plane allowed,
    0 = mixed → every rank keeps the Python plane) is cached shim-
    side, so the KVS round is paid once per signature.  Derived ranks
    publish and return immediately — they already know their plane.
    Supported envelope note: a signature must keep a consistent
    handle class per rank across the program (re-agreement is cached
    by signature, not per call)."""
    try:
        c = _comm(h)
        eng = getattr(c, "dcn", None)
        ctx = getattr(c, "procctx", None)
        if (eng is None or ctx is None
                or int(getattr(eng, "nprocs", 1)) <= 1):
            return (MPI_SUCCESS, 1 if pre else 0)
        from ompi_tpu.core.var import Deadline

        kvs = ctx.kvs
        ns = getattr(ctx, "ns", "")
        key = (f"{ns}hagree.{c.cid}.{int(kind)}.{int(root)}."
               f"{int(nbytes)}")

        def _poisoned() -> bool:
            try:
                kvs.get(f"{key}.verdict0", wait=False)
                return True
            except KeyError:
                return False

        # verdict-0 marker first: a peer that already degraded this
        # signature (derived handle, or a timeout) binds EVERY later
        # arrival to the same Python-plane verdict — without it, a
        # rank whose wait expired would cache 0 while a late-arriving
        # rank reads the complete all-"p" key set and caches 1: the
        # exact cross-rank plane split the guard exists to prevent
        if _poisoned():
            kvs.put(f"{key}.{int(eng.proc)}", "d")
            return (MPI_SUCCESS, 0)
        kvs.put(f"{key}.{int(eng.proc)}", "p" if pre else "d")
        if not pre:
            kvs.put(f"{key}.verdict0", 1)
            return (MPI_SUCCESS, 0)
        dl = Deadline.for_timeout("recv")
        verdict = 1
        for p in range(int(eng.nprocs)):
            if p == int(eng.proc):
                continue
            v = None
            while v is None:
                try:
                    v = kvs.get(f"{key}.{p}", timeout=dl.slice(1.0))
                except KeyError:
                    if dl.expired():
                        break  # silent peer: conservative Python plane
                except OSError:
                    # transient KVS hiccup: retry inside the same
                    # deadline rather than raising — the raise path
                    # would cache verdict 0 on THIS rank while peers
                    # holding our published "p" complete an all-"p"
                    # read and cache 1: the cross-plane split the
                    # guard exists to prevent.  A dead KVS ends in
                    # the deadline degrade below like a silent peer.
                    if dl.expired():
                        break
                    time.sleep(0.05)
            if v != "p":
                verdict = 0
                break
        if verdict == 0:
            # publish the degradation (and flip our own class key) so
            # peers arriving after our deadline converge on 0 instead
            # of reading a complete "p" set.  The residual race — a
            # peer completing its all-"p" read in the same instant
            # this marker lands — needs the skew to hit the deadline
            # within the marker-write window; the supported envelope
            # (consistent handle classes per signature) is unaffected.
            kvs.put(f"{key}.verdict0", 1)
            kvs.put(f"{key}.{int(eng.proc)}", "d")
        elif _poisoned():
            verdict = 0  # a peer degraded while we were reading keys
        return (MPI_SUCCESS, verdict)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def comm_dup(h: int):
    try:
        nh = _store_comm(_comm(h).dup(), h)
        attr_copy_on_dup("comm", h, nh)  # keyval copy callbacks fire here
        return (MPI_SUCCESS, nh)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def comm_split(h: int, color: int, key: int):
    try:
        c = _comm(h)
        # Comm.split / MultiProcComm.split take per-local-rank color/key
        # sequences; with the C process=rank model each process (or the
        # single-controller comm's ranks — handled by the length) gives
        # exactly one.  Cross-process sub-comms ride DcnSubEngine.
        if _is_single_controller(c):
            colors = [color] * c.size
            keys = [key] * c.size
            sub = c.split(colors, keys)[0]
        else:
            sub = c.split([color], [key])[0]
        if sub is None:  # MPI_UNDEFINED color → MPI_COMM_NULL
            return (MPI_SUCCESS, 0)
        return (MPI_SUCCESS, _store_comm(sub, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def comm_free(h: int) -> int:
    try:
        if h > 2:  # WORLD/SELF are persistent
            _comm(h).free()
            _comms.pop(h, None)
            _carts.pop(h, None)
            _graphs.pop(h, None)
            _errhandlers.pop(h, None)
            _dist_graphs.pop(h, None)
            # keyval delete callbacks fire at comm destruction (MPI
            # attribute caching semantics)
            for kv in list(_attr_tables.get(("comm", h), {})):
                attr_delete("comm", h, kv)
            _attr_tables.pop(("comm", h), None)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def comm_set_name(h: int, name: str) -> int:
    try:
        _comm(h).name = name
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def _is_single_controller(c) -> bool:
    """True for single-process Comm objects (one Python process drives
    every rank — the standalone / COMM_SELF case)."""
    return getattr(c, "dcn", None) is None


def type_size(dtcode: int):
    d = _dtypes.get(dtcode)
    if d is not None:
        return (MPI_SUCCESS, int(d.size))
    dt = DTYPES.get(dtcode)
    if dt is None:
        return (MPI_ERR_TYPE, 0)
    return (MPI_SUCCESS, int(dt.itemsize))


def type_leaf_count(dtcode: int):
    """Basic (leaf) elements per datatype instance — what
    MPI_Get_elements multiplies the type-unit count by (derived types:
    typemap length; predefined scalars: 1; the predefined pair types
    28-32 are registered in ``_dtypes`` with 2-entry typemaps)."""
    d = _dtypes.get(dtcode)
    if d is not None:
        return (MPI_SUCCESS, max(1, len(d.typemap)))
    if DTYPES.get(dtcode) is None:
        return (MPI_ERR_TYPE, 0)
    return (MPI_SUCCESS, 1)


# -- collectives --------------------------------------------------------


def _coll_in(sptr: int, rptr: int, count: int, dtcode: int) -> np.ndarray:
    """Sendbuf view honoring MPI_IN_PLACE (input taken from recvbuf)."""
    if sptr == _IN_PLACE:
        return _view(rptr, count, dtcode)
    return _view(sptr, count, dtcode)


def _reduce_in(sptr, rptr, count, dtcode) -> np.ndarray:
    """Reduction input honoring MPI_IN_PLACE AND derived datatypes:
    derived contributions go through the convertor pack onto their
    uniform leaf dtype (MPI requires reducible derived types to be
    leaf-uniform) — the fallback contract behind the shim's C fast
    path, which only serves contiguous predefined types."""
    src = rptr if sptr == _IN_PLACE else sptr
    if dtcode in _dtypes:
        d = _dtypes[dtcode]
        if d.uniform_leaf is None:
            raise err.MPITypeError(
                "reductions need a uniform-leaf datatype")
        return _pack_from(src, count, dtcode)
    return _view(src, count, dtcode)


def allreduce(sptr, rptr, count, dtcode, opcode, h) -> int:
    try:
        c = _comm(h)
        x = _reduce_in(sptr, rptr, count, dtcode)[None, :]
        out = np.asarray(c.allreduce(x, OPS[opcode]))
        _unpack_into(rptr, count, dtcode, out[0])
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def reduce(sptr, rptr, count, dtcode, opcode, root, h) -> int:
    try:
        c = _comm(h)
        x = _reduce_in(sptr, rptr, count, dtcode)[None, :]
        out = np.asarray(c.reduce(x, OPS[opcode], root=root))
        me = comm_rank(h)[1]
        if me == root and rptr not in (0, _IN_PLACE):
            _unpack_into(rptr, count, dtcode, out[0])
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def bcast(ptr, count, dtcode, root, h) -> int:
    try:
        c = _comm(h)
        if dtcode in _dtypes:  # derived: pack → bcast bytes → unpack
            x = _pack_from(ptr, count, dtcode)
            out = np.asarray(c.bcast(np.asarray(x)[None, :], root=root))
            _unpack_into(ptr, count, dtcode, out[0])
            return MPI_SUCCESS
        buf = _view(ptr, count, dtcode)
        out = np.asarray(c.bcast(buf[None, :], root=root))
        buf[:] = out.reshape(-1)[:count]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def allgather(sptr, scount, sdt, rptr, rcount, rdt, h) -> int:
    # Derived send/recv handles ride the convertor pack/unpack (like
    # bcast): matching signatures pack to identical leaf-typed (or
    # raw-byte) blocks, so a derived-sendtype rank interoperates with
    # predefined-handle peers — the capi fallback must serve every
    # legal call the shim's agreement routes here (a derived handle
    # ANYWHERE forces all ranks onto this plane).
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        if sptr == _IN_PLACE:
            # input is this rank's block of recvbuf
            me = comm_rank(h)[1]
            d = _dtypes.get(rdt)
            if d is not None:
                x = _pack_from(rptr + me * rcount * d.extent, rcount, rdt)
            else:
                full = _view(rptr, rcount * n, rdt)
                x = full[me * rcount : (me + 1) * rcount].copy()
        elif sdt in _dtypes:
            x = _pack_from(sptr, scount, sdt)
        else:
            x = _view(sptr, scount, sdt)
        out = np.asarray(c.allgather(x[None, :]))  # (1, n, per-rank)
        if rdt in _dtypes:
            _unpack_into(rptr, rcount * n, rdt, out[0])
        else:
            _view(rptr, rcount * n, rdt)[:] = out.reshape(-1)[: rcount * n]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def gather(sptr, scount, sdt, rptr, rcount, rdt, root, h) -> int:
    # rooted gather rides the allgather path (wire cost is acceptable on
    # the fabric; the dedicated rooted schedule is a coll/base variant)
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        me = comm_rank(h)[1]
        if sptr == _IN_PLACE:
            # root's contribution is already in place in recvbuf
            full = _view(rptr, rcount * n, rdt)
            x = full[me * rcount : (me + 1) * rcount].copy()
            scount, sdt = rcount, rdt
        else:
            x = _view(sptr, scount, sdt)
        out = np.asarray(c.allgather(x[None, :]))
        if me == root:
            _view(rptr, rcount * n, rdt)[:] = out.reshape(-1)[: rcount * n]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def scatter(sptr, scount, sdt, rptr, rcount, rdt, root, h) -> int:
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        me = comm_rank(h)[1]
        if me == root:
            full = _view(sptr, scount * n, sdt).reshape(n, scount)
            if rptr == _IN_PLACE:
                # MPI_IN_PLACE recvbuf at root: its block stays in sendbuf
                rcount = 0
        else:
            full = np.zeros((n, max(scount, rcount)), DTYPES[rdt])
        out = np.asarray(c.scatter(full, root=root))
        if rcount:
            _view(rptr, rcount, rdt)[:] = out.reshape(-1)[:rcount]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def alltoall(sptr, scount, sdt, rptr, rcount, rdt, h) -> int:
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        if sptr == _IN_PLACE:
            x = _view(rptr, rcount * n, rdt).reshape(1, n, rcount).copy()
        else:
            x = _view(sptr, scount * n, sdt).reshape(1, n, scount)
        out = np.asarray(c.alltoall(x))
        _view(rptr, rcount * n, rdt)[:] = out.reshape(-1)[: rcount * n]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def reduce_scatter_block(sptr, rptr, rcount, dtcode, opcode, h) -> int:
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        if sptr == _IN_PLACE:
            x = _view(rptr, rcount * n, dtcode).reshape(1, n, rcount).copy()
        else:
            x = _view(sptr, rcount * n, dtcode).reshape(1, n, rcount)
        out = np.asarray(c.reduce_scatter_block(x, OPS[opcode]))
        _view(rptr, rcount, dtcode)[:] = out.reshape(-1)[:rcount]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def scan(sptr, rptr, count, dtcode, opcode, h) -> int:
    try:
        c = _comm(h)
        x = _coll_in(sptr, rptr, count, dtcode)[None, :]
        out = np.asarray(c.scan(x, OPS[opcode]))
        _view(rptr, count, dtcode)[:] = out.reshape(-1)[:count]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def exscan(sptr, rptr, count, dtcode, opcode, h) -> int:
    try:
        c = _comm(h)
        x = _coll_in(sptr, rptr, count, dtcode)[None, :]
        out = np.asarray(c.exscan(x, OPS[opcode]))
        me = comm_rank(h)[1]
        if me != 0:  # rank 0's recvbuf is undefined in MPI_Exscan
            _view(rptr, count, dtcode)[:] = out.reshape(-1)[:count]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def barrier(h) -> int:
    try:
        _comm(h).barrier()
        # freed-active requests whose message arrived before/during the
        # barrier must be delivered BEFORE the barrier returns to C —
        # the canonical MPI_Request_free inference pattern (free; peer
        # sends + barriers; read buffer) relies on exactly this, and
        # channel FIFO guarantees the data frame was matched by now
        _reap_freed_active()
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


# -- pt2pt --------------------------------------------------------------


def send(ptr, count, dtcode, dest, tag, h) -> int:
    try:
        c = _comm(h)
        me = comm_rank(h)[1]
        # derived datatypes go through the convertor pack (SURVEY §3.3);
        # predefined ones are a zero-copy view + copy
        payload = _pack_from(ptr, count, dtcode)
        c.send(payload, source=me, dest=dest, tag=tag)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def recv(ptr, count, dtcode, source, tag, h):
    try:
        c = _comm(h)
        me = comm_rank(h)[1]
        out = None
        kw = {}
        if (dtcode in DTYPES and dtcode not in _dtypes
                and getattr(c, "_pml_native", False)):
            # native plane + predefined contiguous dtype: post the
            # user buffer itself (the ctypes recv_into surface) — a
            # racing streamed RTS lands straight in it, and the copy
            # path becomes one C-side memcpy, never a Python unpack
            out = _view(ptr, count, dtcode)
            kw["out"] = out
        payload, st = c.recv(
            dest=me,
            source=None if source == -1 else source,
            tag=None if tag == -1 else tag,
            **kw,
        )
        if out is not None and payload is out:
            unit = _unit_nbytes(dtcode)
            got = min(count, int(st.nbytes) // max(1, unit))
        else:
            got = _unpack_into(ptr, count, dtcode, payload)
        return (MPI_SUCCESS, int(st.source), int(st.tag),
                got * _unit_nbytes(dtcode))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), -1, -1, 0)


def isend(ptr, count, dtcode, dest, tag, h):
    # sends are buffered-eager (pml): local completion is immediate
    rc = send(ptr, count, dtcode, dest, tag, h)
    if rc != MPI_SUCCESS:
        return (rc, 0)
    return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, 0))))


def irecv(ptr, count, dtcode, source, tag, h):
    try:
        c = _comm(h)
        me = comm_rank(h)[1]
        req = c.irecv(
            dest=me,
            source=None if source == -1 else source,
            tag=None if tag == -1 else tag,
        )
        return (MPI_SUCCESS, _store_req(("recv", req, ptr, count, dtcode)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


# -- requests -----------------------------------------------------------


def _complete(entry) -> tuple[int, int, int]:
    """Finish a request entry; returns (source, tag, nbytes) — the
    count slot is BYTES (what the C status carries; PMPI_Get_count
    divides by the queried datatype's size)."""
    kind, req, ptr, count, dtcode = entry
    if kind == "done":
        return entry[4] if isinstance(entry[4], tuple) else (0, 0, 0)
    if kind == "recv":
        payload = req.wait()
        st = req.status
        got = _unpack_into(ptr, count, dtcode, payload)
        return (int(st.source), int(st.tag), got * _unit_nbytes(dtcode))
    if kind == "coll":
        out = req.wait()
        if ptr not in (0, _IN_PLACE) and count:
            # _unpack_into: predefined lands as the plain flat view,
            # derived goes through the convertor (iallreduce's
            # mixed-handle fallback leg)
            _unpack_into(ptr, count, dtcode, np.asarray(out))
        return (0, 0, count * _unit_nbytes(dtcode))
    raise err.MPIInternalError(f"bad request kind {kind}")


def _complete_persistent(rh: int, entry) -> tuple[int, int, int]:
    """Finish a persistent request's CURRENT round; the handle stays
    valid (inactive) for the next MPI_Start — MPI persistent-request
    lifecycle (handle dies only on MPI_Request_free)."""
    kind, req, params = entry[0], entry[1], entry[2]
    out = (0, 0, 0)
    try:
        if req is not None:
            if kind == "pers_recv":
                payload = req.wait()
                st = req.status
                ptr, count, dtcode = params[0], params[1], params[2]
                got = _unpack_into(ptr, count, dtcode, payload)
                out = (int(st.source), int(st.tag),
                       got * _unit_nbytes(dtcode))
            else:
                req.wait()
    finally:
        _requests[rh] = (kind, None, params, 0, 0)  # back to inactive
    return out


def wait(rh: int):
    pers = 0
    try:
        entry = _requests.get(rh)
        if entry is None:
            raise err.MPIArgError(f"invalid request handle {rh}")
        if entry[0] == "grequest":
            # generalized request: block until the user's worker calls
            # MPI_Grequest_complete (which rewrites the entry to done)
            from ompi_tpu.request import _poll_backoff

            sleep = 0.0
            while _requests.get(rh, ("done",))[0] == "grequest":
                sleep = _poll_backoff(sleep)
            entry = _requests.get(rh)
            if entry is None:
                return (MPI_SUCCESS, -1, -1, 0, 0)
        if entry[0].startswith("pers_"):
            pers = 1  # even on error the handle must survive (spec)
            source, tag, count = _complete_persistent(rh, entry)
            # trailing 1 = persistent: the shim keeps the handle alive
            return (MPI_SUCCESS, source, tag, count, 1)
        _requests.pop(rh, None)
        source, tag, count = _complete(entry)
        return (MPI_SUCCESS, source, tag, count, 0)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), -1, -1, 0, pers)


def test(rh: int):
    try:
        entry = _requests.get(rh)
        if entry is None:
            raise err.MPIArgError(f"invalid request handle {rh}")
        kind, req = entry[0], entry[1]
        if kind.startswith("pers_"):
            if req is None:  # inactive persistent request: trivially done
                return (MPI_SUCCESS, 1, -1, -1, 0, 1)
            if not req.test():
                return (MPI_SUCCESS, 0, -1, -1, 0, 1)
            source, tag, count = _complete_persistent(rh, entry)
            return (MPI_SUCCESS, 1, source, tag, count, 1)
        ready = kind == "done" or (req is not None and req.test())
        if not ready:
            return (MPI_SUCCESS, 0, -1, -1, 0, 0)
        _requests.pop(rh, None)
        source, tag, count = _complete(entry)
        return (MPI_SUCCESS, 1, source, tag, count, 0)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0, -1, -1, 0, 0)


# -- non-blocking collectives ------------------------------------------


def iallreduce(sptr, rptr, count, dtcode, opcode, h):
    try:
        c = _comm(h)
        # _reduce_in (not _coll_in): derived handles pack onto their
        # uniform leaf like the blocking allreduce — the agreement
        # guard routes every mixed-handle I*-collective here
        x = _reduce_in(sptr, rptr, count, dtcode)[None, :].copy()
        req = c.iallreduce(x, OPS[opcode])
        return (MPI_SUCCESS, _store_req(("coll", req, rptr, count, dtcode)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def _eager_coll(fn) -> tuple[int, int]:
    """Blocking execution + completed handle: MPI-legal (completion at
    wait is a superset of completion before wait); overlap comes from
    the fabric-side async dispatch underneath where available."""
    rc = fn()
    if rc not in (None, MPI_SUCCESS):
        return (int(rc), 0)
    return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, 0))))


def ibarrier(h):
    try:
        return _eager_coll(lambda: _comm(h).barrier())
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def ibcast(ptr, count, dtcode, root, h):
    try:
        return _eager_coll(lambda: bcast(ptr, count, dtcode, root, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def iallgather(sptr, scount, sdt, rptr, rcount, rdt, h):
    try:
        return _eager_coll(
            lambda: allgather(sptr, scount, sdt, rptr, rcount, rdt, h)
        )
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def ialltoall(sptr, scount, sdt, rptr, rcount, rdt, h):
    try:
        return _eager_coll(
            lambda: alltoall(sptr, scount, sdt, rptr, rcount, rdt, h)
        )
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


# -- groups (MPI_Comm_group + group algebra; ≈ ompi/group/) --------------


def _group(gh: int):
    if gh == 1:
        from ompi_tpu.api.group import Group

        return Group([])
    g = _groups.get(gh)
    if g is None:
        raise err.MPIGroupError(f"invalid group handle {gh}")
    return g


def _store_group(g) -> int:
    global _next_group
    if g.size == 0:
        return 1  # MPI_GROUP_EMPTY
    _next_group += 1
    _groups[_next_group] = g
    return _next_group


def comm_group(h: int):
    """MPI_Comm_group.  Groups carry WORLD ranks (the comm's ``group``
    attribute), so group algebra and rank lookups compose across groups
    taken from different communicators."""
    try:
        from ompi_tpu.api.group import Group

        c = _comm(h)
        g = getattr(c, "group", None)
        ranks = list(g.ranks) if g is not None else range(getattr(c, "size", 1))
        return (MPI_SUCCESS, _store_group(Group(ranks)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def group_size(gh: int):
    try:
        return (MPI_SUCCESS, _group(gh).size)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def group_rank(gh: int):
    """Rank of the calling process in the group (MPI_UNDEFINED=-32766
    if absent)."""
    try:
        g = _group(gh)
        me = comm_rank(1)[1]
        return (MPI_SUCCESS, int(g.rank_of(me)))  # UNDEFINED if absent
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def group_free(gh: int) -> int:
    _groups.pop(gh, None)
    return MPI_SUCCESS


def group_incl(gh: int, ranks_ptr: int, n: int):
    try:
        ranks = [int(v) for v in _view(ranks_ptr, n, 7)] if n else []
        return (MPI_SUCCESS, _store_group(_group(gh).incl(ranks)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def group_excl(gh: int, ranks_ptr: int, n: int):
    try:
        ranks = [int(v) for v in _view(ranks_ptr, n, 7)] if n else []
        return (MPI_SUCCESS, _store_group(_group(gh).excl(ranks)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def group_union(ga: int, gb: int):
    try:
        return (MPI_SUCCESS, _store_group(_group(ga).union(_group(gb))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def group_intersection(ga: int, gb: int):
    try:
        return (MPI_SUCCESS, _store_group(_group(ga).intersection(_group(gb))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def group_difference(ga: int, gb: int):
    try:
        return (MPI_SUCCESS, _store_group(_group(ga).difference(_group(gb))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def group_translate_ranks(ga: int, n: int, ranks_ptr: int, gb: int,
                          out_ptr: int) -> int:
    try:
        ga_, gb_ = _group(ga), _group(gb)
        ranks = [int(v) for v in _view(ranks_ptr, n, 7)]
        out = ga_.translate_ranks(ranks, gb_)
        _view(out_ptr, n, 7)[:] = [int(r) for r in out]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def group_compare(ga: int, gb: int):
    """Maps the internal IDENT(0)/SIMILAR(1)/UNEQUAL(2) to the C
    header's MPI_IDENT(0)/MPI_SIMILAR(2)/MPI_UNEQUAL(3)."""
    try:
        v = int(_group(ga).compare(_group(gb)))
        return (MPI_SUCCESS, {0: 0, 1: 2, 2: 3}[v])
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def comm_create(h: int, gh: int):
    """MPI_Comm_create (and _group): new comm over the group's ranks,
    ordered by group rank.  Cross-process membership routes through
    comm_split with key = position in the group."""
    try:
        c = _comm(h)
        g = _group(gh)
        if g.size == 0:
            return (MPI_SUCCESS, 0)
        if _is_single_controller(c):
            sub = c.create_group(g)
            return (MPI_SUCCESS,
                    _store_comm(sub, h) if sub is not None else 0)
        me = comm_rank(h)[1]
        pos = int(g.rank_of(me))
        if pos == -32766:  # UNDEFINED: participate in the split collective
            c.split([-32766], [0])
            return (MPI_SUCCESS, 0)
        sub = c.split([0], [pos])[0]
        return (MPI_SUCCESS, _store_comm(sub, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def comm_create_group(h: int, gh: int, tag: int):
    """MPI_Comm_create_group (MPI-3.0): collective over the GROUP
    members only — routed to the members-only construction path (the
    full-comm split behind comm_create would deadlock: nonmembers
    never call)."""
    try:
        c = _comm(h)
        g = _group(gh)
        if g.size == 0:
            return (MPI_SUCCESS, 0)
        if _is_single_controller(c):
            sub = c.create_group(g)
            return (MPI_SUCCESS,
                    _store_comm(sub, h) if sub is not None else 0)
        sub = c.create_group_members(list(g.ranks), int(tag))
        return (MPI_SUCCESS, _store_comm(sub, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def comm_compare(ha: int, hb: int):
    """MPI_Comm_compare: IDENT(0)/CONGRUENT(1)/SIMILAR(2)/UNEQUAL(3)."""
    try:
        ca, cb = _comm(ha), _comm(hb)
        if ca is cb:
            return (MPI_SUCCESS, 0)
        ra = list(getattr(ca, "group").ranks)
        rb = list(getattr(cb, "group").ranks)
        if ra == rb:
            return (MPI_SUCCESS, 1)
        if sorted(ra) == sorted(rb):
            return (MPI_SUCCESS, 2)
        return (MPI_SUCCESS, 3)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


# -- errhandlers ----------------------------------------------------------


def comm_set_errhandler(h: int, eh: int) -> int:
    try:
        c = _comm(h)
        if eh not in (ERRH_FATAL, ERRH_RETURN):
            raise err.MPIArgError(f"invalid errhandler handle {eh}")
        _errhandlers[h] = eh
        from ompi_tpu.core import errors as _err

        c.set_errhandler(
            _err.ERRORS_ARE_FATAL if eh == ERRH_FATAL else _err.ERRORS_RETURN
        )
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def comm_get_errhandler(h: int):
    try:
        _comm(h)
        return (MPI_SUCCESS, _errhandlers.get(h, ERRH_FATAL))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


# -- derived datatypes (≈ ompi/datatype constructors over ddt/) -----------


def _ddt(dtcode: int):
    """Datatype for a C handle: derived registry, or predefined leaf."""
    d = _dtypes.get(dtcode)
    if d is not None:
        return d
    from ompi_tpu.ddt.datatype import from_numpy_dtype

    dt = DTYPES.get(dtcode)
    if dt is None:
        raise err.MPITypeError(f"unsupported C datatype code {dtcode}")
    return from_numpy_dtype(dt)


def _store_dtype(d) -> int:
    global _next_dtype
    _next_dtype += 1
    _dtypes[_next_dtype] = d
    return _next_dtype


def type_contiguous(count: int, base: int):
    try:
        code = _store_dtype(_ddt(base).create_contiguous(count))
        _record_envelope(code, 3, [count], [], [base])
        return (MPI_SUCCESS, code)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def type_vector(count: int, blocklength: int, stride: int, base: int):
    try:
        d = _ddt(base).create_vector(count, blocklength, stride)
        code = _store_dtype(d)
        _record_envelope(code, 4, [count, blocklength, stride], [], [base])
        return (MPI_SUCCESS, code)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def type_indexed(count: int, bl_ptr: int, disp_ptr: int, base: int):
    try:
        bls = [int(v) for v in _view(bl_ptr, count, 7)]
        disps = [int(v) for v in _view(disp_ptr, count, 7)]
        d = _ddt(base).create_indexed(bls, disps)
        code = _store_dtype(d)
        _record_envelope(code, 6, [count] + bls + disps, [], [base])
        return (MPI_SUCCESS, code)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def type_commit(dtcode: int) -> int:
    try:
        d = _dtypes.get(dtcode)
        if d is not None:
            d.commit()
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def type_free(dtcode: int) -> int:
    _dtypes.pop(dtcode, None)
    return MPI_SUCCESS


def type_get_extent(dtcode: int):
    try:
        d = _ddt(dtcode)
        return (MPI_SUCCESS, int(d.lb), int(d.extent))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0, 0)


def _pack_from(ptr: int, count: int, dtcode: int) -> np.ndarray:
    """Read `count` elements of a (possibly derived) datatype from a C
    buffer into a packed contiguous array (leaf-typed when uniform) —
    the convertor's pack path (SURVEY.md §3.3)."""
    d = _dtypes.get(dtcode)
    if d is None:
        return _view(ptr, count, dtcode).copy()
    from ompi_tpu.ddt.convertor import pack, packed_to_typed

    span = d.lb + d.extent * count
    raw = (ctypes.c_ubyte * max(span, 1)).from_address(ptr)
    buf = np.frombuffer(raw, dtype=np.uint8)
    packed = pack(buf, d, count)
    if d.uniform_leaf is not None:
        return packed_to_typed(packed, d, count)
    return packed


def _unpack_into(ptr: int, count: int, dtcode: int, data: np.ndarray) -> int:
    """Write packed/typed data into a C buffer laid out as `count`
    elements of a (possibly derived) datatype; returns elements written."""
    d = _dtypes.get(dtcode)
    if d is None:
        flat = np.asarray(data).reshape(-1).view(DTYPES[dtcode])
        got = min(flat.size, count)
        _view(ptr, got, dtcode)[:] = flat[:got]
        return got
    from ompi_tpu.ddt.convertor import unpack

    span = d.lb + d.extent * count
    raw = (ctypes.c_ubyte * max(span, 1)).from_address(ptr)
    buf = np.frombuffer(raw, dtype=np.uint8)
    payload = np.asarray(data).reshape(-1).view(np.uint8)
    n_elems = min(count, payload.nbytes // max(d.size, 1))
    unpack(buf, d, n_elems, payload[: n_elems * d.size])
    return n_elems


# -- v-collectives (jagged counts/displacements) --------------------------


def _vparams(ptr_counts: int, ptr_displs: int, n: int):
    counts = [int(v) for v in _view(ptr_counts, n, 7)]
    displs = [int(v) for v in _view(ptr_displs, n, 7)]
    return counts, displs


def allgatherv(sptr, scount, sdt, rptr, rcounts_ptr, displs_ptr, rdt, h) -> int:
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        counts, displs = _vparams(rcounts_ptr, displs_ptr, n)
        me = comm_rank(h)[1]
        if sptr == _IN_PLACE:
            base = _view(rptr, displs[me] + counts[me], rdt)
            x = base[displs[me] : displs[me] + counts[me]].copy()
        else:
            x = _view(sptr, scount, sdt).copy()
        if _is_single_controller(c):
            blocks = c.allgatherv([x] * n) if n > 1 else [x]
        else:
            blocks = c.allgatherv([x])
        item = DTYPES[rdt].itemsize
        for r in range(n):
            dst = _view(rptr + displs[r] * item, counts[r], rdt)
            dst[:] = np.asarray(blocks[r]).reshape(-1).view(DTYPES[rdt])[: counts[r]]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def gatherv(sptr, scount, sdt, rptr, rcounts_ptr, displs_ptr, rdt, root, h) -> int:
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        me = comm_rank(h)[1]
        if sptr == _IN_PLACE:  # root's block already in recvbuf
            counts, displs = _vparams(rcounts_ptr, displs_ptr, n)
            item = DTYPES[rdt].itemsize
            x = _view(rptr + displs[me] * item, counts[me], rdt).copy()
        else:
            x = _view(sptr, scount, sdt).copy()
        if _is_single_controller(c):
            blocks = c.gatherv([x] * n if n > 1 else [x], root)
        else:
            blocks = c.gatherv([x], root)
        if me == root:
            counts, displs = _vparams(rcounts_ptr, displs_ptr, n)
            item = DTYPES[rdt].itemsize
            for r in range(n):
                dst = _view(rptr + displs[r] * item, counts[r], rdt)
                dst[:] = (
                    np.asarray(blocks[r]).reshape(-1).view(DTYPES[rdt])[: counts[r]]
                )
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def scatterv(sptr, scounts_ptr, displs_ptr, sdt, rptr, rcount, rdt, root, h) -> int:
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        me = comm_rank(h)[1]
        blocks = None
        if me == root:
            counts, displs = _vparams(scounts_ptr, displs_ptr, n)
            item = DTYPES[sdt].itemsize
            blocks = [
                _view(sptr + displs[r] * item, counts[r], sdt).copy()
                for r in range(n)
            ]
        out = c.scatterv(blocks, root)
        mine = out[0] if not _is_single_controller(c) else out[me]
        got = min(rcount, np.asarray(mine).size)
        if rptr != _IN_PLACE and got:
            _view(rptr, got, rdt)[:] = (
                np.asarray(mine).reshape(-1).view(DTYPES[rdt])[:got]
            )
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


# -- dynamic process management (MPI_Comm_spawn family) -------------------


def comm_spawn(cmd: str, argv_packed: str, maxprocs: int, root: int,
               h: int):
    try:
        c = _comm(h)
        if c is not _comms.get(1):
            # spawn's rendezvous is collective over the whole world
            # (every world proc joins the merged space); sub-comm spawn
            # would deadlock the procs outside it — reject loudly
            raise err.MPICommError(
                "MPI_Comm_spawn is supported on MPI_COMM_WORLD only"
            )
        from ompi_tpu.api.spawn import spawn

        args = [a for a in argv_packed.split("\x1f") if a]
        ic = spawn([cmd] + args, maxprocs, root)
        return (MPI_SUCCESS, _store_comm(ic, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def comm_get_parent():
    try:
        from ompi_tpu.api.spawn import get_parent

        p = get_parent()
        return (MPI_SUCCESS, _store_comm(p) if p is not None else 0)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def intercomm_merge(h: int, high: int):
    try:
        ic = _comm(h)
        merged = ic.merge(bool(high))
        return (MPI_SUCCESS, _store_comm(merged, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def comm_remote_size(h: int):
    try:
        c = _comm(h)
        rs = getattr(c, "remote_size", None)
        if rs is None:
            raise err.MPICommError(f"handle {h} is not an intercommunicator")
        return (MPI_SUCCESS, int(rs))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


# -- user-defined ops (MPI_Op_create over a C callback) -------------------

#: reverse map: numpy dtype → a representative C datatype code
_DT_CODE = {}
for _code, _dt in DTYPES.items():
    _DT_CODE.setdefault(_dt, _code)

_next_op = 64  # predefined op codes stay below (OPS is the registry)


def op_create(fnptr: int, commute: int):
    """MPI_Op_create: wrap the C user function
    ``void fn(void *invec, void *inoutvec, int *len, MPI_Datatype *dt)``
    as an Op whose host kernel invokes it per fold step (invec = left
    operand, inoutvec = accumulator, per the reference's
    ompi_op_reduce convention)."""
    global _next_op
    try:
        UFN = ctypes.CFUNCTYPE(
            None, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        )
        cfn = UFN(fnptr)
        # the np_fn closure holds cfn — the trampoline lives exactly as
        # long as the Op it powers

        def np_fn(a, b):
            a = np.ascontiguousarray(a)
            out = np.array(b, copy=True)
            code = _DT_CODE.get(out.dtype)
            if code is None:
                raise err.MPITypeError(
                    f"user op: unsupported dtype {out.dtype}"
                )
            n = ctypes.c_int(out.size)
            dt = ctypes.c_int(code)
            cfn(a.ctypes.data, out.ctypes.data,
                ctypes.byref(n), ctypes.byref(dt))
            return out

        op = opmod.Op(
            f"user_op_{_next_op}", jax_fn=None, np_fn=np_fn,
            commutative=bool(commute),
        )
        handle = _next_op
        _next_op += 1
        OPS[handle] = op
        return (MPI_SUCCESS, handle)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def op_free(handle: int) -> int:
    if handle >= 64:  # predefined ops are permanent
        OPS.pop(handle, None)
    return MPI_SUCCESS


# -- comm_split_type / struct datatype / jagged reduce_scatter ------------


def comm_split_type(h: int, split_type: int, key: int):
    """MPI_Comm_split_type.  Rides the collective comm_split machinery
    (so SHARED/UNDEFINED mixes across ranks pair up and ``key``
    orders ranks per the standard).  SHARED (1) resolves to one domain
    spanning the comm: the RTE is single-host, so every process shares
    the host — a multi-host RTE would key the color by hostname from
    the modex."""
    try:
        if split_type == -32766:  # MPI_UNDEFINED
            return comm_split(h, -32766, key)
        if split_type != 1:  # MPI_COMM_TYPE_SHARED
            raise err.MPIArgError(f"unknown split_type {split_type}")
        return comm_split(h, 0, key)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def type_create_struct(count: int, bl_ptr: int, disp_ptr: int,
                       types_ptr: int):
    try:
        from ompi_tpu.ddt.datatype import create_struct

        bls = [int(v) for v in _view(bl_ptr, count, 7)]
        disps = [int(v) for v in _view(disp_ptr, count, 20)]  # MPI_Aint
        codes = [int(v) for v in _view(types_ptr, count, 7)]
        d = create_struct(bls, disps, [_ddt(c) for c in codes])
        code = _store_dtype(d)
        _record_envelope(code, 10, [count] + bls, disps, codes)
        return (MPI_SUCCESS, code)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def reduce_scatter(sptr, rptr, counts_ptr, dtcode, opcode, h) -> int:
    """MPI_Reduce_scatter with per-rank counts (jagged allowed).
    Equal counts route through the block path (fabric); jagged through
    the ordered host fold."""
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        counts = [int(v) for v in _view(counts_ptr, n, 7)]
        total = sum(counts)
        me = comm_rank(h)[1]
        src = (_view(rptr, total, dtcode) if sptr == _IN_PLACE
               else _view(sptr, total, dtcode))
        if len(set(counts)) == 1:
            x = src.reshape(1, n, counts[0]).copy()
            out = c.reduce_scatter_block(x, OPS[opcode])
            mine = np.asarray(out)[me if _is_single_controller(c) else 0]
        else:
            x = src[None, :].copy()
            if _is_single_controller(c):
                # Comm.reduce_scatter validates op/dtype + counts and
                # takes the (n, total) whole-comm shape
                out = c.reduce_scatter(
                    np.broadcast_to(x[0], (n,) + x[0].shape).copy(),
                    OPS[opcode], counts,
                )
                mine = out[me]
            else:
                out = c.reduce_scatter(x, OPS[opcode], counts)
                mine = out[0]
        got = min(counts[me], int(np.asarray(mine).size))
        if got:
            _view(rptr, got, dtcode)[:] = (
                np.asarray(mine).reshape(-1).view(DTYPES[dtcode])[:got]
            )
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


# -- one-sided (MPI_Win_* over the DCN osc / single-controller osc) -------

_wins: dict[int, object] = {}
_next_win_h = 1


def _win(h: int):
    w = _wins.get(h)
    if w is None:
        raise err.MPIWinError(f"invalid window handle {h}")
    return w


def win_create(base_ptr: int, size_bytes: int, disp_unit: int, h: int):
    """MPI_Win_create: expose `size_bytes` of caller memory.  The
    window views the C memory zero-copy (puts land in the C array)."""
    global _next_win_h
    try:
        c = _comm(h)
        nbytes = int(size_bytes)
        if nbytes > 0:
            raw = (ctypes.c_ubyte * nbytes).from_address(base_ptr)
            base = np.frombuffer(raw, dtype=np.uint8)
        else:
            base = np.zeros(0, np.uint8)
        if _is_single_controller(c):
            from ompi_tpu.osc.win import Win

            # standalone: a size-1 world — per-rank bases is just ours
            w = Win.create(c, [base])
        else:
            w = c.win_create([base])
        w._disp_unit = max(1, int(disp_unit))
        handle = _next_win_h
        _next_win_h += 1
        _wins[handle] = w
        return (MPI_SUCCESS, handle)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def win_free(wh: int) -> int:
    try:
        w = _wins.pop(wh, None)
        if w is not None:
            w.free()
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_fence(wh: int, assertion: int) -> int:
    try:
        _win(wh).fence(assertion)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def _is_dist_win(w) -> bool:
    """MultiProcWin (DCN windows) vs the single-controller Win."""
    return not _is_single_controller(w.comm)


def _win_elem_disp(w, tdisp: int, dt) -> int:
    byte_disp = int(tdisp) * w._disp_unit
    if byte_disp % dt.itemsize:
        raise err.MPIWinError(
            f"displacement {tdisp} (x{w._disp_unit}B) not aligned to "
            f"{dt.itemsize}-byte elements"
        )
    return byte_disp // dt.itemsize


def win_type_error() -> int:
    """Shim helper: asymmetric origin/target type signatures are
    unsupported — raised HERE so the comm errhandler applies (the
    default ARE_FATAL aborts instead of silently skipping the op)."""
    return _fail(err.MPITypeError(
        "RMA origin and target type/count must match in this "
        "implementation"
    ), 1)


def win_put(wh: int, optr: int, count: int, dtcode: int, target: int,
            tdisp: int) -> int:
    try:
        w = _win(wh)
        dt = DTYPES[dtcode]
        data = _view(optr, count, dtcode).copy()
        e0 = _win_elem_disp(w, tdisp, dt)
        if _is_dist_win(w):
            w.put(target, data, disp=e0, dt=dt)
        else:
            w.memory(target).view(dt)[e0 : e0 + count] = data
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_get(wh: int, optr: int, count: int, dtcode: int, target: int,
            tdisp: int) -> int:
    try:
        w = _win(wh)
        dt = DTYPES[dtcode]
        e0 = _win_elem_disp(w, tdisp, dt)
        if _is_dist_win(w):
            out = w.get(target, count, disp=e0, dt=dt)
        else:
            out = w.memory(target).view(dt)[e0 : e0 + count]
        _view(optr, count, dtcode)[:] = np.asarray(out).reshape(-1)[:count]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_accumulate(wh: int, optr: int, count: int, dtcode: int,
                   target: int, tdisp: int, opcode: int) -> int:
    try:
        w = _win(wh)
        dt = DTYPES[dtcode]
        data = _view(optr, count, dtcode).copy()
        op = OPS[opcode]
        e0 = _win_elem_disp(w, tdisp, dt)
        if _is_dist_win(w):
            w.accumulate(target, data, disp=e0, op=op, dt=dt)
        else:
            seg = w.memory(target).view(dt)[e0 : e0 + count]
            seg[:] = data if op is opmod.REPLACE else op.np_fn(seg, data)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_fetch_and_op(wh: int, optr: int, rptr: int, dtcode: int,
                     target: int, tdisp: int, opcode: int) -> int:
    try:
        w = _win(wh)
        dt = DTYPES[dtcode]
        op = OPS[opcode]
        # MPI_NO_OP: origin buffer is irrelevant and may be NULL —
        # never dereference it (a read would segfault the interpreter)
        val = (dt.type(0) if op is opmod.NO_OP or optr == 0
               else _view(optr, 1, dtcode)[0])
        e0 = _win_elem_disp(w, tdisp, dt)
        if _is_dist_win(w):
            old = w.fetch_and_op(target, val, disp=e0, op=op, dt=dt)
        else:
            mem = w.memory(target).view(dt)
            old = mem[e0].copy()
            if op is opmod.REPLACE:
                mem[e0] = val
            elif op is not opmod.NO_OP:
                mem[e0] = op.np_fn(np.asarray(mem[e0]), np.asarray(val))
        _view(rptr, 1, dtcode)[0] = old
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_lock(wh: int, lock_type: int, target: int, assertion: int) -> int:
    try:
        w = _win(wh)
        if _is_dist_win(w):
            w.lock(target, lock_type)
        else:
            from ompi_tpu.osc import win as _oscwin

            # mpi.h: SHARED=1, EXCLUSIVE=2 — osc/win.py's constants
            # differ, so translate rather than forward the raw value
            lt = (_oscwin.LOCK_SHARED if lock_type == 1
                  else _oscwin.LOCK_EXCLUSIVE)
            w.lock(0, target, lt, assertion)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_unlock(wh: int, target: int) -> int:
    try:
        w = _win(wh)
        if _is_dist_win(w):
            w.unlock(target)
        else:
            w.unlock(0, target)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_flush(wh: int, target: int) -> int:
    try:
        w = _win(wh)
        if _is_dist_win(w):
            w.flush(target)
        else:
            w.flush(0, target)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


# -- MPI-IO (MPI_File_* over the ompio stack) -----------------------------

_files: dict[int, object] = {}
_next_file_h = 1


def _file(fh: int):
    f = _files.get(fh)
    if f is None:
        raise err.MPIFileError(f"invalid file handle {fh}")
    return f


def file_open(h: int, path: str, amode: int, info_h: int = 0):
    """MPI_File_open (collective).  Multi-process jobs open the file
    per-process over the LOCAL comm (the shared filesystem is the
    coupling, as in fs/ufs); collective completion is a comm barrier.
    Shared-file-pointer ops are therefore single-process only.
    ``info_h``: MPI_Info handle whose hints attach to the handle."""
    global _next_file_h
    try:
        c = _comm(h)
        hints = dict(_infos.get(info_h, {})) if info_h else None
        if _is_single_controller(c):
            f = c.file_open(path, amode, hints=hints)
            # authoritative shared-pointer reset: a stale <path>.shfp
            # left by an earlier job must not leak in (creator-only
            # seeding inside File.__init__ deliberately skips existing
            # side files; with one controlling process there are no
            # unsynchronized peers to protect, so reset is safe here)
            from ompi_tpu.io.file import MODE_APPEND

            f._sharedfp.set(f.get_size() if amode & MODE_APPEND else 0)
            ent = (f, False, 0, c)
        else:
            from ompi_tpu.io.file import MODE_DELETE_ON_CLOSE
            from ompi_tpu.op import MIN as _MIN

            # per-process open over the shared filesystem: exactly one
            # process (proc 0) carries DELETE_ON_CLOSE, so the first
            # close cannot delete the file out from under the others
            amode_local = amode
            if (amode & MODE_DELETE_ON_CLOSE) and c.proc != 0:
                amode_local &= ~MODE_DELETE_ON_CLOSE
            f = exc = None
            try:
                f = c.local.file_open(path, amode_local, hints=hints)
            except err.MPIError as e2:
                exc = e2
            # collective success agreement: a one-sided failure must
            # not leave the successful openers stuck in a barrier
            ok = c.allreduce(
                np.full((c.local_size, 1), 0.0 if exc else 1.0), _MIN
            )
            if float(np.asarray(ok).min()) < 1.0:
                if f is not None:
                    f.close()
                raise exc if exc is not None else err.MPIFileError(
                    f"collective open of {path!r} failed on a peer process"
                )
            # shared-pointer epoch: every peer's open (and creator-only
            # seed) is complete by the agreement above, so one
            # designated process now authoritatively resets the
            # cross-process pointer (a stale <path>.shfp from an
            # earlier job on the same path must not leak in), and a
            # second barrier orders that reset before any peer's
            # write_shared/read_shared
            if c.proc == 0:
                from ompi_tpu.io.file import MODE_APPEND

                f._sharedfp.set(f.get_size() if amode & MODE_APPEND
                                else 0)
            c.barrier()
            ent = (f, True, 0, c)
        handle = _next_file_h
        _next_file_h += 1
        _files[handle] = ent
        return (MPI_SUCCESS, handle)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def file_set_info(fh: int, info_h: int) -> int:
    """MPI_File_set_info: merge the info's hints onto the handle
    (striping hints only matter at create time; later merges are
    recorded and surfaced, per the reference's hint semantics)."""
    try:
        f = _file(fh)[0]  # invalid/closed handle -> MPI_ERR_FILE
        if info_h:
            f.hints.update(
                {str(k): str(v) for k, v in _infos.get(info_h, {}).items()}
            )
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def file_get_info(fh: int):
    """MPI_File_get_info: a NEW info carrying the handle's effective
    hints plus the selected fs driver name."""
    try:
        f = _file(fh)[0]  # invalid/closed handle -> MPI_ERR_FILE
        _, ih = info_create()
        d = dict(f.hints)
        fs = getattr(f.component, "fs", None)
        if fs is not None and hasattr(fs, "fs_name"):
            d.setdefault("mca_fs", fs.fs_name(f._fd))
        _infos[ih] = d
        return (MPI_SUCCESS, ih)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_close(fh: int) -> int:
    """Collective close: multi-process files barrier first so the
    DELETE_ON_CLOSE holder (proc 0) deletes only after every process
    finished its IO."""
    try:
        ent = _files.get(fh)
        if ent is not None:
            if ent[1]:
                ent[3].barrier()
            ent[0].close()
            _files.pop(fh, None)  # only a completed close releases
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def file_get_size(fh: int):
    try:
        return (MPI_SUCCESS, int(_file(fh)[0].get_size()))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_set_size(fh: int, size: int) -> int:
    try:
        _file(fh)[0].set_size(int(size))
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def file_seek(fh: int, offset: int, whence: int) -> int:
    try:
        f, multi, _r, _c = _file(fh)
        f.seek(0, int(offset), int(whence))
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def _dense_read_clamp(f, byte_start: int, count: int, itemsize: int) -> int:
    """MPI requires a reduced count at EOF.  For dense views (filetype
    == etype: the byte-stream default) the available bytes are exactly
    file size − start; exotic filetype maps keep the requested count
    (the io engine zero-fills holes by design)."""
    disp, etype, filetype = f.get_view(0)
    if filetype.size != etype.size:
        return count
    avail = max(0, f.get_size() - (disp + byte_start))
    return min(count, avail // max(1, itemsize))


def _etype_units(f, nbytes: int) -> int:
    """C counts are datatype elements; the io layer counts etypes of
    the current view — convert (must divide exactly)."""
    esize = f.get_view(0)[1].size
    if nbytes % max(1, esize):
        raise err.MPIArgError(
            f"{nbytes} B is not a whole number of view etypes ({esize} B)"
        )
    return nbytes // max(1, esize)


def file_write_at(fh: int, offset: int, ptr: int, count: int,
                  dtcode: int):
    try:
        f = _file(fh)[0]
        data = _pack_from(ptr, count, dtcode)
        dt_size = (_dtypes[dtcode].size if dtcode in _dtypes
                   else DTYPES[dtcode].itemsize)
        written = f.write_at(0, int(offset), np.asarray(data))
        esize = f.get_view(0)[1].size
        return (MPI_SUCCESS,
                (written * esize // max(1, dt_size)) * dt_size)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_read_at(fh: int, offset: int, ptr: int, count: int, dtcode: int):
    try:
        f = _file(fh)[0]
        dt = DTYPES.get(dtcode)
        if dt is None:
            raise err.MPITypeError(f"unsupported datatype {dtcode}")
        esize = f.get_view(0)[1].size
        count = _dense_read_clamp(f, int(offset) * esize, count, dt.itemsize)
        units = _etype_units(f, count * dt.itemsize)
        out = f.read_at(0, int(offset), units, dtype=dt)
        got = int(np.asarray(out).size)
        if got:
            _view(ptr, got, dtcode)[:] = np.asarray(out).reshape(-1)
        return (MPI_SUCCESS, got * _unit_nbytes(dtcode))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_write(fh: int, ptr: int, count: int, dtcode: int):
    try:
        f = _file(fh)[0]
        data = _pack_from(ptr, count, dtcode)
        written = f.write(0, np.asarray(data))
        esize = f.get_view(0)[1].size
        dt_size = (_dtypes[dtcode].size if dtcode in _dtypes
                   else DTYPES[dtcode].itemsize)
        return (MPI_SUCCESS,
                (written * esize // max(1, dt_size)) * dt_size)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_read(fh: int, ptr: int, count: int, dtcode: int):
    try:
        f = _file(fh)[0]
        dt = DTYPES.get(dtcode)
        if dt is None:
            raise err.MPITypeError(f"unsupported datatype {dtcode}")
        esize = f.get_view(0)[1].size
        count = _dense_read_clamp(f, f.get_position(0) * esize, count,
                                  dt.itemsize)
        out = f.read(0, _etype_units(f, count * dt.itemsize), dtype=dt)
        got = int(np.asarray(out).size)
        if got:
            _view(ptr, got, dtcode)[:] = np.asarray(out).reshape(-1)
        return (MPI_SUCCESS, got * _unit_nbytes(dtcode))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_write_at_all(fh: int, offset: int, ptr: int, count: int,
                      dtcode: int):
    """Collective write: independent data movement + completion
    barrier (the fcoll two-phase optimization applies in the
    single-controller engine; across processes the filesystem is the
    aggregator)."""
    try:
        ent = _file(fh)
        rc = file_write_at(fh, offset, ptr, count, dtcode)
        if ent[1]:
            ent[3].barrier()
        return rc
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_read_at_all(fh: int, offset: int, ptr: int, count: int,
                     dtcode: int):
    try:
        ent = _file(fh)
        if ent[1]:
            ent[3].barrier()  # writers before us have completed
        return file_read_at(fh, offset, ptr, count, dtcode)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_set_view(fh: int, disp: int, etype_code: int, filetype_code: int):
    try:
        f = _file(fh)[0]
        f.set_view(0, int(disp), _ddt(etype_code), _ddt(filetype_code))
        _file_view_codes[fh] = (int(disp), etype_code, filetype_code)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


# -- probe / name / error utilities --------------------------------------


def iprobe(source: int, tag: int, h: int):
    """MPI_Iprobe: (flag, source, tag, nbytes) — payload BYTES (the C
    status unit; PMPI_Get_count divides by the queried type's size)."""
    try:
        c = _comm(h)
        me = comm_rank(h)[1]
        st = c.iprobe(me, None if source == -1 else source,
                      None if tag == -1 else tag)
        if st is None:
            return (MPI_SUCCESS, 0, -1, -1, 0)
        return (MPI_SUCCESS, 1, int(st.source), int(st.tag), int(st.nbytes))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0, -1, -1, 0)


def probe(source: int, tag: int, h: int):
    """MPI_Probe (blocking); count slot in payload BYTES."""
    try:
        c = _comm(h)
        me = comm_rank(h)[1]
        st = c.probe(me, None if source == -1 else source,
                     None if tag == -1 else tag)
        return (MPI_SUCCESS, int(st.source), int(st.tag), int(st.nbytes))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), -1, -1, 0)


def comm_get_name(h: int):
    try:
        return (MPI_SUCCESS, str(_comm(h).name))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), "")


# -- MPI_T tool interface -------------------------------------------------


def t_init() -> int:
    try:
        from ompi_tpu.tool import mpit

        mpit.init_thread()
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _t_fail(e)


def t_finalize() -> int:
    try:
        from ompi_tpu.tool import mpit

        mpit.finalize()
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _t_fail(e)


def t_cvar_get_num():
    try:
        from ompi_tpu.tool import mpit

        return (MPI_SUCCESS, int(mpit.cvar_get_num()))
    except BaseException as e:  # noqa: BLE001
        return (_t_fail(e), 0)


def t_cvar_get_name(index: int):
    try:
        from ompi_tpu.tool import mpit

        return (MPI_SUCCESS, str(mpit.cvar_get_info(index).name))
    except BaseException as e:  # noqa: BLE001
        return (_t_fail(e), "")


def t_cvar_read(index: int):
    """Integer/bool cvars only (the C shim's _int reader): non-integer
    cvars return an error instead of a fabricated value."""
    try:
        from ompi_tpu.tool import mpit

        v = mpit.cvar_read(index)
        if isinstance(v, bool) or isinstance(v, int):
            return (MPI_SUCCESS, int(v))
        raise err.MPIArgError(
            f"cvar {index} is not integer-valued (use the string reader)"
        )
    except BaseException as e:  # noqa: BLE001
        return (_t_fail(e), 0)


def t_cvar_index(name: str):
    try:
        from ompi_tpu.tool import mpit

        return (MPI_SUCCESS, int(mpit.cvar_index(name)))
    except BaseException as e:  # noqa: BLE001
        return (_t_fail(e), -1)


def t_pvar_get_num():
    try:
        from ompi_tpu.tool import mpit

        return (MPI_SUCCESS, int(mpit.pvar_get_num()))
    except BaseException as e:  # noqa: BLE001
        return (_t_fail(e), 0)


def t_pvar_read(index: int):
    try:
        from ompi_tpu.tool import mpit

        v = mpit.pvar_read(index)
        # array-valued pvars (trace latency histograms) collapse to
        # their total through the scalar C surface
        return (MPI_SUCCESS, int(sum(v) if isinstance(v, list) else v))
    except BaseException as e:  # noqa: BLE001
        return (_t_fail(e), 0)


def t_pvar_index(name: str):
    try:
        from ompi_tpu.tool import mpit

        return (MPI_SUCCESS, int(mpit.pvar_index(name)))
    except BaseException as e:  # noqa: BLE001
        return (_t_fail(e), -1)


_pvar_starts = 0


def t_pvar_start() -> int:
    """Refcounted: SPC attachment is process-global, so counting stays
    on until the LAST started handle stops (stopping one handle must
    not silently freeze another's counter)."""
    global _pvar_starts
    try:
        from ompi_tpu.tool import mpit

        mpit.pvar_start()
        _pvar_starts += 1
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _t_fail(e)


def t_pvar_stop() -> int:
    global _pvar_starts
    try:
        from ompi_tpu.tool import mpit

        _pvar_starts = max(0, _pvar_starts - 1)
        if _pvar_starts == 0:
            mpit.pvar_stop()
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _t_fail(e)


# -- cartesian topology (MPI_Cart_* / MPI_Dims_create) --------------------

_carts: dict[int, tuple[list[int], list[int]]] = {}  # comm handle → geometry


def dims_create(nnodes: int, ndims: int, dims_ptr: int) -> int:
    try:
        from ompi_tpu.api.topo import dims_create as _dc

        view = _view(dims_ptr, ndims, 7)
        out = _dc(nnodes, ndims, [int(v) for v in view])
        view[:] = out
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def cart_create(h: int, ndims: int, dims_ptr: int, periods_ptr: int,
                reorder: int):
    """MPI_Cart_create: geometry over the first prod(dims) ranks (ranks
    beyond get MPI_COMM_NULL) — rides the collective comm_split."""
    try:
        import math

        from ompi_tpu.api.topo import validate_dims

        c = _comm(h)
        dims = [int(v) for v in _view(dims_ptr, ndims, 7)]
        periods = [int(v) for v in _view(periods_ptr, ndims, 7)]
        validate_dims(dims)
        del reorder  # rank order already ICI-contiguous (topo reorder
        # is the accelerator component's device-order job)
        nnodes = math.prod(dims)
        if nnodes > getattr(c, "size", 1):
            raise err.MPIDimsError(
                f"cartesian grid {dims} needs {nnodes} ranks; comm has "
                f"{c.size}"
            )
        rc, ch = _split_prefix(h, nnodes)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        if ch:
            _carts[ch] = (dims, periods)
        return (MPI_SUCCESS, ch)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def _split_prefix(h: int, nnodes: int):
    """Collective split keeping the first ``nnodes`` ranks (others get
    MPI_COMM_NULL) — correct in BOTH models: the single-controller
    split takes per-rank colors; the distributed one this process's."""
    c = _comm(h)
    if _is_single_controller(c):
        n = c.size
        colors = [0] * nnodes + [-32766] * (n - nnodes)
        sub = c.split(colors, [0] * n)[0] if nnodes else None
        return (MPI_SUCCESS, _store_comm(sub, h) if sub is not None else 0)
    me = comm_rank(h)[1]
    return comm_split(h, 0 if me < nnodes else -32766, 0)


def _cart_geom(h: int):
    _comm(h)  # liveness: freed comms lose their topology too
    g = _carts.get(h)
    if g is None:
        raise err.MPITopologyError(f"comm {h} has no cartesian topology")
    return g


def cartdim_get(h: int):
    try:
        return (MPI_SUCCESS, len(_cart_geom(h)[0]))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def cart_get(h: int, maxdims: int, dims_ptr: int, periods_ptr: int,
             coords_ptr: int) -> int:
    try:
        dims, periods = _cart_geom(h)
        nd = min(maxdims, len(dims))
        _view(dims_ptr, nd, 7)[:] = dims[:nd]
        _view(periods_ptr, nd, 7)[:] = periods[:nd]
        me = comm_rank(h)[1]
        _view(coords_ptr, nd, 7)[:] = _coords_of(dims, me)[:nd]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def _coords_of(dims: list[int], rank: int) -> list[int]:
    from ompi_tpu.api.topo import cart_coords_of

    return cart_coords_of(dims, rank)


def _rank_of(dims: list[int], periods: list[int], coords: list[int]) -> int:
    from ompi_tpu.api.topo import cart_rank_of

    return cart_rank_of(dims, periods, coords)


def cart_rank(h: int, coords_ptr: int):
    try:
        dims, periods = _cart_geom(h)
        coords = [int(v) for v in _view(coords_ptr, len(dims), 7)]
        return (MPI_SUCCESS, _rank_of(dims, periods, coords))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def cart_coords(h: int, rank: int, maxdims: int, coords_ptr: int) -> int:
    try:
        dims, _ = _cart_geom(h)
        nd = min(maxdims, len(dims))
        _view(coords_ptr, nd, 7)[:] = _coords_of(dims, rank)[:nd]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def cart_shift(h: int, direction: int, disp: int):
    """(rank_source, rank_dest); MPI_PROC_NULL (-2) off non-periodic
    edges."""
    try:
        dims, periods = _cart_geom(h)
        me = comm_rank(h)[1]
        coords = _coords_of(dims, me)

        def shifted(sign: int) -> int:
            c2 = list(coords)
            c2[direction] += sign * disp
            try:
                return _rank_of(dims, periods, c2)
            except err.MPIArgError:
                return -2  # MPI_PROC_NULL

        return (MPI_SUCCESS, shifted(-1), shifted(+1))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), -2, -2)


# -- graph topology (MPI_Graph_*) ----------------------------------------

_graphs: dict[int, tuple[list[int], list[int]]] = {}  # handle → (index, edges)


def graph_create(h: int, nnodes: int, index_ptr: int, edges_ptr: int,
                 reorder: int):
    """MPI_Graph_create over the collective comm_split (ranks beyond
    nnodes get MPI_COMM_NULL)."""
    try:
        c = _comm(h)
        from ompi_tpu.api.topo import validate_graph

        index = [int(v) for v in _view(index_ptr, nnodes, 7)]
        nedges = index[-1] if index else 0
        if nedges < 0:
            raise err.MPIArgError(f"negative edge count from index {index}")
        edges = [int(v) for v in _view(edges_ptr, nedges, 7)]
        del reorder
        if nnodes > getattr(c, "size", 1):
            raise err.MPITopologyError(
                f"graph of {nnodes} nodes larger than comm ({c.size})"
            )
        validate_graph(index, edges)
        rc, ch = _split_prefix(h, nnodes)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        if ch:
            _graphs[ch] = (index, edges)
        return (MPI_SUCCESS, ch)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def _graph_geom(h: int):
    _comm(h)  # liveness
    g = _graphs.get(h)
    if g is None:
        raise err.MPITopologyError(f"comm {h} has no graph topology")
    return g


def graphdims_get(h: int):
    try:
        index, edges = _graph_geom(h)
        return (MPI_SUCCESS, len(index), len(edges))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0, 0)


def graph_neighbors_count(h: int, rank: int):
    try:
        from ompi_tpu.api.topo import graph_neighbors_of

        index, edges = _graph_geom(h)
        return (MPI_SUCCESS, len(graph_neighbors_of(index, edges, rank)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def graph_neighbors(h: int, rank: int, maxn: int, out_ptr: int) -> int:
    try:
        from ompi_tpu.api.topo import graph_neighbors_of

        index, edges = _graph_geom(h)
        ns = graph_neighbors_of(index, edges, rank)[:maxn]
        if ns:
            _view(out_ptr, len(ns), 7)[:] = ns
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


# ======================================================================
# Round-3 C ABI breadth (VERDICT r2 missing #1): pack/unpack, alltoallv,
# reduce_local, sendrecv_replace, attributes/keyvals, Info objects,
# persistent p2p, i-variant collectives, error classes.
# ======================================================================

# -- MPI_Pack / MPI_Unpack (the convertor exposed at the C surface) ----


def pack_size(incount: int, dtcode: int):
    try:
        d = _dtypes.get(dtcode)
        size = d.size * incount if d is not None \
            else DTYPES[dtcode].itemsize * incount
        return (MPI_SUCCESS, int(size))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def pack(inptr: int, incount: int, dtcode: int, outptr: int, outsize: int,
         position: int):
    """MPI_Pack: convertor-pack `incount` elements into outbuf at
    `position`; returns (err, new_position)."""
    try:
        data = _pack_from(inptr, incount, dtcode)
        raw = np.ascontiguousarray(data).view(np.uint8).reshape(-1)
        if position + raw.nbytes > outsize:
            raise err.MPIArgError(
                f"pack overflow: {position}+{raw.nbytes} > {outsize}")
        dst = (ctypes.c_ubyte * outsize).from_address(outptr)
        np.frombuffer(dst, np.uint8)[position : position + raw.nbytes] = raw
        return (MPI_SUCCESS, position + raw.nbytes)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), position)


def unpack(inptr: int, insize: int, position: int, outptr: int,
           outcount: int, dtcode: int):
    """MPI_Unpack: convertor-unpack from the packed buffer at
    `position`; returns (err, new_position)."""
    try:
        d = _dtypes.get(dtcode)
        nbytes = (d.size if d is not None
                  else DTYPES[dtcode].itemsize) * outcount
        if position + nbytes > insize:
            raise err.MPIArgError(
                f"unpack overflow: {position}+{nbytes} > {insize}")
        src = (ctypes.c_ubyte * insize).from_address(inptr)
        payload = np.frombuffer(src, np.uint8)[
            position : position + nbytes].copy()
        _unpack_into(outptr, outcount, dtcode, payload)
        return (MPI_SUCCESS, position + nbytes)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), position)


def pack_external(inptr: int, incount: int, dtcode: int, outptr: int,
                  outsize: int, position: int):
    """MPI_Pack_external("external32"): big-endian canonical layout."""
    try:
        data = _pack_from(inptr, incount, dtcode)
        big = np.ascontiguousarray(data)
        if big.dtype.byteorder != ">":
            big = big.astype(big.dtype.newbyteorder(">"))
        raw = big.view(np.uint8).reshape(-1)
        if position + raw.nbytes > outsize:
            raise err.MPIArgError("pack_external overflow")
        dst = (ctypes.c_ubyte * outsize).from_address(outptr)
        np.frombuffer(dst, np.uint8)[position : position + raw.nbytes] = raw
        return (MPI_SUCCESS, position + raw.nbytes)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), position)


def unpack_external(inptr: int, insize: int, position: int, outptr: int,
                    outcount: int, dtcode: int):
    try:
        d = _dtypes.get(dtcode)
        base = DTYPES[dtcode] if d is None else np.dtype(
            d.uniform_leaf.np_dtype if d.uniform_leaf is not None else np.uint8)
        nbytes = (d.size if d is not None else base.itemsize) * outcount
        if position + nbytes > insize:
            raise err.MPIArgError("unpack_external overflow")
        src = (ctypes.c_ubyte * insize).from_address(inptr)
        payload = np.frombuffer(src, np.uint8)[
            position : position + nbytes].copy()
        native = payload.view(base.newbyteorder(">")).astype(base)
        _unpack_into(outptr, outcount, dtcode, native.view(np.uint8))
        return (MPI_SUCCESS, position + nbytes)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), position)


# -- MPI_Reduce_local / MPI_Op_commutative ------------------------------


def reduce_local(inptr: int, inoutptr: int, count: int, dtcode: int,
                 opcode: int) -> int:
    try:
        op = OPS[opcode]
        a = _view(inptr, count, dtcode)
        b = _view(inoutptr, count, dtcode)
        b[:] = op.np_fn(a, b)  # MPI: inout = in ⊕ inout (in = left operand)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def op_commutative(opcode: int):
    try:
        return (MPI_SUCCESS, 1 if OPS[opcode].commutative else 0)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


# -- MPI_Sendrecv_replace ----------------------------------------------


def sendrecv_replace(ptr: int, count: int, dtcode: int, dest: int,
                     sendtag: int, source: int, recvtag: int, h: int):
    try:
        c = _comm(h)
        me = comm_rank(h)[1]
        buf = _view(ptr, count, dtcode).copy()
        c.send(buf, me, dest, sendtag)
        req = c.irecv(
            me,
            None if source == -1 else source,
            None if recvtag == -1 else recvtag,
        )
        payload = req.wait()
        st = req.status
        got = _unpack_into(ptr, count, dtcode, payload)
        return (MPI_SUCCESS, int(st.source), int(st.tag),
                got * _unit_nbytes(dtcode))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), -1, -1, 0)


# -- MPI_Alltoallv ------------------------------------------------------


def alltoallv(sptr, scounts_ptr, sdispls_ptr, sdt, rptr, rcounts_ptr,
              rdispls_ptr, rdt, h) -> int:
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        me = comm_rank(h)[1]
        scounts, sdispls = _vparams(scounts_ptr, sdispls_ptr, n)
        rcounts, rdispls = _vparams(rcounts_ptr, rdispls_ptr, n)
        sitem = DTYPES[sdt].itemsize
        row = [
            _view(sptr + sdispls[j] * sitem, scounts[j], sdt).copy()
            for j in range(n)
        ]
        if _is_single_controller(c):
            matrix = [row] * n if n > 1 else [row]
            out = c.alltoallv(matrix)
            mine = out[me]
        else:
            out = c.alltoallv([row])
            mine = out[0]
        ritem = DTYPES[rdt].itemsize
        for j in range(n):
            got = min(rcounts[j], int(np.asarray(mine[j]).size))
            if got:
                dst = _view(rptr + rdispls[j] * ritem, got, rdt)
                dst[:] = np.asarray(mine[j]).reshape(-1).view(
                    DTYPES[rdt])[:got]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


# -- eager i-variants (completion-at-issue is MPI-legal) ---------------


def ireduce(sptr, rptr, count, dtcode, opcode, root, h):
    try:
        return _eager_coll(
            lambda: reduce(sptr, rptr, count, dtcode, opcode, root, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def iscan(sptr, rptr, count, dtcode, opcode, h):
    try:
        return _eager_coll(lambda: scan(sptr, rptr, count, dtcode, opcode, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def iexscan(sptr, rptr, count, dtcode, opcode, h):
    try:
        return _eager_coll(
            lambda: exscan(sptr, rptr, count, dtcode, opcode, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def igather(sptr, scount, sdt, rptr, rcount, rdt, root, h):
    try:
        return _eager_coll(
            lambda: gather(sptr, scount, sdt, rptr, rcount, rdt, root, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def iscatter(sptr, scount, sdt, rptr, rcount, rdt, root, h):
    try:
        return _eager_coll(
            lambda: scatter(sptr, scount, sdt, rptr, rcount, rdt, root, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def igatherv(sptr, scount, sdt, rptr, rcounts_ptr, displs_ptr, rdt, root, h):
    try:
        return _eager_coll(
            lambda: gatherv(sptr, scount, sdt, rptr, rcounts_ptr,
                            displs_ptr, rdt, root, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def iscatterv(sptr, scounts_ptr, displs_ptr, sdt, rptr, rcount, rdt, root, h):
    try:
        return _eager_coll(
            lambda: scatterv(sptr, scounts_ptr, displs_ptr, sdt, rptr,
                             rcount, rdt, root, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def iallgatherv(sptr, scount, sdt, rptr, rcounts_ptr, displs_ptr, rdt, h):
    try:
        return _eager_coll(
            lambda: allgatherv(sptr, scount, sdt, rptr, rcounts_ptr,
                               displs_ptr, rdt, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def ialltoallv(sptr, scounts_ptr, sdispls_ptr, sdt, rptr, rcounts_ptr,
               rdispls_ptr, rdt, h):
    try:
        return _eager_coll(
            lambda: alltoallv(sptr, scounts_ptr, sdispls_ptr, sdt, rptr,
                              rcounts_ptr, rdispls_ptr, rdt, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def ireduce_scatter(sptr, rptr, counts_ptr, dtcode, opcode, h):
    try:
        return _eager_coll(
            lambda: reduce_scatter(sptr, rptr, counts_ptr, dtcode, opcode, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def ireduce_scatter_block(sptr, rptr, rcount, dtcode, opcode, h):
    try:
        return _eager_coll(
            lambda: reduce_scatter_block(sptr, rptr, rcount, dtcode,
                                         opcode, h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


# -- persistent point-to-point (MPI_Send_init / MPI_Start) --------------
# Entry kinds: ("pers_send", params) / ("pers_recv", params, live_req).
# Persistent handles survive wait (inactive), die on request_free.


def send_init(ptr: int, count: int, dtcode: int, dest: int, tag: int, h: int):
    try:
        _comm(h)  # validate now (MPI_ERR_COMM at init time)
        return (MPI_SUCCESS, _store_req(
            ("pers_send", None, (ptr, count, dtcode, dest, tag, h), 0, 0)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def recv_init(ptr: int, count: int, dtcode: int, source: int, tag: int,
              h: int):
    try:
        _comm(h)
        return (MPI_SUCCESS, _store_req(
            ("pers_recv", None, (ptr, count, dtcode, source, tag, h), 0, 0)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


# -- persistent collectives (MPI_Allreduce_init / MPI_Start) ------------
# The embedded-Python fallback behind the shim's C plan cache (derived
# datatypes, user/logical ops, non-fast-path comms, size-1 worlds):
# entry kind "pers_coll" carries a plan dict whose ``run`` closure was
# compiled ONCE at init — comm resolution, buffer views, op lookup,
# IN_PLACE resolution all pre-bound — and MPI_Start replays it.


def _pers_coll_req(plan: dict):
    return (MPI_SUCCESS, _store_req(("pers_coll", None, plan, 0, 0)))


def allreduce_init(sptr, rptr, count, dtcode, opcode, h):
    try:
        c = _comm(h)
        if dtcode in _dtypes:
            # derived datatype: the blocking path's convertor staging
            # dominates — replay the whole entry point per start
            return _pers_coll_req(
                {"run": lambda: allreduce(sptr, rptr, count, dtcode,
                                          opcode, h)})
        op = OPS[opcode]
        x = _coll_in(sptr, rptr, count, dtcode)
        out_v = _view(rptr, count, dtcode)

        def run() -> None:
            res = np.asarray(c.allreduce(x[None, :], op))
            out_v[:] = res.reshape(-1)[:count]

        return _pers_coll_req({"run": run})
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def bcast_init(ptr, count, dtcode, root, h):
    try:
        c = _comm(h)
        if dtcode in _dtypes:
            return _pers_coll_req(
                {"run": lambda: bcast(ptr, count, dtcode, root, h)})
        buf = _view(ptr, count, dtcode)

        def run() -> None:
            res = np.asarray(c.bcast(buf[None, :], root=root))
            buf[:] = res.reshape(-1)[:count]

        return _pers_coll_req({"run": run})
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def allgather_init(sptr, scount, sdt, rptr, rcount, rdt, h):
    try:
        c = _comm(h)
        if sdt in _dtypes or rdt in _dtypes:
            return _pers_coll_req(
                {"run": lambda: allgather(sptr, scount, sdt, rptr, rcount,
                                          rdt, h)})
        n = getattr(c, "size", 1)
        out_v = _view(rptr, rcount * n, rdt)
        if sptr == _IN_PLACE:
            me = comm_rank(h)[1]

            def run() -> None:
                x = out_v[me * rcount:(me + 1) * rcount].copy()
                res = np.asarray(c.allgather(x[None, :]))
                out_v[:] = res.reshape(-1)[:rcount * n]
        else:
            x_in = _view(sptr, scount, sdt)

            def run() -> None:
                res = np.asarray(c.allgather(x_in[None, :]))
                out_v[:] = res.reshape(-1)[:rcount * n]

        return _pers_coll_req({"run": run})
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def reduce_init(sptr, rptr, count, dtcode, opcode, root, h):
    try:
        c = _comm(h)
        if dtcode in _dtypes:
            return _pers_coll_req(
                {"run": lambda: reduce(sptr, rptr, count, dtcode, opcode,
                                       root, h)})
        op = OPS[opcode]
        x = _coll_in(sptr, rptr, count, dtcode)
        me = comm_rank(h)[1]
        out_v = (_view(rptr, count, dtcode)
                 if me == root and rptr not in (0, _IN_PLACE) else None)

        def run() -> None:
            res = np.asarray(c.reduce(x[None, :], op, root=root))
            if out_v is not None:
                out_v[:] = res.reshape(-1)[:count]

        return _pers_coll_req({"run": run})
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def barrier_init(h):
    try:
        c = _comm(h)
        return _pers_coll_req({"run": c.barrier})
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def start(rh: int) -> int:
    try:
        entry = _requests.get(rh)
        if entry is None:
            raise err.MPIRequestError(f"invalid request handle {rh}")
        kind = entry[0]
        if kind == "pers_coll":
            # replay the compiled plan (eager completion, like the
            # blocking-underneath i-collectives — MPI-legal)
            entry[2]["run"]()
            _requests[rh] = ("pers_coll", CompletedRequest(), entry[2],
                             0, 0)
            return MPI_SUCCESS
        if kind == "pers_send":
            ptr, count, dtcode, dest, tag, h = entry[2]
            rc = send(ptr, count, dtcode, dest, tag, h)
            if rc != MPI_SUCCESS:
                return rc
            _requests[rh] = ("pers_send", CompletedRequest(), entry[2], 0, 0)
            return MPI_SUCCESS
        if kind == "pers_recv":
            ptr, count, dtcode, source, tag, h = entry[2]
            c = _comm(h)
            me = comm_rank(h)[1]
            req = c.irecv(
                me,
                None if source == -1 else source,
                None if tag == -1 else tag,
            )
            _requests[rh] = ("pers_recv", req, entry[2], 0, 0)
            return MPI_SUCCESS
        raise err.MPIRequestError(f"start on non-persistent request {kind}")
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def request_free(rh: int) -> int:
    """MPI_Request_free: the handle dies now, but an ACTIVE operation
    must be allowed to run to completion (MPI 3.7.3) — including the
    delivery of a freed irecv's payload into the user buffer (the
    standard pattern: post irecv, free the handle, learn of completion
    through a later barrier).  Live requests are detached — normalized
    to a (kind, req, ptr, count, dtcode) completion record — onto a
    background list reaped opportunistically (each free / finalize);
    completion runs the same ``_complete`` delivery a wait would."""
    try:
        entry = _requests.pop(rh, None)
        _reap_freed_active()
        if entry is None:
            return MPI_SUCCESS
        kind, req = entry[0], entry[1]
        if req is None or kind in ("done", "grequest"):
            return MPI_SUCCESS
        if kind == "pers_recv":
            p = entry[2]
            norm = ("recv", req, p[0], p[1], p[2])
        elif kind == "pers_send":
            norm = ("send", req, 0, 0, 0)
        else:
            norm = entry
        if req.test():
            _finish_freed(norm)
        elif not _hook_freed_delivery(req, norm):
            _freed_active.append(norm)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


_freed_active: list = []  # detached live completion records


def _hook_freed_delivery(req, norm) -> bool:
    """Chain the request's ``_deliver`` so the user-buffer unpack runs
    the moment the payload lands (on the delivering thread) — the
    freed-irecv + barrier + read-buffer pattern must see the data
    without any further MPI library call.  Returns False when the
    request kind has no delivery hook (caller falls back to the reap
    list)."""
    orig = getattr(req, "_deliver", None)
    if orig is None or not callable(orig):
        return False
    fired = []

    def hooked(payload, status, _orig=orig):
        _orig(payload, status)
        fired.append(1)
        _finish_freed(norm)

    req._deliver = hooked
    # raced: delivered between the test() above and the hook landing
    if not fired and req.test():
        _finish_freed(norm)
    return True


def _finish_freed(norm) -> None:
    """Run a detached request's completion action (buffer delivery for
    recv/coll kinds).  Errors are swallowed: the handle is gone, so
    there is no request to report them through (MPI's liberty for
    freed requests)."""
    try:
        if norm[0] in ("recv", "coll"):
            _complete(norm)
        else:
            norm[1].wait()
    except BaseException:  # noqa: BLE001
        pass


def _reap_freed_active() -> None:
    if not _freed_active:
        return
    keep = []
    for norm in _freed_active:
        try:
            done = norm[1].test()
        except BaseException:  # noqa: BLE001
            done = True  # errored in flight: nothing left to deliver
        if done:
            _finish_freed(norm)
        else:
            keep.append(norm)
    _freed_active[:] = keep


def request_get_status(rh: int):
    """Non-destructive test: (err, flag, source, tag, count)."""
    try:
        entry = _requests.get(rh)
        if entry is None:  # completed-and-freed or NULL: flag=1
            return (MPI_SUCCESS, 1, -1, -1, 0)
        req = entry[1]
        if entry[0].startswith("pers_") and req is None:
            # inactive persistent request: complete by definition
            return (MPI_SUCCESS, 1, -1, -1, 0)
        ready = entry[0] == "done" or (req is not None and req.test())
        if not ready:
            return (MPI_SUCCESS, 0, -1, -1, 0)
        st = getattr(req, "status", None)
        if st is not None:
            return (MPI_SUCCESS, 1, int(st.source), int(st.tag), 0)
        return (MPI_SUCCESS, 1, -1, -1, 0)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0, -1, -1, 0)


# -- attributes / keyvals (MPI_Comm_create_keyval family) ---------------
# keyval table shared by comm/type/win attr surfaces (the reference
# separates namespaces; handle codes here are disjoint by construction).

_keyvals: dict[int, tuple] = {}  # kv -> (copy_fnptr, delete_fnptr, extra)
_next_keyval = 1000
_attr_tables: dict[tuple, dict] = {}  # (kind, handle) -> {kv: value}

#: predefined attribute keyvals (mpi.h codes)
KEYVAL_TAG_UB = 1
KEYVAL_HOST = 2
KEYVAL_IO = 3
KEYVAL_WTIME_IS_GLOBAL = 4
KEYVAL_UNIVERSE_SIZE = 9
KEYVAL_APPNUM = 11
KEYVAL_WIN_BASE = 5
KEYVAL_WIN_SIZE = 6
KEYVAL_WIN_DISP_UNIT = 7

_TAG_UB_VALUE = (1 << 30) - 1


def keyval_create(copy_fnptr: int, delete_fnptr: int, extra: int):
    global _next_keyval
    _next_keyval += 1
    _keyvals[_next_keyval] = (copy_fnptr, delete_fnptr, extra)
    return (MPI_SUCCESS, _next_keyval)


def keyval_free(kv: int) -> int:
    _keyvals.pop(kv, None)
    return MPI_SUCCESS


def _attrs_for(kind: str, h: int) -> dict:
    return _attr_tables.setdefault((kind, h), {})


def attr_set(kind: str, h: int, kv: int, value: int) -> int:
    try:
        if kind == "comm":
            _comm(h)  # validate handle
        _attrs_for(kind, h)[kv] = int(value)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def attr_get(kind: str, h: int, kv: int):
    """(err, flag, value).  Predefined comm keyvals resolve built-ins."""
    try:
        if kind == "comm" and kv in (
            KEYVAL_TAG_UB, KEYVAL_WTIME_IS_GLOBAL, KEYVAL_UNIVERSE_SIZE,
            KEYVAL_APPNUM, KEYVAL_HOST, KEYVAL_IO,
        ):
            if kv == KEYVAL_TAG_UB:
                return (MPI_SUCCESS, 1, _TAG_UB_VALUE)
            if kv == KEYVAL_WTIME_IS_GLOBAL:
                return (MPI_SUCCESS, 1, 0)
            if kv == KEYVAL_UNIVERSE_SIZE:
                return (MPI_SUCCESS, 1, _size)
            if kv == KEYVAL_APPNUM:
                return (MPI_SUCCESS, 1, 0)
            return (MPI_SUCCESS, 0, 0)  # HOST/IO: not set
        table = _attr_tables.get((kind, h))
        if table is None or kv not in table:
            return (MPI_SUCCESS, 0, 0)
        return (MPI_SUCCESS, 1, table[kv])
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0, 0)


def attr_delete(kind: str, h: int, kv: int) -> int:
    try:
        table = _attr_tables.get((kind, h))
        if table is not None:
            ent = _keyvals.get(kv)
            val = table.pop(kv, None)
            if ent is not None and ent[1] and val is not None:
                DFN = ctypes.CFUNCTYPE(
                    ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_void_p, ctypes.c_void_p)
                DFN(ent[1])(h, kv, val, ent[2])
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def attr_copy_on_dup(kind: str, old_h: int, new_h: int) -> None:
    """Run keyval copy callbacks at comm_dup (MPI attribute caching
    semantics: flag-returning C callbacks decide propagation)."""
    table = _attr_tables.get((kind, old_h))
    if not table:
        return
    out = {}
    for kv, val in table.items():
        ent = _keyvals.get(kv)
        if ent is None:
            continue
        copy_fn = ent[0]
        if copy_fn == 0:  # MPI_COMM_NULL_COPY_FN: never copied
            continue
        if copy_fn == 1:  # MPI_COMM_DUP_FN sentinel: always copied
            out[kv] = val
            continue
        CFN = ctypes.CFUNCTYPE(
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int))
        newval = ctypes.c_void_p(0)
        flag = ctypes.c_int(0)
        rc = CFN(copy_fn)(old_h, kv, ent[2], val,
                          ctypes.byref(newval), ctypes.byref(flag))
        if rc == MPI_SUCCESS and flag.value:
            out[kv] = newval.value or 0
    if out:
        _attr_tables[(kind, new_h)] = out


# -- MPI_Info objects ---------------------------------------------------

_infos: dict[int, dict] = {}
_next_info = 1


def info_create():
    global _next_info
    _next_info += 1
    _infos[_next_info] = {}
    return (MPI_SUCCESS, _next_info)


def info_set(ih: int, key: str, value: str) -> int:
    try:
        _infos.setdefault(ih, {})[key] = value
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def info_get_valuelen(ih: int, key: str):
    d = _infos.get(ih, {})
    if key in d:
        return (MPI_SUCCESS, 1, len(d[key]))
    return (MPI_SUCCESS, 0, 0)


def info_delete(ih: int, key: str) -> int:
    _infos.get(ih, {}).pop(key, None)
    return MPI_SUCCESS


def info_dup(ih: int):
    global _next_info
    _next_info += 1
    _infos[_next_info] = dict(_infos.get(ih, {}))
    return (MPI_SUCCESS, _next_info)


def info_free(ih: int) -> int:
    _infos.pop(ih, None)
    return MPI_SUCCESS


def info_get_nkeys(ih: int):
    return (MPI_SUCCESS, len(_infos.get(ih, {})))


# -- user error classes/codes (MPI_Add_error_*) -------------------------

_user_error_strings: dict[int, str] = {}
_next_error_class = 64


def add_error_class():
    global _next_error_class
    _next_error_class += 1
    return (MPI_SUCCESS, _next_error_class)


def add_error_code(errorclass: int):
    global _next_error_class
    _next_error_class += 1
    _user_error_strings.setdefault(
        _next_error_class, _user_error_strings.get(errorclass, ""))
    return (MPI_SUCCESS, _next_error_class)


def add_error_string(errorcode: int, string: str) -> int:
    _user_error_strings[errorcode] = string
    return MPI_SUCCESS


def user_error_string(errorcode: int):
    s = _user_error_strings.get(errorcode)
    if s is None:
        return (MPI_ERR_ARG, "")
    return (MPI_SUCCESS, s)


# -- topology additions (MPI_Cart_sub / MPI_Topo_test / maps) -----------

MPI_GRAPH_TOPO, MPI_CART_TOPO, MPI_DIST_GRAPH_TOPO, MPI_UNDEFINED_TOPO = (
    1, 2, 3, -32766)


def topo_test(h: int):
    try:
        _comm(h)
        if h in _carts:
            return (MPI_SUCCESS, MPI_CART_TOPO)
        if h in _graphs:
            return (MPI_SUCCESS, MPI_GRAPH_TOPO)
        if h in _dist_graphs:
            return (MPI_SUCCESS, MPI_DIST_GRAPH_TOPO)
        return (MPI_SUCCESS, MPI_UNDEFINED_TOPO)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def cart_sub(h: int, remain_ptr: int):
    """MPI_Cart_sub: split the cart comm into sub-grids keeping the
    dims where remain[d] != 0; returns this rank's sub-comm with its
    own cartesian geometry attached."""
    try:
        dims, periods = _cart_geom(h)
        nd = len(dims)
        remain = [int(v) for v in _view(remain_ptr, nd, 7)]
        me = comm_rank(h)[1]
        coords = _coords_of(dims, me)
        # color = coordinates along DROPPED dims; key = rank within kept
        color = 0
        for d in range(nd):
            if not remain[d]:
                color = color * dims[d] + coords[d]
        rc, ch = comm_split(h, color, me)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        keep_dims = [dims[d] for d in range(nd) if remain[d]]
        keep_periods = [periods[d] for d in range(nd) if remain[d]]
        if not keep_dims:
            keep_dims, keep_periods = [1], [0]
        if ch:
            _carts[ch] = (keep_dims, keep_periods)
        return (MPI_SUCCESS, ch)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def cart_map(h: int, ndims: int, dims_ptr: int, periods_ptr: int):
    """MPI_Cart_map: recommended rank for this process (identity order
    — device order is already ICI-contiguous; ranks past the grid get
    MPI_UNDEFINED)."""
    try:
        import math

        c = _comm(h)
        dims = [int(v) for v in _view(dims_ptr, ndims, 7)]
        me = comm_rank(h)[1]
        nnodes = math.prod(dims)
        del periods_ptr
        return (MPI_SUCCESS, me if me < nnodes else -32766)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def graph_map(h: int, nnodes: int):
    try:
        me = comm_rank(h)[1]
        return (MPI_SUCCESS, me if me < nnodes else -32766)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def graph_get(h: int, maxindex: int, maxedges: int, index_ptr: int,
              edges_ptr: int) -> int:
    try:
        index, edges = _graph_geom(h)
        idx = index[:maxindex]
        edg = edges[:maxedges]
        if idx:
            _view(index_ptr, len(idx), 7)[:] = idx
        if edg:
            _view(edges_ptr, len(edg), 7)[:] = edg
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


# -- distributed graph topology (MPI_Dist_graph_*) ----------------------

_dist_graphs: dict[int, tuple] = {}  # h -> (sources, destinations)


def dist_graph_create_adjacent(h: int, indegree: int, sources_ptr: int,
                               outdegree: int, dests_ptr: int):
    try:
        _comm(h)
        sources = ([int(v) for v in _view(sources_ptr, indegree, 7)]
                   if indegree else [])
        dests = ([int(v) for v in _view(dests_ptr, outdegree, 7)]
                 if outdegree else [])
        rc, ch = comm_dup(h)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        _dist_graphs[ch] = (sources, dests)
        return (MPI_SUCCESS, ch)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def dist_graph_create(h: int, n: int, sources_ptr: int, degrees_ptr: int,
                      dests_ptr: int):
    """General constructor: every process contributes edge lists; this
    single-source variant uses the local contribution (each process
    must describe its own edges — the common usage; a cross-process
    union requires an allgather the adjacent form avoids)."""
    try:
        _comm(h)
        me = comm_rank(h)[1]
        srcs = [int(v) for v in _view(sources_ptr, n, 7)] if n else []
        degs = [int(v) for v in _view(degrees_ptr, n, 7)] if n else []
        total = sum(degs)
        dsts = [int(v) for v in _view(dests_ptr, total, 7)] if total else []
        my_out, my_in = [], []
        off = 0
        for i, s in enumerate(srcs):
            block = dsts[off : off + degs[i]]
            off += degs[i]
            if s == me:
                my_out.extend(block)
            my_in.extend([s] * sum(1 for d in block if d == me))
        rc, ch = comm_dup(h)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        _dist_graphs[ch] = (my_in, my_out)
        return (MPI_SUCCESS, ch)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def dist_graph_neighbors_count(h: int):
    try:
        if h not in _dist_graphs:
            raise err.MPITopologyError(f"comm {h} has no dist-graph topology")
        s, d = _dist_graphs[h]
        return (MPI_SUCCESS, len(s), len(d), 0)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0, 0, 0)


def dist_graph_neighbors(h: int, maxin: int, sources_ptr: int,
                         maxout: int, dests_ptr: int) -> int:
    try:
        if h not in _dist_graphs:
            raise err.MPITopologyError(f"comm {h} has no dist-graph topology")
        s, d = _dist_graphs[h]
        if s[:maxin]:
            _view(sources_ptr, len(s[:maxin]), 7)[:] = s[:maxin]
        if d[:maxout]:
            _view(dests_ptr, len(d[:maxout]), 7)[:] = d[:maxout]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


# -- RMA breadth: lock_all/flush family, PSCW, request-based ops --------


def win_lock_all(wh: int, assertion: int) -> int:
    try:
        w = _win(wh)
        if _is_dist_win(w):
            w.lock_all()
        else:
            w.lock_all(0, assertion)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_unlock_all(wh: int) -> int:
    try:
        w = _win(wh)
        w.unlock_all() if _is_dist_win(w) else w.unlock_all(0)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_flush_all(wh: int) -> int:
    try:
        w = _win(wh)
        if _is_dist_win(w):
            w.flush_all()  # one sync round-trip per PROCESS
        else:
            w.flush_all(0)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_flush_local(wh: int, target: int) -> int:
    try:
        w = _win(wh)
        w.flush(target) if _is_dist_win(w) else w.flush_local(0, target)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_flush_local_all(wh: int) -> int:
    return win_flush_all(wh)


def win_sync(wh: int) -> int:
    try:
        w = _win(wh)
        if not _is_dist_win(w):
            w.sync(0)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_post(wh: int, gh: int, assertion: int) -> int:
    """MPI_Win_post (PSCW exposure epoch): origins come from the group."""
    try:
        w = _win(wh)
        g = _group(gh)
        if _is_dist_win(w):
            return MPI_SUCCESS  # dist wins: fence-counted epochs
        w.post(0, list(g.ranks), assertion)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_start(wh: int, gh: int, assertion: int) -> int:
    try:
        w = _win(wh)
        g = _group(gh)
        if _is_dist_win(w):
            return MPI_SUCCESS
        w.start(0, list(g.ranks), assertion)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_complete(wh: int) -> int:
    try:
        w = _win(wh)
        if _is_dist_win(w):
            return win_flush_all(wh)
        w.complete(0)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_wait(wh: int) -> int:
    try:
        w = _win(wh)
        if _is_dist_win(w):
            return MPI_SUCCESS
        w.wait(0)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_test(wh: int):
    try:
        w = _win(wh)
        if _is_dist_win(w):
            return (MPI_SUCCESS, 1)
        return (MPI_SUCCESS, 1 if w.test(0) else 0)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def win_get_accumulate(wh: int, optr: int, ocount: int, rptr: int,
                       rcount: int, dtcode: int, target: int, tdisp: int,
                       opcode: int) -> int:
    try:
        w = _win(wh)
        dt = DTYPES[dtcode]
        op = OPS[opcode]
        e0 = _win_elem_disp(w, tdisp, dt)
        data = (np.zeros(0, dt) if op is opmod.NO_OP or optr == 0
                else _view(optr, ocount, dtcode).copy())
        if _is_dist_win(w):
            # fetch-then-accumulate on the target's ordered request
            # stream; same-origin ordering makes the pair coherent
            old = np.asarray(w.get(target, rcount, disp=e0, dt=dt))
            if op is not opmod.NO_OP and data.size:
                w.accumulate(target, data, disp=e0, op=op, dt=dt)
        else:
            mem = w.memory(target).view(dt)
            old = mem[e0 : e0 + rcount].copy()
            if op is opmod.REPLACE:
                mem[e0 : e0 + data.size] = data
            elif op is not opmod.NO_OP and data.size:
                seg = mem[e0 : e0 + data.size]
                seg[:] = op.np_fn(seg, data)
        _view(rptr, rcount, dtcode)[:] = np.asarray(old).reshape(-1)[:rcount]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_compare_and_swap(wh: int, optr: int, cptr: int, rptr: int,
                         dtcode: int, target: int, tdisp: int) -> int:
    try:
        w = _win(wh)
        dt = DTYPES[dtcode]
        e0 = _win_elem_disp(w, tdisp, dt)
        val = _view(optr, 1, dtcode)[0]
        cmp_ = _view(cptr, 1, dtcode)[0]
        if _is_dist_win(w):
            old = w.compare_and_swap(target, val, cmp_, disp=e0, dt=dt)
        else:
            mem = w.memory(target).view(dt)
            old = mem[e0].copy()
            if old == cmp_:
                mem[e0] = val
        _view(rptr, 1, dtcode)[0] = old
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_rput(wh, optr, count, dtcode, target, tdisp):
    try:
        rc = win_put(wh, optr, count, dtcode, target, tdisp)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, 0))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def win_rget(wh, optr, count, dtcode, target, tdisp):
    try:
        rc = win_get(wh, optr, count, dtcode, target, tdisp)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, 0))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def win_raccumulate(wh, optr, count, dtcode, target, tdisp, opcode):
    try:
        rc = win_accumulate(wh, optr, count, dtcode, target, tdisp, opcode)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, 0))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def win_rget_accumulate(wh, optr, ocount, rptr, rcount, dtcode, target,
                        tdisp, opcode):
    try:
        rc = win_get_accumulate(wh, optr, ocount, rptr, rcount, dtcode,
                                target, tdisp, opcode)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, 0))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def win_allocate(h: int, size_bytes: int, disp_unit: int):
    """(err, win handle, base address) — base is the window memory this
    process owns (numpy-backed, address stable for the window's life)."""
    try:
        global _next_win_h
        c = _comm(h)
        w = c.win_allocate(max(size_bytes, 1), np.uint8)
        w._disp_unit = disp_unit
        _next_win_h += 1
        _wins[_next_win_h] = w
        me = (comm_rank(h)[1] if _is_single_controller(w.comm)
              else w.comm.local_offset)
        mem = w.memory(me)
        addr = int(mem.ctypes.data) if hasattr(mem, "ctypes") else 0
        return (MPI_SUCCESS, _next_win_h, addr)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0, 0)


def win_get_group(wh: int):
    try:
        w = _win(wh)
        g = w.group() if callable(getattr(w, "group", None)) else None
        if g is None:
            from ompi_tpu.api.group import Group

            g = Group(range(w.comm.size))
        return (MPI_SUCCESS, _store_group(g))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def win_set_name(wh: int, name: str) -> int:
    try:
        w = _win(wh)
        if hasattr(w, "set_name"):
            w.set_name(name)
        else:
            w.name = name
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_get_name(wh: int):
    try:
        return (MPI_SUCCESS, getattr(_win(wh), "name", f"win#{wh}"))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), "")


def win_get_attr(wh: int, kv: int):
    """Predefined window attributes resolve from the window itself."""
    try:
        w = _win(wh)
        if kv == KEYVAL_WIN_BASE:
            me = 0 if _is_single_controller(w.comm) else w.comm.local_offset
            mem = w.memory(me)
            return (MPI_SUCCESS, 1,
                    int(mem.ctypes.data) if hasattr(mem, "ctypes") else 0)
        if kv == KEYVAL_WIN_SIZE:
            me = 0 if _is_single_controller(w.comm) else w.comm.local_offset
            return (MPI_SUCCESS, 1, int(w.memory(me).nbytes))
        if kv == KEYVAL_WIN_DISP_UNIT:
            return (MPI_SUCCESS, 1, int(getattr(w, "_disp_unit", 1)))
        return attr_get("win", wh, kv)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0, 0)


# -- MPI-IO breadth: shared pointers, plain _all, async, metadata -------


def file_write_all(fh: int, ptr: int, count: int, dtcode: int):
    """Collective write at individual pointers (two-phase underneath)."""
    try:
        f = _file(fh)[0]
        data = _pack_from(ptr, count, dtcode)
        dt_size = (_dtypes[dtcode].size if dtcode in _dtypes
                   else DTYPES[dtcode].itemsize)
        written = f.write_all([np.asarray(data)])[0]
        esize = f.get_view(0)[1].size
        return (MPI_SUCCESS,
                (written * esize // max(1, dt_size)) * dt_size)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_read_all(fh: int, ptr: int, count: int, dtcode: int):
    try:
        f = _file(fh)[0]
        dt = DTYPES.get(dtcode)
        if dt is None:
            raise err.MPITypeError(f"unsupported datatype {dtcode}")
        pos = f.get_position(0)
        esize = f.get_view(0)[1].size
        count = _dense_read_clamp(f, pos * esize, count, dt.itemsize)
        units = _etype_units(f, count * dt.itemsize)
        out = f.read_all([units])[0].view(dt)
        got = int(np.asarray(out).size)
        if got:
            _view(ptr, got, dtcode)[:] = np.asarray(out).reshape(-1)
        return (MPI_SUCCESS, got * _unit_nbytes(dtcode))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_write_shared(fh: int, ptr: int, count: int, dtcode: int):
    try:
        f = _file(fh)[0]
        data = _pack_from(ptr, count, dtcode)
        written = f.write_shared(0, np.asarray(data))
        return (MPI_SUCCESS, int(written) * _unit_nbytes(dtcode))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_read_shared(fh: int, ptr: int, count: int, dtcode: int):
    try:
        f = _file(fh)[0]
        dt = DTYPES.get(dtcode)
        if dt is None:
            raise err.MPITypeError(f"unsupported datatype {dtcode}")
        units = _etype_units(f, count * dt.itemsize)
        out = f.read_shared(0, units, dtype=dt)
        got = int(np.asarray(out).size)
        if got:
            _view(ptr, got, dtcode)[:] = np.asarray(out).reshape(-1)
        return (MPI_SUCCESS, got * _unit_nbytes(dtcode))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_seek_shared(fh: int, offset: int, whence: int) -> int:
    try:
        f = _file(fh)[0]
        f.seek_shared(int(offset), int(whence))
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def file_get_position_shared(fh: int):
    try:
        return (MPI_SUCCESS, int(_file(fh)[0].get_position_shared()))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_get_position(fh: int):
    try:
        return (MPI_SUCCESS, int(_file(fh)[0].get_position(0)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_get_byte_offset(fh: int, offset: int):
    try:
        return (MPI_SUCCESS, int(_file(fh)[0].get_byte_offset(0, offset)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_sync(fh: int) -> int:
    try:
        _file(fh)[0].sync()
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def file_preallocate(fh: int, size: int) -> int:
    try:
        _file(fh)[0].preallocate(int(size))
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def file_get_amode(fh: int):
    try:
        return (MPI_SUCCESS, int(_file(fh)[0].amode))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_set_atomicity(fh: int, flag: int) -> int:
    try:
        _file(fh)[0].set_atomicity(bool(flag))
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def file_get_atomicity(fh: int):
    try:
        return (MPI_SUCCESS, 1 if _file(fh)[0].get_atomicity() else 0)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_get_type_extent(fh: int, dtcode: int):
    try:
        d = _dtypes.get(dtcode)
        ext = d.extent if d is not None else DTYPES[dtcode].itemsize
        return (MPI_SUCCESS, int(ext))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_delete(path: str) -> int:
    import os

    try:
        os.remove(path)
        return MPI_SUCCESS
    except FileNotFoundError:
        return MPI_ERR_OTHER
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def file_iwrite_at(fh, offset, ptr, count, dtcode):
    try:
        rc, got = file_write_at(fh, offset, ptr, count, dtcode)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, got))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_iread_at(fh, offset, ptr, count, dtcode):
    try:
        rc, got = file_read_at(fh, offset, ptr, count, dtcode)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, got))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_iwrite(fh, ptr, count, dtcode):
    try:
        rc, got = file_write(fh, ptr, count, dtcode)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, got))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_iread(fh, ptr, count, dtcode):
    try:
        rc, got = file_read(fh, ptr, count, dtcode)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, got))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


# -- datatype breadth ---------------------------------------------------


def type_create_hvector(count: int, blocklength: int, stride_bytes: int,
                        base: int):
    try:
        d = _ddt(base).create_hvector(count, blocklength, stride_bytes)
        code = _store_dtype(d)
        _record_envelope(code, 5, [count, blocklength],
                         [stride_bytes], [base])
        return (MPI_SUCCESS, code)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def type_create_hindexed(count: int, bl_ptr: int, disp_ptr: int, base: int):
    try:
        bls = [int(v) for v in _view(bl_ptr, count, 7)]
        disps = [int(v) for v in _view(disp_ptr, count, 20)]  # MPI_Aint
        d = _ddt(base).create_hindexed(bls, disps)
        code = _store_dtype(d)
        _record_envelope(code, 7, [count] + bls, disps, [base])
        return (MPI_SUCCESS, code)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def type_create_hindexed_block(count: int, blocklength: int, disp_ptr: int,
                               base: int):
    try:
        disps = [int(v) for v in _view(disp_ptr, count, 20)]
        d = _ddt(base).create_hindexed([blocklength] * count, disps)
        code = _store_dtype(d)
        _record_envelope(code, 9, [count, blocklength], disps, [base])
        return (MPI_SUCCESS, code)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def type_create_indexed_block(count: int, blocklength: int, disp_ptr: int,
                              base: int):
    try:
        disps = [int(v) for v in _view(disp_ptr, count, 7)]
        d = _ddt(base).create_indexed_block(blocklength, disps)
        code = _store_dtype(d)
        _record_envelope(code, 8, [count, blocklength] + disps, [], [base])
        return (MPI_SUCCESS, code)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def type_create_resized(base: int, lb: int, extent: int):
    try:
        d = _ddt(base).create_resized(int(lb), int(extent))
        code = _store_dtype(d)
        _record_envelope(code, 13, [], [int(lb), int(extent)], [base])
        return (MPI_SUCCESS, code)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def type_create_subarray(ndims: int, sizes_ptr: int, subsizes_ptr: int,
                         starts_ptr: int, order: int, base: int):
    try:
        sizes = [int(v) for v in _view(sizes_ptr, ndims, 7)]
        subsizes = [int(v) for v in _view(subsizes_ptr, ndims, 7)]
        starts = [int(v) for v in _view(starts_ptr, ndims, 7)]
        d = _ddt(base).create_subarray(
            sizes, subsizes, starts,
            order="F" if order == 57 else "C")  # 57 = MPI_ORDER_FORTRAN
        code = _store_dtype(d)
        _record_envelope(code, 11,
                         [ndims] + sizes + subsizes + starts + [order],
                         [], [base])
        return (MPI_SUCCESS, code)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def type_get_true_extent(dtcode: int):
    try:
        d = _dtypes.get(dtcode)
        if d is None:
            size = DTYPES[dtcode].itemsize
            return (MPI_SUCCESS, 0, size)
        return (MPI_SUCCESS, int(d.true_lb), int(d.true_extent))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0, 0)


_type_names: dict[int, str] = {}


def type_set_name(dtcode: int, name: str) -> int:
    _type_names[dtcode] = name
    return MPI_SUCCESS


def type_get_name(dtcode: int):
    name = _type_names.get(dtcode)
    if name is None:
        d = _dtypes.get(dtcode)
        name = d.name if d is not None else f"MPI_dt#{dtcode}"
    return (MPI_SUCCESS, name)


# -- communicator/group breadth -----------------------------------------


def comm_test_inter(h: int):
    try:
        c = _comm(h)
        from ompi_tpu.api.intercomm import Intercomm

        return (MPI_SUCCESS, 1 if isinstance(c, Intercomm) else 0)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def comm_remote_group(h: int):
    try:
        c = _comm(h)
        g = getattr(c, "remote_group", None)
        if g is None:
            raise err.MPICommError(f"comm {h} is not an intercommunicator")
        from ompi_tpu.api.group import Group

        return (MPI_SUCCESS, _store_group(Group(list(g.ranks))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def intercomm_create(local_h: int, local_leader: int, peer_h: int,
                     remote_leader: int, tag: int):
    try:
        from ompi_tpu.api.intercomm import create_intercomm

        local = _comm(local_h)
        peer = _comm(peer_h)
        del tag, local_leader, remote_leader  # leaders implicit: single
        # controller sees both sides, the handshake collapses
        local_ranks = list(getattr(local.group, "ranks",
                                   range(local.size)))
        all_ranks = list(getattr(peer.group, "ranks", range(peer.size)))
        remote_ranks = [r for r in all_ranks if r not in set(local_ranks)]
        ic = create_intercomm(peer, local_ranks, remote_ranks)
        return (MPI_SUCCESS, _store_comm(ic, peer_h))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def group_range_incl(gh: int, n: int, ranges_ptr: int):
    try:
        from ompi_tpu.api.group import Group

        g = _group(gh)
        triplets = _view(ranges_ptr, n * 3, 7)
        ranks = []
        for i in range(n):
            first, last, stride = (int(triplets[3 * i]),
                                   int(triplets[3 * i + 1]),
                                   int(triplets[3 * i + 2]))
            ranks.extend(range(first, last + (1 if stride > 0 else -1),
                               stride))
        world = [g.ranks[r] for r in ranks]
        return (MPI_SUCCESS, _store_group(Group(world)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def group_range_excl(gh: int, n: int, ranges_ptr: int):
    try:
        from ompi_tpu.api.group import Group

        g = _group(gh)
        triplets = _view(ranges_ptr, n * 3, 7)
        excl = set()
        for i in range(n):
            first, last, stride = (int(triplets[3 * i]),
                                   int(triplets[3 * i + 1]),
                                   int(triplets[3 * i + 2]))
            excl.update(range(first, last + (1 if stride > 0 else -1),
                              stride))
        world = [g.ranks[r] for r in range(g.size) if r not in excl]
        return (MPI_SUCCESS, _store_group(Group(world)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


# -- matched probe/recv (MPI_Mprobe / MPI_Mrecv) ------------------------
# A message handle pins the probed (source, tag) pair; mrecv receives
# the next matching message — FIFO per (source, tag) makes this the
# probed message in the single-threaded C model.

_messages: dict[int, tuple] = {}
_next_message = 1


def mprobe(source: int, tag: int, h: int):
    """(err, message handle, source, tag, count_bytes)."""
    try:
        rc = probe(source, tag, h)
        if not isinstance(rc, tuple) or rc[0] != MPI_SUCCESS:
            return (rc if isinstance(rc, int) else rc[0], 0, -1, -1, 0)
        _, src, tg, cnt = rc
        global _next_message
        _next_message += 1
        _messages[_next_message] = (h, src, tg)
        return (MPI_SUCCESS, _next_message, src, tg, cnt)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0, -1, -1, 0)


def improbe(source: int, tag: int, h: int):
    """(err, flag, message handle, source, tag, count_bytes)."""
    try:
        rc = iprobe(source, tag, h)
        if not isinstance(rc, tuple) or rc[0] != MPI_SUCCESS:
            return (rc if isinstance(rc, int) else rc[0], 0, 0, -1, -1, 0)
        _, flag, src, tg, cnt = rc
        if not flag:
            return (MPI_SUCCESS, 0, 0, -1, -1, 0)
        global _next_message
        _next_message += 1
        _messages[_next_message] = (h, src, tg)
        return (MPI_SUCCESS, 1, _next_message, src, tg, cnt)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0, 0, -1, -1, 0)


def mrecv(mh: int, ptr: int, count: int, dtcode: int):
    """(err, source, tag, count)."""
    try:
        ent = _messages.pop(mh, None)
        if ent is None:
            raise err.MPIRequestError(f"invalid message handle {mh}")
        h, src, tg = ent
        return recv(ptr, count, dtcode, src, tg, h)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), -1, -1, 0)


def isend_done_handle(source: int, tag: int, count: int):
    """Completed-request handle carrying a status (shim helper for
    eager i-operations that already finished)."""
    return (MPI_SUCCESS,
            _store_req(("done", None, 0, 0, (source, tag, count))))


def info_get_value(ih: int, key: str):
    """(err, str) form for the shim's string-marshalling helper."""
    d = _infos.get(ih, {})
    if key not in d:
        return (MPI_ERR_ARG, "")
    return (MPI_SUCCESS, d[key])


def info_get_nthkey_str(ih: int, n: int):
    keys = list(_infos.get(ih, {}))
    if 0 <= n < len(keys):
        return (MPI_SUCCESS, keys[n])
    return (MPI_ERR_ARG, "")


_file_view_codes: dict[int, tuple] = {}  # fh -> (disp, etype, filetype)


def file_get_view_codes(fh: int):
    """(err, disp, etype code, filetype code) — codes recorded at
    set_view time (default: byte stream)."""
    try:
        f = _file(fh)[0]
        disp = f.get_view(0)[0]
        _, et, ft = _file_view_codes.get(fh, (0, 4, 4))
        return (MPI_SUCCESS, int(disp), et, ft)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0, 4, 4)


# ======================================================================
# Round-3 C ABI batch 2: neighbor collectives, alltoallw, type
# introspection (envelope/contents/darray/f90), MPI_T breadth,
# generalized requests, name service, window/io remainder.
# ======================================================================

# -- datatype envelope/contents (MPI_Type_get_envelope) -----------------
# combiner codes (mpi.h): NAMED=1, DUP=2, CONTIGUOUS=3, VECTOR=4,
# HVECTOR=5, INDEXED=6, HINDEXED=7, INDEXED_BLOCK=8, HINDEXED_BLOCK=9,
# STRUCT=10, SUBARRAY=11, DARRAY=12, RESIZED=13, F90_REAL=14,
# F90_COMPLEX=15, F90_INTEGER=16

_type_envelope: dict[int, tuple] = {}  # dtcode -> (combiner, ints, aints, types)


def _record_envelope(dtcode: int, combiner: int, ints=(), aints=(),
                     types=()) -> int:
    _type_envelope[dtcode] = (combiner, list(ints), list(aints), list(types))
    return dtcode


def type_get_envelope(dtcode: int):
    """(err, num_integers, num_addresses, num_datatypes, combiner)."""
    env = _type_envelope.get(dtcode)
    if env is None:
        return (MPI_SUCCESS, 0, 0, 0, 1)  # MPI_COMBINER_NAMED
    c, ints, aints, types = env
    return (MPI_SUCCESS, len(ints), len(aints), len(types), c)


def type_get_contents(dtcode: int, max_i: int, max_a: int, max_d: int,
                      ints_ptr: int, aints_ptr: int, types_ptr: int) -> int:
    try:
        env = _type_envelope.get(dtcode)
        if env is None:
            raise err.MPITypeError(
                f"MPI_Type_get_contents on a named datatype {dtcode}")
        _, ints, aints, types = env
        if len(ints) > max_i or len(aints) > max_a or len(types) > max_d:
            raise err.MPIArgError("get_contents arrays too small")
        if ints:
            _view(ints_ptr, len(ints), 7)[:] = ints
        if aints:
            _view(aints_ptr, len(aints), 20)[:] = aints
        if types:
            _view(types_ptr, len(types), 7)[:] = types
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def type_create_darray(size: int, rank: int, ndims: int, gsizes_ptr: int,
                       distribs_ptr: int, dargs_ptr: int, psizes_ptr: int,
                       order: int, base: int):
    """MPI_Type_create_darray, MPI_DISTRIBUTE_BLOCK subset (the HPF
    block distribution ScaLAPACK-style decompositions use; CYCLIC
    would need the full HPF machinery and raises)."""
    try:
        DISTRIBUTE_BLOCK, DISTRIBUTE_NONE = 121, 123
        gsizes = [int(v) for v in _view(gsizes_ptr, ndims, 7)]
        distribs = [int(v) for v in _view(distribs_ptr, ndims, 7)]
        psizes = [int(v) for v in _view(psizes_ptr, ndims, 7)]
        for d in distribs:
            if d not in (DISTRIBUTE_BLOCK, DISTRIBUTE_NONE):
                raise err.MPITypeError(
                    "darray: only MPI_DISTRIBUTE_BLOCK/NONE supported")
        # process coordinates in the process grid (C order)
        coords = []
        r = rank
        for p in reversed(psizes):
            coords.append(r % p)
            r //= p
        coords.reverse()
        subsizes, starts = [], []
        for i in range(ndims):
            if distribs[i] == DISTRIBUTE_NONE or psizes[i] == 1:
                subsizes.append(gsizes[i])
                starts.append(0)
            else:
                block = -(-gsizes[i] // psizes[i])  # ceil
                s = coords[i] * block
                subsizes.append(max(0, min(block, gsizes[i] - s)))
                starts.append(min(s, gsizes[i]))
        d = _ddt(base).create_subarray(
            gsizes, subsizes, starts, order="F" if order == 57 else "C")
        code = _store_dtype(d)
        _record_envelope(code, 12,
                         [size, rank, ndims] + gsizes + distribs
                         + [int(v) for v in _view(dargs_ptr, ndims, 7)]
                         + psizes + [order],
                         [], [base])
        return (MPI_SUCCESS, code)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def type_match_size(typeclass: int, size: int):
    """MPI_Type_match_size: TYPECLASS_{INTEGER=1,REAL=2,COMPLEX=3}."""
    table = {
        (1, 1): 17, (1, 2): 18, (1, 4): 19, (1, 8): 20,
        (2, 4): 13, (2, 8): 14,
        (3, 8): 25, (3, 16): 26,
    }
    code = table.get((typeclass, size))
    if code is None:
        return (MPI_ERR_ARG, 0)
    return (MPI_SUCCESS, code)


def type_create_f90(kind: str, p: int, r: int):
    """F90 parameterized types resolve to the matching C types."""
    if kind == "real":
        return (MPI_SUCCESS, 14 if p > 6 else 13)
    if kind == "complex":
        return (MPI_SUCCESS, 26 if p > 6 else 25)
    if kind == "integer":
        if r <= 2:
            return (MPI_SUCCESS, 17)
        if r <= 4:
            return (MPI_SUCCESS, 18)
        if r <= 9:
            return (MPI_SUCCESS, 19)
        return (MPI_SUCCESS, 20)
    return (MPI_ERR_ARG, 0)


# -- neighbor collectives (over cart/graph/dist-graph topologies) -------


#: reserved tag base for neighbor-collective internal traffic (user
#: tags live below; TAG_UB is 2^30-1 so this range is addressable)
_NEIGH_TAG = 1 << 29


def _cart_mirror(h: int, i: int) -> int | None:
    """For cartesian topologies, the SENDER's slot index that addresses
    me when I receive at slot ``i``: dimension d's (-1, +1) pair is
    mirrored (my -1 source used ITS +1 dest), i.e. i^1.  None for
    graph topologies, where occurrence-order FIFO pairing is already
    the adjacency-order semantics."""
    return (i ^ 1) if h in _carts else None


def _neighbors_of(h: int):
    """(sources, destinations) global-rank lists for comm ``h``'s
    topology (cart: shift neighbors in dimension order, the standard's
    required ordering; graph: adjacency; dist_graph: stored edges)."""
    me = comm_rank(h)[1]
    if h in _carts:
        dims, periods = _carts[h]
        coords = _coords_of(dims, me)
        ns = []
        for d in range(len(dims)):
            for disp in (-1, 1):
                c = list(coords)
                c[d] += disp
                if periods[d]:
                    c[d] %= dims[d]
                elif not 0 <= c[d] < dims[d]:
                    ns.append(-2)  # MPI_PROC_NULL
                    continue
                ns.append(_rank_of(dims, periods, c))
        return ns, ns  # cartesian neighborhoods are symmetric
    if h in _graphs:
        from ompi_tpu.api.topo import graph_neighbors_of

        index, edges = _graphs[h]
        ns = graph_neighbors_of(index, edges, me)
        return list(ns), list(ns)
    if h in _dist_graphs:
        s, d = _dist_graphs[h]
        return list(s), list(d)
    raise err.MPITopologyError(f"comm {h} has no topology")


def neighbor_allgather(sptr, scount, sdt, rptr, rcount, rdt, h) -> int:
    """Each process sends its block to every out-neighbor and receives
    one block per in-neighbor (recvbuf order = neighbor order)."""
    try:
        c = _comm(h)
        me = comm_rank(h)[1]
        sources, dests = _neighbors_of(h)
        x = _view(sptr, scount, sdt).copy()
        cart = h in _carts
        for j, d in enumerate(dests):
            if d != -2:
                c.send(x, me, d, tag=_NEIGH_TAG + 0 + (j if cart else 0))
        item = DTYPES[rdt].itemsize
        for i, s in enumerate(sources):
            dst = _view(rptr + i * rcount * item, rcount, rdt)
            if s == -2:
                continue
            j = _cart_mirror(h, i)
            payload, _st = c.recv(me, s, _NEIGH_TAG + 0 if j is None else _NEIGH_TAG + 0 + j)
            flat = np.asarray(payload).reshape(-1).view(DTYPES[rdt])
            dst[:] = flat[:rcount]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def neighbor_allgatherv(sptr, scount, sdt, rptr, rcounts_ptr, displs_ptr,
                        rdt, h) -> int:
    try:
        c = _comm(h)
        me = comm_rank(h)[1]
        sources, dests = _neighbors_of(h)
        x = _view(sptr, scount, sdt).copy()
        cart = h in _carts
        for j, d in enumerate(dests):
            if d != -2:
                c.send(x, me, d, tag=_NEIGH_TAG + 64 + (j if cart else 0))
        counts, displs = _vparams(rcounts_ptr, displs_ptr, len(sources))
        item = DTYPES[rdt].itemsize
        for i, s in enumerate(sources):
            if s == -2:
                continue
            j = _cart_mirror(h, i)
            payload, _st = c.recv(me, s, _NEIGH_TAG + 64 if j is None else _NEIGH_TAG + 64 + j)
            flat = np.asarray(payload).reshape(-1).view(DTYPES[rdt])
            dst = _view(rptr + displs[i] * item, counts[i], rdt)
            dst[:] = flat[: counts[i]]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def neighbor_alltoall(sptr, scount, sdt, rptr, rcount, rdt, h) -> int:
    """Distinct block per out-neighbor; one block per in-neighbor."""
    try:
        c = _comm(h)
        me = comm_rank(h)[1]
        sources, dests = _neighbors_of(h)
        sitem = DTYPES[sdt].itemsize
        cart = h in _carts
        for j, d in enumerate(dests):
            if d != -2:
                blk = _view(sptr + j * scount * sitem, scount, sdt).copy()
                c.send(blk, me, d, tag=_NEIGH_TAG + 128 + (j if cart else 0))
        ritem = DTYPES[rdt].itemsize
        for i, s in enumerate(sources):
            if s == -2:
                continue
            j = _cart_mirror(h, i)
            payload, _st = c.recv(me, s, _NEIGH_TAG + 128 if j is None else _NEIGH_TAG + 128 + j)
            flat = np.asarray(payload).reshape(-1).view(DTYPES[rdt])
            dst = _view(rptr + i * rcount * ritem, rcount, rdt)
            dst[:] = flat[:rcount]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def neighbor_alltoallv(sptr, scounts_ptr, sdispls_ptr, sdt, rptr,
                       rcounts_ptr, rdispls_ptr, rdt, h) -> int:
    try:
        c = _comm(h)
        me = comm_rank(h)[1]
        sources, dests = _neighbors_of(h)
        scounts, sdispls = _vparams(scounts_ptr, sdispls_ptr, len(dests))
        rcounts, rdispls = _vparams(rcounts_ptr, rdispls_ptr, len(sources))
        sitem = DTYPES[sdt].itemsize
        cart = h in _carts
        for j, d in enumerate(dests):
            if d != -2:
                blk = _view(sptr + sdispls[j] * sitem, scounts[j], sdt).copy()
                c.send(blk, me, d, tag=_NEIGH_TAG + 192 + (j if cart else 0))
        ritem = DTYPES[rdt].itemsize
        for i, s in enumerate(sources):
            if s == -2:
                continue
            j = _cart_mirror(h, i)
            payload, _st = c.recv(me, s, _NEIGH_TAG + 192 if j is None else _NEIGH_TAG + 192 + j)
            flat = np.asarray(payload).reshape(-1).view(DTYPES[rdt])
            dst = _view(rptr + rdispls[i] * ritem, rcounts[i], rdt)
            dst[:] = flat[: rcounts[i]]
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def ineighbor(fn_name: str, *args):
    try:
        fn = globals()[fn_name]
        return _eager_coll(lambda: fn(*args))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


# -- MPI_Alltoallw (per-block datatypes; counts in ELEMENTS, displs in
# BYTES, per the standard) ---------------------------------------------


def alltoallw(sptr, scounts_ptr, sdispls_ptr, stypes_ptr, rptr,
              rcounts_ptr, rdispls_ptr, rtypes_ptr, h) -> int:
    try:
        c = _comm(h)
        n = getattr(c, "size", 1)
        me = comm_rank(h)[1]
        scounts = [int(v) for v in _view(scounts_ptr, n, 7)]
        sdispls = [int(v) for v in _view(sdispls_ptr, n, 7)]
        stypes = [int(v) for v in _view(stypes_ptr, n, 7)]
        rcounts = [int(v) for v in _view(rcounts_ptr, n, 7)]
        rdispls = [int(v) for v in _view(rdispls_ptr, n, 7)]
        rtypes = [int(v) for v in _view(rtypes_ptr, n, 7)]
        # pack every outgoing block to bytes (the convertor handles
        # derived types), jagged-exchange, unpack per-block
        row = [
            np.ascontiguousarray(
                _pack_from(sptr + sdispls[j], scounts[j], stypes[j])
            ).view(np.uint8).reshape(-1)
            for j in range(n)
        ]
        if _is_single_controller(c):
            out = c.alltoallv([row] * n if n > 1 else [row])[me]
        else:
            out = c.alltoallv([row])[0]
        for j in range(n):
            _unpack_into(rptr + rdispls[j], rcounts[j], rtypes[j],
                         np.asarray(out[j]).view(np.uint8))
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e, h)


def ialltoallw(*args):
    try:
        return _eager_coll(lambda: alltoallw(*args))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


# -- generalized requests (MPI_Grequest_start/complete) -----------------


def grequest_start(query_fnptr: int, free_fnptr: int, cancel_fnptr: int,
                   extra: int):
    """The user drives completion (grequest_complete); at wait/test
    completion the query callback fills the status, and the free
    callback releases user state — the MPI-2 generalized request
    lifecycle."""
    try:
        return (MPI_SUCCESS, _store_req(
            ("grequest", None,
             (query_fnptr, free_fnptr, cancel_fnptr, extra), 0, 0)))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def grequest_complete(rh: int) -> int:
    try:
        entry = _requests.get(rh)
        if entry is None or entry[0] != "grequest":
            raise err.MPIRequestError(f"not a generalized request: {rh}")
        query_fnptr, free_fnptr, cancel_fnptr, extra = entry[2]
        status = np.zeros(4, np.int32)  # MPI_Status layout (4 ints)
        CB = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                              ctypes.c_void_p)
        if query_fnptr:
            CB(query_fnptr)(extra, status.ctypes.data)
        if free_fnptr:
            ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)(free_fnptr)(extra)
        _requests[rh] = ("done", None, 0, 0,
                         (int(status[0]), int(status[1]), int(status[3])))
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


# -- name service (MPI_Open_port / Publish_name family) -----------------
# Port names resolve through the job KVS under tpurun (visible to every
# process of the job) and a process-local registry standalone — the
# reference's ompi-server plays this role; cross-JOB rendezvous needs
# that external server there too, so the parity boundary is identical.

_local_names: dict[str, str] = {}
_next_port = 1


def open_port():
    global _next_port
    _next_port += 1
    return (MPI_SUCCESS, f"tpumpi-port-{_rank}-{_next_port}")


def close_port(port: str) -> int:
    del port
    return MPI_SUCCESS


def _kvs_or_none():
    try:
        from ompi_tpu.boot.proc import launched_by_tpurun

        if not launched_by_tpurun():
            return None
        from ompi_tpu.api import comm_world

        return getattr(comm_world(), "procctx", None)
    except BaseException:  # noqa: BLE001
        return None


def publish_name(service: str, port: str) -> int:
    ctx = _kvs_or_none()
    if ctx is not None:
        try:
            ctx.kvs.put(f"svc:{service}", port)
            return MPI_SUCCESS
        except BaseException:  # noqa: BLE001
            pass  # standalone / KVS gone: process-local registry below
    _local_names[service] = port
    return MPI_SUCCESS


def unpublish_name(service: str) -> int:
    ctx = _kvs_or_none()
    if ctx is not None:
        try:  # tombstone: the KVS has no delete; "" reads as absent
            ctx.kvs.put(f"svc:{service}", "")
        except BaseException:  # noqa: BLE001
            pass
    _local_names.pop(service, None)
    return MPI_SUCCESS


def lookup_name(service: str):
    ctx = _kvs_or_none()
    if ctx is not None:
        try:
            # a tombstoned ("") value reads as absent (unpublished)
            port = ctx.kvs.get(f"svc:{service}", timeout=5.0)
            if port:
                return (MPI_SUCCESS, port)
        except BaseException:  # noqa: BLE001
            pass
    port = _local_names.get(service)
    if port is None:
        return (MPI_ERR_ARG, "")
    return (MPI_SUCCESS, port)


# -- window remainder ---------------------------------------------------


def win_allocate_shared(h: int, size_bytes: int, disp_unit: int):
    try:
        global _next_win_h
        c = _comm(h)
        w = (c.win_allocate_shared(max(size_bytes, 1), np.uint8)
             if hasattr(c, "win_allocate_shared")
             else c.win_allocate(max(size_bytes, 1), np.uint8))
        w._disp_unit = disp_unit
        _next_win_h += 1
        _wins[_next_win_h] = w
        me = (comm_rank(h)[1] if _is_single_controller(c)
              else c.local_offset)
        mem = w.memory(me)
        addr = int(mem.ctypes.data) if hasattr(mem, "ctypes") else 0
        return (MPI_SUCCESS, _next_win_h, addr)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0, 0)


def win_create_dynamic(h: int):
    try:
        global _next_win_h
        c = _comm(h)
        w = c.win_create_dynamic(np.uint8)
        w._disp_unit = 1
        _next_win_h += 1
        _wins[_next_win_h] = w
        return (MPI_SUCCESS, _next_win_h)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e, h), 0)


def win_attach(wh: int, addr: int, size_bytes: int) -> int:
    try:
        w = _win(wh)
        # the C model runs one rank per process → the caller is always
        # its process's local rank 0 (single-controller ditto)
        raw = (ctypes.c_ubyte * max(size_bytes, 1)).from_address(addr)
        w.attach(0, addr, np.frombuffer(raw, np.uint8))
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_detach(wh: int, addr: int) -> int:
    try:
        w = _win(wh)
        w.detach(0, addr)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def win_shared_query(wh: int, rank: int):
    """(err, size, disp_unit, base address)."""
    try:
        w = _win(wh)
        q = getattr(w, "shared_query", None)
        if q is not None:
            size, mem = q(rank)
        else:
            mem = w.memory(rank)
            size = mem.nbytes
        addr = int(mem.ctypes.data) if hasattr(mem, "ctypes") else 0
        return (MPI_SUCCESS, int(size), int(getattr(w, "_disp_unit", 1)),
                addr)
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0, 0, 0)


# -- MPI-IO split-phase / ordered / async shared ------------------------

_file_split: dict[int, tuple] = {}  # fh -> ("read"/"write", data/count)


def file_write_ordered(fh: int, ptr: int, count: int, dtcode: int):
    """Rank-ordered write at the shared pointer.  Multi-process jobs:
    the shared pointer is single-process-scoped (see file_open) — same
    boundary, reported not silently corrupted."""
    try:
        f, multi = _file(fh)[0], _file(fh)[1]
        if multi:
            raise err.MPIFileError(
                "shared-file-pointer ordered ops are single-process "
                "scoped in this build (see MPI_File_open notes)")
        data = _pack_from(ptr, count, dtcode)
        written = f.write_ordered([np.asarray(data)])[0]
        return (MPI_SUCCESS, int(written) * _unit_nbytes(dtcode))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_read_ordered(fh: int, ptr: int, count: int, dtcode: int):
    try:
        f, multi = _file(fh)[0], _file(fh)[1]
        if multi:
            raise err.MPIFileError(
                "shared-file-pointer ordered ops are single-process "
                "scoped in this build (see MPI_File_open notes)")
        dt = DTYPES.get(dtcode)
        if dt is None:
            raise err.MPITypeError(f"unsupported datatype {dtcode}")
        units = _etype_units(f, count * dt.itemsize)
        out = f.read_ordered([units], dtype=dt)[0]
        got = int(np.asarray(out).size)
        if got:
            _view(ptr, got, dtcode)[:] = np.asarray(out).reshape(-1)
        return (MPI_SUCCESS, got * _unit_nbytes(dtcode))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_split_begin(fh: int, kind: str, offset: int, ptr: int, count: int,
                     dtcode: int) -> int:
    """Split-phase *_begin: the operation runs now; _end returns its
    status (MPI allows completion any time inside the begin/end pair)."""
    try:
        if fh in _file_split:
            raise err.MPIFileError("split collective already active")
        if kind == "write_at":
            rc, got = file_write_at_all(fh, offset, ptr, count, dtcode)
        elif kind == "read_at":
            rc, got = file_read_at_all(fh, offset, ptr, count, dtcode)
        elif kind == "write":
            rc, got = file_write_all(fh, ptr, count, dtcode)
        elif kind == "read":
            rc, got = file_read_all(fh, ptr, count, dtcode)
        elif kind == "write_ordered":
            rc, got = file_write_ordered(fh, ptr, count, dtcode)
        elif kind == "read_ordered":
            rc, got = file_read_ordered(fh, ptr, count, dtcode)
        else:
            raise err.MPIArgError(f"bad split kind {kind}")
        if rc != MPI_SUCCESS:
            return rc
        _file_split[fh] = (kind, got)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _fail(e)


def file_split_end(fh: int):
    """(err, element count) for the active split collective."""
    try:
        ent = _file_split.pop(fh, None)
        if ent is None:
            raise err.MPIFileError("no split collective active")
        return (MPI_SUCCESS, int(ent[1]))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_iwrite_shared(fh, ptr, count, dtcode):
    try:
        rc, got = file_write_shared(fh, ptr, count, dtcode)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, got))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_iread_shared(fh, ptr, count, dtcode):
    try:
        rc, got = file_read_shared(fh, ptr, count, dtcode)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, got))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_iwrite_at_all(fh, offset, ptr, count, dtcode):
    try:
        rc, got = file_write_at_all(fh, offset, ptr, count, dtcode)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, got))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_iread_at_all(fh, offset, ptr, count, dtcode):
    try:
        rc, got = file_read_at_all(fh, offset, ptr, count, dtcode)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, got))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_iwrite_all(fh, ptr, count, dtcode):
    try:
        rc, got = file_write_all(fh, ptr, count, dtcode)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, got))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


def file_iread_all(fh, ptr, count, dtcode):
    try:
        rc, got = file_read_all(fh, ptr, count, dtcode)
        if rc != MPI_SUCCESS:
            return (rc, 0)
        return (MPI_SUCCESS, _store_req(("done", None, 0, 0, (0, 0, got))))
    except BaseException as e:  # noqa: BLE001
        return (_fail(e), 0)


_datareps: set[str] = {"native", "internal", "external32"}


def register_datarep(name: str) -> int:
    """MPI_Register_datarep: user representations register by name;
    conversion functions are not invoked (the io engine reads/writes
    native byte order — external32 conversion lives in Pack_external)."""
    _datareps.add(name)
    return MPI_SUCCESS


# -- MPI_T breadth -------------------------------------------------------


def t_cvar_get_info(index: int):
    """(err, name, verbosity, scope) via the str helper pattern:
    returns (err, packed 'name|verbosity|scope') for the shim."""
    try:
        from ompi_tpu.tool import mpit

        info = mpit.cvar_get_info(index)
        return (MPI_SUCCESS, f"{info.name}|{info.verbosity}|{info.scope}")
    except BaseException as e:  # noqa: BLE001
        return (_t_fail(e), "")


def t_cvar_handle_alloc(index: int):
    """cvar handles alias the index (no per-object binding needed)."""
    try:
        from ompi_tpu.tool import mpit

        mpit.cvar_get_info(index)  # validates
        return (MPI_SUCCESS, index + 1)  # 0 = invalid handle
    except BaseException as e:  # noqa: BLE001
        return (_t_fail(e), 0)


def t_cvar_handle_read(handle: int):
    return t_cvar_read(handle - 1)


def t_cvar_handle_write(handle: int, value: int) -> int:
    try:
        from ompi_tpu.tool import mpit

        mpit.cvar_write(handle - 1, value)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _t_fail(e)


def t_pvar_get_info(index: int):
    try:
        from ompi_tpu.tool import mpit

        info = mpit.pvar_get_info(index)
        return (MPI_SUCCESS, f"{info.name}|{info.var_class}")
    except BaseException as e:  # noqa: BLE001
        return (_t_fail(e), "")


def t_pvar_write(index: int, value: int) -> int:
    """pvars here are monotonic counters — only reset-to-zero writes
    are meaningful; MPI_T allows rejecting others."""
    try:
        if value != 0:
            return MPI_ERR_ARG
        return t_pvar_reset(index)
    except BaseException as e:  # noqa: BLE001
        return _t_fail(e)


def t_pvar_reset(index: int) -> int:
    try:
        from ompi_tpu.tool import mpit

        mpit.pvar_reset_one(index)
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _t_fail(e)


def t_pvar_readreset(index: int):
    try:
        rc = t_pvar_read(index)
        if not isinstance(rc, tuple) or rc[0] != MPI_SUCCESS:
            return rc if isinstance(rc, tuple) else (rc, 0)
        reset_rc = t_pvar_reset(index)
        if reset_rc != MPI_SUCCESS:
            # a non-resettable pvar (trace_events watermark) must not
            # report success while silently keeping its value — the
            # caller's per-interval deltas would double-count forever
            return (reset_rc, rc[1])
        return rc
    except BaseException as e:  # noqa: BLE001
        return (_t_fail(e), 0)


def t_category_get_num():
    try:
        from ompi_tpu.tool import mpit

        return (MPI_SUCCESS, mpit.category_get_num())
    except BaseException as e:  # noqa: BLE001
        return (_t_fail(e), 0)


def t_category_get_info(index: int):
    """(err, 'name|num_cvars')."""
    try:
        from ompi_tpu.tool import mpit

        name, ncvars = mpit.category_get_info(index)
        return (MPI_SUCCESS, f"{name}|{ncvars}")
    except BaseException as e:  # noqa: BLE001
        return (_t_fail(e), "")


def t_category_get_index(name: str):
    try:
        from ompi_tpu.tool import mpit

        cats = [c[0] for c in mpit._categories()]
        return (MPI_SUCCESS, cats.index(name))
    except ValueError:
        return (MPI_ERR_ARG, 0)
    except BaseException as e:  # noqa: BLE001
        return (_t_fail(e), 0)


def t_category_get_cvars(index: int, maxn: int, out_ptr: int) -> int:
    try:
        from ompi_tpu.tool import mpit

        name, _ = mpit.category_get_info(index)
        idxs = [i for i, v in enumerate(mpit._cvar_names())
                if v.split("_", 1)[0] == name][:maxn]
        if idxs:
            _view(out_ptr, len(idxs), 7)[:] = idxs
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _t_fail(e)


def t_category_get_pvars(index: int, maxn: int, out_ptr: int) -> int:
    try:
        from ompi_tpu.tool import mpit

        del index  # pvars are uncategorized: every category reports none
        del maxn, out_ptr
        return MPI_SUCCESS
    except BaseException as e:  # noqa: BLE001
        return _t_fail(e)


def t_category_changed():
    """Category layout is fixed after init: a constant stamp."""
    return (MPI_SUCCESS, 1)
