"""Point-to-point messaging layer — the pml/ob1-equivalent.

TPU-native re-design of ``ompi/mca/pml/ob1`` (SURVEY.md §2.2: the
matching engine under MPI_Send/Recv, fragment callbacks
``mca_pml_ob1_recv_frag_callback_match`` [bin]) reduced to its semantic
core. In the single-controller model every rank lives in one address
space and all bulk data is resident on the fabric, so ob1's byte
machinery (BTL scheduling, eager/rendezvous, convertor fragmentation)
collapses; what remains — and is preserved faithfully — is **MPI
matching semantics**:

* posted-receive queue + unexpected-message queue per communicator
  (the two queues at the heart of ob1's matching);
* match on (source, tag) with ``ANY_SOURCE``/``ANY_TAG`` wildcards;
* the non-overtaking rule: messages from the same (source, comm) match
  posted receives in send order;
* ``Status`` carrying (source, tag, count); probe/iprobe.

Send is **buffered eager**: the payload is copied at send time (device
arrays: device-to-device put onto the receiver's device — the ICI
analog of the sm BTL's copy-in/copy-out), so the sender's buffer is
immediately reusable, matching MPI_Send's local-completion liberty.
"""

from __future__ import annotations

import collections
import threading
from dataclasses import dataclass, field
from typing import Any

import jax
import numpy as np

from ompi_tpu.core.errors import MPIArgError, MPIRankError
from ompi_tpu.metrics import core as _metrics
from ompi_tpu.request import Request
from ompi_tpu.tool import spc
from ompi_tpu.trace import core as _trace
from ompi_tpu.trace import waitgraph as _waitgraph

ANY_SOURCE = -1
ANY_TAG = -1
PROC_NULL = -2


@dataclass
class Status:
    """MPI_Status: envelope of a completed/probed receive."""

    source: int
    tag: int
    count: int  # elements of the payload's dtype
    nbytes: int = 0  # payload bytes (what the C ABI's status carries)

    @classmethod
    def null(cls) -> "Status":
        return cls(PROC_NULL, ANY_TAG, 0, 0)


def _copy_payload(buf, dest_device=None):
    """Eager-copy the payload; device arrays hop to the receiver's
    device (ICI put), host arrays are copied."""
    if isinstance(buf, np.ndarray):
        return buf.copy()
    if isinstance(buf, jax.Array):
        if dest_device is not None:
            return jax.device_put(buf, dest_device)
        return jax.numpy.copy(buf)
    return np.asarray(buf).copy()


def _count_of(payload) -> int:
    try:
        return int(np.prod(np.shape(payload)))
    except Exception:
        return 0


def _nbytes_of(payload) -> int:
    try:
        return int(payload.nbytes)
    except AttributeError:
        try:
            return int(np.asarray(payload).nbytes)
        except Exception:
            return 0


@dataclass
class _Posted:
    source: int
    tag: int
    request: "RecvRequest"
    seq: int


@dataclass
class _Unexpected:
    source: int
    tag: int
    payload: Any
    seq: int


class RecvRequest(Request):
    """Pending receive; completed by the matching engine."""

    def __init__(self):
        super().__init__()
        self._event = threading.Event()
        self.status: Status | None = None
        self._payload: Any = None
        #: cross-process receives: (timeout_s, check, escalate) armed
        #: by the comm layer — see :meth:`arm_remote_guard`
        self._guard = None

    def _deliver(self, payload: Any, status: Status) -> None:
        self._payload = payload
        self.status = status
        self._event.set()

    def arm_remote_guard(self, timeout: float, check, escalate) -> None:
        """Make the blocking wait failure- and deadline-sensitive for a
        receive whose sender lives in another process: ``check()``
        raises once the watched peer is marked failed (ULFM in-band
        error instead of waiting out the deadline), ``escalate(t)``
        raises when the shared ``dcn_recv_timeout`` deadline expires —
        a remote receive must never hang.  Local receives stay
        unguarded: blocking on a not-yet-posted local send is plain
        MPI semantics, not a transport fault."""
        self._guard = (float(timeout), check, escalate)

    def _poll(self) -> bool:
        return self._event.is_set()

    def _block(self) -> None:
        if self._guard is None:
            self._event.wait()
            return
        from ompi_tpu.core.var import Deadline

        timeout, check, escalate = self._guard
        dl = Deadline(timeout)
        wtok = 0
        try:
            while not self._event.wait(dl.slice(0.25)):
                # hang diagnosis: one full slice without delivery is a
                # blocked wait — register lazily (first failed slice)
                if not wtok and _waitgraph._enabled:
                    wtok = _waitgraph.begin(
                        "p2p_recv",
                        peer=getattr(self, "wait_peer", None),
                        plane="host")
                check()
                if dl.expired():
                    escalate(timeout)
                    # escalate returning (not raising) means it chose to
                    # keep waiting — the ANY_SOURCE liveness guard with
                    # every member alive; re-arm so the wait does not
                    # degenerate into a 1 ms busy spin on an expired clock
                    dl = Deadline(timeout)
        finally:
            if wtok:
                _waitgraph.end(wtok)

    def _finalize(self) -> Any:
        return self._payload


class MatchingEngine:
    """Per-communicator matching state (≈ ob1's per-comm match tables).

    Matching walks the queues in arrival order, so the MPI
    non-overtaking guarantee holds: for a given (source, tag) the
    earliest-sent unexpected message (lowest seq) matches first, and
    the earliest-posted receive wins an incoming message.
    """

    def __init__(self, comm_size: int):
        self.comm_size = comm_size
        self._lock = threading.Lock()
        self._seq = 0
        # per destination rank
        self._posted: dict[int, list[_Posted]] = collections.defaultdict(list)
        self._unexpected: dict[int, list[_Unexpected]] = collections.defaultdict(list)

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _check_rank(self, r: int, wild_ok: bool = False) -> None:
        if r == PROC_NULL:
            return
        if wild_ok and r == ANY_SOURCE:
            return
        if not 0 <= r < self.comm_size:
            raise MPIRankError(f"rank {r} outside [0, {self.comm_size})")

    # -- send ----------------------------------------------------------

    def send(self, source: int, dest: int, payload: Any, tag: int,
             dest_device=None, _account: bool = True) -> None:
        """_account=False marks a relayed delivery (DCN frame already
        accounted on the SENDING process) — SPC counts stay sender-side."""
        self._check_rank(source)
        self._check_rank(dest)
        if dest == PROC_NULL:
            return
        if tag < 0:
            raise MPIArgError(f"send tag must be >= 0, got {tag}")
        if _account and spc.attached():
            spc.inc("send")
            spc.inc("send_bytes", spc.payload_nbytes(payload))
        if _account and _metrics._enabled:
            _metrics.observe_size("p2p_send", spc.payload_nbytes(payload))
        t0 = _trace.now() if _trace._enabled else 0
        data = _copy_payload(payload, dest_device)
        with self._lock:
            seq = self._next_seq()
            posted = self._posted[dest]
            for i, p in enumerate(posted):
                if (p.source in (ANY_SOURCE, source)) and (p.tag in (ANY_TAG, tag)):
                    posted.pop(i)
                    p.request._deliver(
                        data,
                        Status(source, tag, _count_of(data), _nbytes_of(data)),
                    )
                    if t0:
                        _trace.complete("p2p", "send", t0, src=source,
                                        dst=dest, tag=tag, matched=True,
                                        nbytes=_nbytes_of(data))
                    return
            self._unexpected[dest].append(_Unexpected(source, tag, data, seq))
        if t0:
            _trace.complete("p2p", "send", t0, src=source, dst=dest, tag=tag,
                            matched=False, nbytes=_nbytes_of(data))

    # -- recv ----------------------------------------------------------

    def irecv(self, dest: int, source: int = ANY_SOURCE, tag: int = ANY_TAG) -> RecvRequest:
        self._check_rank(dest)
        self._check_rank(source, wild_ok=True)
        spc.inc("irecv")
        if _trace._enabled:
            _trace.instant("p2p", "irecv", dst=dest, src=source, tag=tag)
        req = RecvRequest()
        if source == PROC_NULL:
            req._deliver(None, Status.null())
            return req
        with self._lock:
            uq = self._unexpected[dest]
            best = None
            for i, m in enumerate(uq):
                if (source in (ANY_SOURCE, m.source)) and (tag in (ANY_TAG, m.tag)):
                    if best is None or m.seq < uq[best].seq:
                        best = i
            if best is not None:
                m = uq.pop(best)
                req._deliver(
                    m.payload,
                    Status(m.source, m.tag, _count_of(m.payload),
                           _nbytes_of(m.payload)),
                )
                return req
            self._posted[dest].append(_Posted(source, tag, req, self._next_seq()))
        return req

    # -- probe ---------------------------------------------------------

    def iprobe(self, dest: int, source: int = ANY_SOURCE, tag: int = ANY_TAG) -> Status | None:
        """Non-blocking probe: envelope of the first matching unexpected
        message, without consuming it.  PROC_NULL probes "match"
        immediately with the null status (MPI 3.8.2)."""
        self._check_rank(dest)
        self._check_rank(source, wild_ok=True)
        if source == PROC_NULL:
            return Status.null()
        with self._lock:
            best = None
            for m in self._unexpected[dest]:
                if (source in (ANY_SOURCE, m.source)) and (tag in (ANY_TAG, m.tag)):
                    if best is None or m.seq < best.seq:
                        best = m
            if best is None:
                return None
            return Status(best.source, best.tag, _count_of(best.payload),
                          _nbytes_of(best.payload))

    def pending_unexpected(self, dest: int) -> int:
        with self._lock:
            return len(self._unexpected[dest])

    def pending_posted(self, dest: int) -> int:
        with self._lock:
            return len(self._posted[dest])
