"""``pml/eager`` MCA component — matching-engine provider.

≈ the pml framework's component slot (ob1/cm/ucx in the reference);
one pml is selected per job (SURVEY.md §2.2 "One pml is selected per
job"), enforced here via Framework.select_one().
"""

from __future__ import annotations

from ompi_tpu.core.registry import Component, register_component
from .pml import MatchingEngine


@register_component
class EagerPmlComponent(Component):
    FRAMEWORK = "pml"
    NAME = "eager"
    PRIORITY = 50

    def register_params(self, store) -> None:
        super().register_params(store)
        store.register(
            "pml", "eager", "max_pending", 1 << 20, type="int",
            help="Soft cap on unexpected-queue length before warnings",
        )

    def make_engine(self, comm_size: int, comm_name: str = "?") -> MatchingEngine:
        return MatchingEngine(comm_size)
