"""``vprotocol`` — message event logging for deterministic replay.

≈ the reference's ``ompi/mca/vprotocol/pessimist`` (SURVEY.md §2.2
vprotocol row): a pml interposer that records every point-to-point
event — and, crucially, the SOURCE each wildcard (ANY_SOURCE) receive
actually matched, which is the nondeterminism a pessimist protocol
must pin down for replay.  Events go to a per-process JSONL file
(``--mca vprotocol_pessimist_log PATH``; rank substituted for ``%r``).

The log is the replay substrate: :func:`load_log` returns the event
stream, and a harness re-running the application can force each
ANY_SOURCE receive to its logged source (event ``match``).  Matching
the reference's scope split: logging here, orchestration in the
replay driver.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any

from ompi_tpu.core.registry import Component, register_component


class LoggedEngine:
    """Proxy over a matching engine, journaling p2p events."""

    def __init__(self, inner, comm_name: str, path: str):
        self._inner = inner
        self._comm_name = comm_name
        self._path = path
        self._lock = threading.Lock()
        self._fh = open(path, "a", buffering=1)

    def _log(self, event: str, **kw) -> None:
        rec = {"event": event, "comm": self._comm_name, **kw}
        with self._lock:
            self._fh.write(json.dumps(rec) + "\n")

    def send(self, source: int, dest: int, payload, tag: int,
             dest_device=None, _account: bool = True) -> None:
        from ompi_tpu.tool.spc import payload_nbytes

        self._inner.send(source, dest, payload, tag, dest_device,
                         _account=_account)
        self._log("send", src=source, dst=dest, tag=tag,
                  nbytes=payload_nbytes(payload))

    def irecv(self, dest: int, source: int = -1, tag: int = -1):
        req = self._inner.irecv(dest, source, tag)
        self._log("post", dst=dest, src=source, tag=tag)
        wildcard = source == -1
        log = self._log
        once = threading.Lock()
        done = [False]

        def log_match(status):
            # exactly ONE match record per receive: the wrapped deliver
            # and the already-completed branch below can race when the
            # engine delivers between the swap and the test()
            with once:
                if done[0]:
                    return
                done[0] = True
            log("match", dst=dest, src=int(status.source),
                tag=int(status.tag), wildcard=wildcard)

        orig_deliver = req._deliver

        def deliver(payload, status):
            orig_deliver(payload, status)
            log_match(status)

        req._deliver = deliver
        # already-completed (unexpected-queue hit): _deliver already ran
        if req.test():
            log_match(req.status)
        return req

    def __getattr__(self, name):
        return getattr(self._inner, name)


def load_log(path: str) -> list[dict[str, Any]]:
    """The journaled event stream (replay-driver input)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


@register_component
class VprotocolPmlComponent(Component):
    """pml/vprotocol — outbids the plain pml when a log path is set."""

    FRAMEWORK = "pml"
    NAME = "vprotocol"
    PRIORITY = 85  # above monitoring (80): logging wraps accounting

    def register_params(self, store) -> None:
        super().register_params(store)
        self._store = store
        store.register(
            "vprotocol", "pessimist", "log", "", type="string",
            help="Per-process p2p event-log path ('%%r' -> rank) — "
            "enables message logging (≈ vprotocol/pessimist)",
        )

    def open(self, store) -> bool:
        self._store = store
        return bool(store.get("vprotocol_pessimist_log", ""))

    def make_engine(self, comm_size: int, comm_name: str = "?"):
        from ompi_tpu.p2p.pml import MatchingEngine

        inner = MatchingEngine(comm_size)
        # compose with monitoring when both are enabled (the stacked
        # pml shims of the reference)
        if bool(self._store.get("monitoring_base_enable", False)):
            from ompi_tpu.tool.monitoring import MonitoredEngine

            inner = MonitoredEngine(inner, comm_name, comm_size)
        path = str(self._store.get("vprotocol_pessimist_log"))
        path = path.replace("%r", os.environ.get("OMPI_TPU_PROC", "0"))
        return LoggedEngine(inner, comm_name, path)
