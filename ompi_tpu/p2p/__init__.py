"""Point-to-point layer (≈ ompi/mca/pml, SURVEY.md §2.2)."""

from .pml import (  # noqa: F401
    ANY_SOURCE,
    ANY_TAG,
    PROC_NULL,
    MatchingEngine,
    RecvRequest,
    Status,
)
from .component import EagerPmlComponent  # noqa: F401
