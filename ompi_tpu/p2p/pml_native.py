"""Native matching engine bridge — pml over libtpudcn's C matcher.

≈ the hot half of ``ompi/mca/pml/ob1`` (SURVEY.md §2.2: the matching
engine under MPI_Send/Recv) moved to C++: posted/unexpected queues,
wildcard matching, and the non-overtaking rule all live in
``native/src/dcn.cc``; a blocked ``recv`` sleeps on a C condition
variable the C receiver thread signals — no Python between wire and
wakeup.  This module is the thin Python face: argument checks, SPC
accounting, the buffered-eager copy for local sends, and Request/
Status materialization.

Same-process sends enter the C matcher as HANDLE references (the
payload object stays in a Python-side table), so ANY_SOURCE receives
match local and remote senders in one total arrival order — the
single-queue property ob1's matching relies on.

Selected automatically for communicators whose pml is the default
``eager`` component on a native DCN engine; monitored/logged pmls
(monitoring, vprotocol) keep the Python engine via the dispatcher
path.
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from ompi_tpu.core.errors import MPIArgError, MPIRankError
from ompi_tpu.request import Request
from ompi_tpu.tool import spc
from ompi_tpu.trace import waitgraph as _waitgraph
from .pml import (
    ANY_SOURCE,
    ANY_TAG,
    PROC_NULL,
    Status,
    _copy_payload,
    _count_of,
    _nbytes_of,
)


class NativeRecvRequest(Request):
    """Pending receive whose completion lives in the C engine."""

    def __init__(self, root, rid: int):
        super().__init__()
        self._root = root
        self._rid = rid
        self._msg = None
        self.status: Status | None = None
        self._lock = threading.Lock()
        #: cross-process receives: (timeout_s, check, escalate) — the
        #: same remote-recv guard contract as pml.RecvRequest
        self._guard = None

    def arm_remote_guard(self, timeout: float, check, escalate) -> None:
        self._guard = (float(timeout), check, escalate)

    def _take(self, msg) -> None:
        from ompi_tpu.dcn.native import _wrap_payload

        if msg.pyhandle:
            payload = self._root.take_handle(msg.pyhandle)
            count, nbytes = int(msg.count), int(msg.nbytes)
        else:
            payload = _wrap_payload(self._root._lib, msg)
            count, nbytes = int(payload.size), int(payload.nbytes)
        self._msg = payload
        self.status = Status(int(msg.src), int(msg.tag), count, nbytes)

    def _poll(self) -> bool:
        from ompi_tpu.dcn.native import TdcnMsg

        with self._lock:
            if self._msg is not None:
                return True
            msg = TdcnMsg()
            rc = self._root._lib.tdcn_req_test(
                self._root._h, self._rid, ctypes.byref(msg))
            if rc == 0:
                self._take(msg)
                return True
            return False

    def _block(self) -> None:
        from ompi_tpu.dcn.native import TdcnMsg, _RC_CLOSED

        dl = None
        if self._guard is not None:
            from ompi_tpu.core.var import Deadline

            dl = Deadline(self._guard[0])
        with self._lock:
            if self._msg is not None:
                return
            msg = TdcnMsg()
            wtok = 0
            try:
                while True:
                    rc = self._root._lib.tdcn_req_wait(
                        self._root._h, self._rid, 0.25, ctypes.byref(msg))
                    if rc == 0:
                        self._take(msg)
                        return
                    if rc == _RC_CLOSED or rc < 0:
                        from ompi_tpu.core.errors import MPIInternalError

                        raise MPIInternalError(
                            f"native recv wait failed (rc={rc})")
                    # hang diagnosis: a timed-out wait slice means the
                    # request is blocked — register lazily (once)
                    if not wtok and _waitgraph._enabled:
                        wtok = _waitgraph.begin(
                            "p2p_recv",
                            peer=getattr(self, "wait_peer", None),
                            plane="native")
                    if dl is not None:
                        _timeout, check, escalate = self._guard
                        check()
                        if dl.expired():
                            escalate(_timeout)
                            # escalate returning = keep waiting (anysrc
                            # liveness guard, all members alive): re-arm
                            dl = Deadline(_timeout)
            finally:
                if wtok:
                    _waitgraph.end(wtok)

    def _finalize(self):
        return self._msg


class _NullRecvRequest(Request):
    def __init__(self):
        super().__init__()
        self.status = Status.null()
        self._complete = True
        self._result = None


class NativeMatchingEngine:
    """Per-communicator matching facade over the root native engine.

    Interface-compatible with :class:`ompi_tpu.p2p.pml.MatchingEngine`
    (send/irecv/iprobe/pending_*) — everything the Comm layers and the
    persistent/partitioned mixins call."""

    def __init__(self, root, cid, comm_size: int):
        self._root = root
        self._cid = str(cid)
        self._cid_b = self._cid.encode()
        self.comm_size = comm_size

    def _check_rank(self, r: int, wild_ok: bool = False) -> None:
        if r == PROC_NULL or (wild_ok and r == ANY_SOURCE):
            return
        if not 0 <= r < self.comm_size:
            raise MPIRankError(f"rank {r} outside [0, {self.comm_size})")

    # -- send (local ranks only; remote riders use the DCN frame path) --

    def send(self, source: int, dest: int, payload, tag: int,
             dest_device=None, _account: bool = True) -> None:
        self._check_rank(source)
        self._check_rank(dest)
        if dest == PROC_NULL:
            return
        if tag < 0:
            raise MPIArgError(f"send tag must be >= 0, got {tag}")
        if _account and spc.attached():
            spc.inc("send")
            spc.inc("send_bytes", spc.payload_nbytes(payload))
        if isinstance(payload, np.ndarray):
            # the engine's local data path memcpys into C — that IS the
            # buffered-eager copy; a Python-side copy first would be a
            # second one
            data = payload
        else:
            data = _copy_payload(payload, dest_device)
        self._root.local_send(self._cid, source, dest, tag, data,
                              _count_of(data), _nbytes_of(data))

    # -- recv -----------------------------------------------------------

    def irecv(self, dest: int, source: int = ANY_SOURCE,
              tag: int = ANY_TAG) -> Request:
        self._check_rank(dest)
        self._check_rank(source, wild_ok=True)
        spc.inc("irecv")
        if source == PROC_NULL:
            return _NullRecvRequest()
        rid = self._root._lib.tdcn_post_recv(
            self._root._h, self._cid_b, dest, source, tag)
        return NativeRecvRequest(self._root, rid)

    # -- probe ----------------------------------------------------------

    def iprobe(self, dest: int, source: int = ANY_SOURCE,
               tag: int = ANY_TAG) -> Status | None:
        from ompi_tpu.dcn.native import TdcnMsg

        self._check_rank(dest)
        self._check_rank(source, wild_ok=True)
        if source == PROC_NULL:
            return Status.null()
        msg = TdcnMsg()
        rc = self._root._lib.tdcn_probe(
            self._root._h, self._cid_b, dest, source, tag,
            ctypes.byref(msg))
        if rc != 0:
            return None
        if msg.pyhandle:
            count, nbytes = int(msg.count), int(msg.nbytes)
        else:
            dt = np.dtype(msg.dtype.decode() or "u1")
            count = int(msg.nbytes) // max(1, dt.itemsize)
            nbytes = int(msg.nbytes)
        return Status(int(msg.src), int(msg.tag), count, nbytes)

    def recv_blocking(self, dest: int, source: int, tag: int,
                      fail_proc: int = -1, remote: bool = False,
                      guard=None, into=None):
        """Blocking receive in ONE C crossing (match-or-post + sleep on
        the request condvar): the fast path under MPI_Recv.  Returns
        (payload, Status); raises on engine close or watched-proc
        failure — and, for a SPECIFIC REMOTE source (``remote`` is the
        comm layer's verdict), escalates after the shared
        ``dcn_recv_timeout`` deadline instead of re-arming the C wait
        forever.  ANY_SOURCE and local sources keep plain MPI blocking
        semantics: there is no dead transport to escalate — unless the
        comm layer armed ``guard`` (the opt-in ``dcn_anysrc_timeout``
        triple): then expiry runs the guard's communicator-wide
        liveness check and RE-ARMS when every member is alive.

        ``into``: optional contiguous destination ndarray — the ctypes
        ``recv_into`` surface (tdcn_precv_into): the post carries the
        buffer, so a racing in-order streamed RTS lands its FRAGs
        straight in it and a copy-path delivery is memcpy'd into it in
        C.  When placement/fill happened the returned payload IS
        ``into`` (identity check — nothing left to copy); oversized
        messages fall back to the engine-owned payload for the
        caller's truncation handling."""
        from ompi_tpu.dcn.native import _tls, _tls_msg, _wrap_payload

        self._check_rank(dest)
        self._check_rank(source, wild_ok=True)
        spc.inc("irecv")
        if source == PROC_NULL:
            return None, Status.null()
        root = self._root
        msg = _tls_msg()
        into_ptr = 0
        into_cap = 0
        if into is not None:
            if not (isinstance(into, np.ndarray)
                    and into.flags["C_CONTIGUOUS"]):
                into = None
            else:
                into_ptr = into.ctypes.data
                into_cap = into.nbytes
        dl = None
        anysrc_guard = None
        if remote and source != ANY_SOURCE:
            from ompi_tpu.core.var import Deadline

            dl = Deadline.for_timeout("recv")
        elif guard is not None and source == ANY_SOURCE:
            from ompi_tpu.core.var import Deadline

            anysrc_guard = guard
            dl = Deadline(guard[0])
        wtok = 0
        try:
            while True:
                if into is not None:
                    rc = root._lib.tdcn_precv_into(
                        root._h, self._cid_b, dest, source, tag, fail_proc,
                        dl.slice(2.0) if dl is not None else 120.0,
                        into_ptr, into_cap, _tls.msg_ref)
                else:
                    rc = root._lib.tdcn_precv(
                        root._h, self._cid_b, dest, source, tag, fail_proc,
                        dl.slice(2.0) if dl is not None else 120.0,
                        _tls.msg_ref)
                if rc == 0:
                    break
                if rc == -2:
                    from ompi_tpu.core.errors import MPIProcFailedError

                    raise MPIProcFailedError(
                        f"recv: peer rank {source} failed",
                        failed=(source,))
                if rc < 0:
                    from ompi_tpu.core.errors import MPIInternalError

                    raise MPIInternalError(f"native recv failed (rc={rc})")
                # hang diagnosis: one expired C wait slice without a
                # match — register the blocked site lazily (once).
                # precv parks inside the C call, which does not hit the
                # engine's own wait registry, so this is the only
                # introspection point for the native p2p plane.
                if not wtok and _waitgraph._enabled:
                    wtok = _waitgraph.begin(
                        "p2p_recv",
                        peer=fail_proc if fail_proc >= 0 else None,
                        plane="native", cid=self._cid)
                if dl is not None and dl.expired():
                    if anysrc_guard is not None:
                        from ompi_tpu.core.var import Deadline

                        _t, g_check, g_escalate = anysrc_guard
                        g_check()
                        g_escalate(_t)
                        dl = Deadline(_t)  # all alive: re-arm the wait
                        continue
                    root._escalate_deadline(
                        "p2p_recv", dl.seconds,
                        f"recv deadline (dcn_recv_timeout={dl.seconds}s) "
                        f"expired: rank {dest} waiting for rank {source} "
                        f"(tag={tag}) — peer dead, wedged, or send never "
                        f"issued", failed_rank=source, root_proc=fail_proc,
                        src=int(source), tag=int(tag))
        finally:
            if wtok:
                _waitgraph.end(wtok)
        if msg.pyhandle:
            payload = root.take_handle(msg.pyhandle)
            count, nbytes = int(msg.count), int(msg.nbytes)
        elif into is not None and msg.data == into_ptr:
            # delivered in place (streamed RTS fill, ring eager
            # placement, or the C-side memcpy): the payload IS the
            # caller's buffer — identity tells the caller nothing is
            # left to copy or free
            payload = into
            nbytes = int(msg.nbytes)
            dt = np.dtype(msg.dtype.decode() or "u1")
            count = nbytes // max(1, dt.itemsize)
        else:
            payload = _wrap_payload(root._lib, msg)
            count, nbytes = int(payload.size), int(payload.nbytes)
        return payload, Status(int(msg.src), int(msg.tag), count, nbytes)

    def pending_unexpected(self, dest: int) -> int:
        return int(self._root._lib.tdcn_pending(
            self._root._h, self._cid_b, dest, 0))

    def pending_posted(self, dest: int) -> int:
        return int(self._root._lib.tdcn_pending(
            self._root._h, self._cid_b, dest, 1))
