"""Partitioned point-to-point (MPI-4 ``MPI_Psend_init``/``MPI_Precv_init``).

≈ the reference's ``mca/part/persist`` component (SURVEY.md §2.2 part
row, ≥5.0): a persistent channel whose send buffer is filled in
partitions, each marked ready with ``pready(i)``; the transfer may
complete partition-by-partition or aggregated — this implementation
aggregates (a conforming choice the reference's persist component also
makes for small partition counts): the message is handed to the pml
when the LAST partition is marked ready, so partially-ready starts
never publish stale bytes.

Receiver side: ``parrived(i)`` reports per-partition arrival; with
aggregated transfer all partitions arrive together, which conforms
(arrival may be observed late, never early).
"""

from __future__ import annotations

import numpy as np

from ompi_tpu.core.errors import MPIArgError, MPIRequestError
from ompi_tpu.request import Request


class PersistentP2PMixin:
    """Persistent (Send_init/Recv_init) and partitioned (Psend/Precv)
    channel constructors over any communicator exposing ``send`` /
    ``irecv`` — shared by Comm and MultiProcComm."""

    def send_init(self, buf, source: int, dest: int, tag: int = 0):
        """MPI_Send_init: persistent send channel.  ``buf`` is held by
        reference — each ``start()`` sends its CURRENT contents, the
        standard's refill-between-starts contract."""
        from ompi_tpu.request import CompletedRequest, PersistentRequest

        def dispatch():
            self.send(buf, source, dest, tag)
            return CompletedRequest()

        return PersistentRequest(dispatch)

    def recv_init(self, dest: int, source: int | None = None,
                  tag: int | None = None):
        """MPI_Recv_init: persistent receive channel."""
        from ompi_tpu.request import PersistentRequest

        return PersistentRequest(lambda: self.irecv(dest, source, tag))

    def psend_init(self, buf, partitions: int, source: int, dest: int,
                   tag: int = 0):
        """MPI_Psend_init (partitioned send — see module docstring)."""
        return PsendRequest(self, buf, partitions, source, dest, tag)

    def precv_init(self, partitions: int, dest: int,
                   source: int | None = None, tag: int | None = None):
        """MPI_Precv_init."""
        return PrecvRequest(self, partitions, dest, source, tag)


class PsendRequest(Request):
    """Partitioned send channel (MPI_Psend_init → Start → Pready*)."""

    def __init__(self, comm, buf, partitions: int, source: int, dest: int,
                 tag: int):
        super().__init__()
        if partitions < 1:
            raise MPIArgError(f"partitions must be >= 1, got {partitions}")
        arr = np.asarray(buf)
        if arr.shape[0] % partitions:
            raise MPIArgError(
                f"leading dim {arr.shape[0]} not divisible into "
                f"{partitions} partitions"
            )
        self.comm = comm
        self.buf = arr  # by reference: Start() reads current contents
        self.partitions = partitions
        self.source, self.dest, self.tag = source, dest, tag
        self._active = False
        self._ready: set[int] = set()
        self._complete = True  # inactive persistent requests are complete
        #: memchecker-lite: partition → adler32 at pready time
        self._part_sums: dict[int, int] = {}

    def start(self) -> "PsendRequest":
        if self._active:
            raise MPIRequestError("partitioned send started while active")
        self._active = True
        self._ready.clear()
        self._part_sums.clear()
        self._complete = False
        return self

    def _partition_view(self, partition: int) -> np.ndarray:
        rows = self.buf.shape[0] // self.partitions
        return self.buf[partition * rows : (partition + 1) * rows]

    def pready(self, partition: int) -> None:
        """MPI_Pready: partition may be sent.  On the last one the
        aggregated message goes to the matching engine.

        Memchecker-lite (SURVEY.md §5b): filling a partition BEFORE its
        pready is legal, so the guard is per-partition — an adler32
        snapshot at pready, re-verified when the aggregated transfer
        dispatches; a partition mutated after its pready raises instead
        of silently publishing torn bytes."""
        from ompi_tpu.tool import memchecker

        if not self._active:
            raise MPIRequestError("pready before start")
        if not 0 <= partition < self.partitions:
            raise MPIArgError(f"partition {partition} out of range")
        if partition in self._ready:
            raise MPIRequestError(f"partition {partition} already ready")
        self._ready.add(partition)
        if memchecker.attached():
            self._part_sums[partition] = memchecker.checksum(
                self._partition_view(partition))
        if len(self._ready) == self.partitions:
            if self._part_sums:
                for part, sum0 in self._part_sums.items():
                    if memchecker.checksum(self._partition_view(part)) != sum0:
                        raise memchecker.MPIBufferError(
                            f"partition {part} mutated after its pready "
                            f"(partitioned send publishes ready "
                            f"partitions; memchecker diagnostic)"
                        )
            self.comm.send(np.asarray(self.buf).copy(), source=self.source,
                           dest=self.dest, tag=self.tag)
            self._active = False
            self._complete = True

    def pready_range(self, lo: int, hi: int) -> None:
        for p in range(lo, hi + 1):
            self.pready(p)

    def _poll(self) -> bool:
        return not self._active

    def _block(self) -> None:
        if self._active:
            raise MPIRequestError(
                f"wait on partitioned send with only {len(self._ready)}/"
                f"{self.partitions} partitions ready — mark all with pready"
            )


class PrecvRequest(Request):
    """Partitioned receive channel (MPI_Precv_init → Start → Parrived)."""

    def __init__(self, comm, partitions: int, dest: int, source: int,
                 tag: int):
        super().__init__()
        if partitions < 1:
            raise MPIArgError(f"partitions must be >= 1, got {partitions}")
        self.comm = comm
        self.partitions = partitions
        self.dest, self.source, self.tag = dest, source, tag
        self._inner = None
        self._complete = True

    def start(self) -> "PrecvRequest":
        if self._inner is not None and not self._inner.test():
            raise MPIRequestError("partitioned recv started while active")
        self._inner = self.comm.irecv(self.dest, self.source, self.tag)
        self._complete = False
        return self

    def parrived(self, partition: int) -> bool:
        """MPI_Parrived: has this partition's data arrived?"""
        if not 0 <= partition < self.partitions:
            raise MPIArgError(f"partition {partition} out of range")
        if self._inner is None:
            raise MPIRequestError("parrived before start")
        return self._inner.test()

    @property
    def status(self):
        return None if self._inner is None else self._inner.status

    def _poll(self) -> bool:
        return self._inner is None or self._inner.test()

    def _block(self) -> None:
        if self._inner is not None:
            self._inner.wait()

    def _finalize(self):
        return None if self._inner is None else self._inner.wait()
