"""Mesh layer (≈ opal/mca/accelerator + mpool/rcache, SURVEY.md §7.3)."""

from .mesh import AXIS, CommMesh, TpuAcceleratorComponent, world_mesh  # noqa: F401
