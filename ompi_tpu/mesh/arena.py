"""HBM arena — device-staging management for host-sourced buffers.

≈ ``opal/mca/mpool`` + ``opal/mca/rcache`` (SURVEY.md §2.3): the
reference preallocates registered host memory so NIC DMA never pays
per-call registration; the TPU analog is HBM staging for buffers that
enter through the host (numpy) API.

**Why there is no literal "H2D into a pooled buffer" path.**  Under
PJRT/IFRT a host→device transfer *always* materializes a new logical
buffer — there is no public API to overwrite an existing device
allocation with host bytes (and on the axon tunnel even
``unsafe_buffer_pointer`` is unimplemented).  The mpool free-list
therefore lives at three levels, all of which this class owns or
accounts:

* **runtime allocator recycling** — successive ``stage_in`` calls of
  the same signature land on XLA's BFC free list, so steady-state
  staging reuses the same HBM *addresses*.  Where the backend exposes
  buffer pointers this is measured per signature
  (``addr_reuse``/``addr_new``); on backends without pointer access
  the counters report -1 (unobservable, not zero).
* **buffer donation** — compiled collectives for shape-preserving ops
  are built with ``donate_argnums`` when their input is the
  framework-owned staged buffer, so XLA writes the result into the
  SAME HBM allocation: steady state is ONE buffer per in-flight
  collective instead of two.  User jax arrays are NEVER donated
  (MPI semantics: sendbuf is preserved).
* **device-buffer free list** — ``acquire``/``release`` pool
  framework-internal device temporaries (barrier tokens, schedule
  scratch) keyed by (shape, dtype): after warm-up every acquisition
  is a pool hit, no allocation, no H2D.  The zero-per-call-alloc path
  for *user* payloads is the persistent-request family
  (``allreduce_init`` …): buffer staged once, program compiled once,
  each ``start()`` re-dispatches on the same allocation.

Donation is controlled by ``--mca accelerator_tpu_donate_staged`` (the
compiled-callable caches key on the var-store version, so toggling it
takes effect on the next resolution).
"""

from __future__ import annotations

import threading

import jax
import numpy as np

from ompi_tpu.tool import spc

#: free-list depth per (shape, dtype) signature — temporaries are tiny
#: (tokens/scratch); deeper lists would just pin HBM
_POOL_CAP = 4

#: per-signature cap on remembered addresses (bounds _addrs growth)
_ADDR_CAP = 64


class HbmArena:
    """Per-mesh staging manager: free-lists device temporaries, counts
    H2D traffic, allocator-level address reuse, and donation
    resolutions.  Cheap by construction — the per-call cost is one
    attribute test plus integer adds; everything signature-level
    (donation) is accounted at resolution time, not per call."""

    __slots__ = (
        "stage_calls", "stage_bytes", "donate_signatures",
        "pool_hits", "pool_allocs", "addr_reuse", "addr_new",
        "_lock", "_free", "_addrs", "_ptr_ok", "_addr_overflow",
        "_addr_sample",
    )

    def __init__(self):
        self.stage_calls = 0
        self.stage_bytes = 0
        #: call signatures resolved to a donating compiled program
        self.donate_signatures = 0
        self.pool_hits = 0
        self.pool_allocs = 0
        self.addr_reuse = 0
        self.addr_new = 0
        self._lock = threading.Lock()
        #: (shape, dtype str) → free device buffers
        self._free: dict[tuple, list] = {}
        #: (shape, dtype str) → HBM addresses previously handed out
        self._addrs: dict[tuple, set] = {}
        #: backend exposes unsafe_buffer_pointer (axon tunnel: no)
        self._ptr_ok = True
        #: a signature overflowed _ADDR_CAP — reuse counts undercount
        self._addr_overflow = False
        #: stage_in calls seen by the address sampler
        self._addr_sample = 0

    # -- staging accounting --------------------------------------------

    def _note_addr(self, d: jax.Array, key: tuple) -> None:
        """Track whether the runtime allocator recycled an address we
        have staged to before (the BFC free list acting as the mpool).
        Pointer extraction costs tens of us, so stage_in SAMPLES it
        (first 8 calls, then 1-in-8) — the counters are a recycling
        indicator, not an exact census."""
        try:
            shards = d.addressable_shards
            p = shards[0].data.unsafe_buffer_pointer() if shards \
                else d.unsafe_buffer_pointer()
        except Exception:
            self._ptr_ok = False
            return
        with self._lock:
            if len(self._addrs) > 512:  # unbounded-signature backstop
                self._addrs.clear()
            seen = self._addrs.setdefault(key, set())
            if p in seen:
                self.addr_reuse += 1
            else:
                if len(seen) < _ADDR_CAP:
                    seen.add(p)
                else:
                    # can no longer distinguish recycled from fresh for
                    # this signature — flag it instead of lying
                    self._addr_overflow = True
                self.addr_new += 1

    def stage_in(self, host_array: np.ndarray, sharding) -> jax.Array:
        with self._lock:
            self.stage_calls += 1
            self.stage_bytes += host_array.nbytes
        if spc.attached():
            spc.inc("arena_stage_in")
            spc.inc("arena_stage_bytes", host_array.nbytes)
        d = jax.device_put(host_array, sharding)
        if self._ptr_ok:
            self._addr_sample += 1
            if self._addr_sample <= 8 or (self._addr_sample & 7) == 0:
                self._note_addr(d, (host_array.shape, host_array.dtype.str))
        return d

    def note_donation(self) -> None:
        """A collective signature resolved to a donating program."""
        with self._lock:
            self.donate_signatures += 1
        if spc.attached():
            spc.inc("arena_donations")

    # -- device-temporary free list (mpool free list proper) -----------

    def acquire(self, shape: tuple, dtype, sharding) -> jax.Array:
        """A pooled device buffer of the given signature: pool hit when
        one is free, fresh allocation otherwise.  Contents are
        **unspecified** (pool hits return stale bytes — callers use
        these strictly as tokens/scratch whose values are never read;
        there is deliberately no fill parameter so value-dependent use
        cannot be expressed).  The sharding is part of the pool key — a
        replicated token is never served where a rank-sharded one was
        asked for."""
        key = (tuple(shape), np.dtype(dtype).str, sharding)
        with self._lock:
            lst = self._free.get(key)
            while lst:
                buf = lst.pop()
                if not buf.is_deleted():
                    self.pool_hits += 1
                    return buf
            self.pool_allocs += 1
        if spc.attached():
            spc.inc("arena_pool_alloc")
        return jax.device_put(
            np.zeros(shape, np.dtype(dtype)), sharding)

    def release(self, buf: jax.Array) -> None:
        """Return a buffer to the free list (drops it when full or when
        XLA already consumed it through donation)."""
        if buf is None or buf.is_deleted():
            return
        key = (tuple(buf.shape), buf.dtype.str, buf.sharding)
        with self._lock:
            if len(self._free) > 256:  # unbounded-signature backstop:
                self._free.clear()     # drop pooled HBM, keep counters
            lst = self._free.setdefault(key, [])
            if len(lst) < _POOL_CAP:
                lst.append(buf)

    def stats(self) -> dict:
        with self._lock:
            return {
                "stage_calls": self.stage_calls,
                "stage_bytes": self.stage_bytes,
                "donate_signatures": self.donate_signatures,
                "pool_hits": self.pool_hits,
                "pool_allocs": self.pool_allocs,
                "addr_reuse": self.addr_reuse if self._ptr_ok else -1,
                "addr_new": self.addr_new if self._ptr_ok else -1,
                "addr_overflow": self._addr_overflow,
            }
