"""HBM arena — device-staging management for host-sourced buffers.

≈ ``opal/mca/mpool`` + ``opal/mca/rcache`` (SURVEY.md §2.3): the
reference preallocates registered host memory so NIC DMA never pays
per-call registration; the TPU analog is HBM staging for buffers that
enter through the host (numpy) API.  Two mechanisms:

* **staging accounting** — every H2D stage flows through the arena and
  is counted (SPC counters ``arena_stage_in`` / ``arena_stage_bytes``,
  surfaced as MPI_T pvars like every SPC), giving the rcache-style
  visibility into staging traffic;
* **buffer donation** — compiled collectives for shape-preserving ops
  are built with ``donate_argnums`` when their input is the
  framework-owned staged buffer, so XLA writes the result into the
  SAME HBM allocation: steady state is ONE buffer per in-flight
  collective instead of two (mpool free-list reuse, expressed the XLA
  way), halving per-call HBM footprint and allocator traffic — which
  is what raises the largest benchable message size.  User-provided
  jax arrays are NEVER donated (MPI semantics: sendbuf is preserved).

Donation is controlled by ``--mca accelerator_tpu_donate_staged`` (the
compiled-callable caches key on the var-store version, so toggling it
takes effect on the next resolution).
"""

from __future__ import annotations

import threading

import jax
import numpy as np

from ompi_tpu.tool import spc


class HbmArena:
    """Per-mesh staging manager: counts H2D traffic and donation
    resolutions.  Cheap by construction — the per-call cost is one
    attribute test plus integer adds; everything signature-level
    (donation) is accounted at resolution time, not per call."""

    __slots__ = ("stage_calls", "stage_bytes", "donate_signatures", "_lock")

    def __init__(self):
        self.stage_calls = 0
        self.stage_bytes = 0
        #: call signatures resolved to a donating compiled program
        self.donate_signatures = 0
        self._lock = threading.Lock()

    def stage_in(self, host_array: np.ndarray, sharding) -> jax.Array:
        with self._lock:
            self.stage_calls += 1
            self.stage_bytes += host_array.nbytes
        if spc.attached():
            spc.inc("arena_stage_in")
            spc.inc("arena_stage_bytes", host_array.nbytes)
        return jax.device_put(host_array, sharding)

    def note_donation(self) -> None:
        """A collective signature resolved to a donating program."""
        with self._lock:
            self.donate_signatures += 1
        if spc.attached():
            spc.inc("arena_donations")

    def stats(self) -> dict:
        with self._lock:
            return {
                "stage_calls": self.stage_calls,
                "stage_bytes": self.stage_bytes,
                "donate_signatures": self.donate_signatures,
            }
