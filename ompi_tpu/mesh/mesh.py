"""Persistent device mesh — the fabric every communicator rides on.

TPU-native replacement for the reference's transport bring-up: where
``ompi_mpi_init`` opens BTLs and exchanges endpoints via PMIx
(SURVEY.md §3.2), here ``WorldMesh`` enumerates the job's devices ONCE
and pins a persistent ordering; every communicator owns a
``jax.sharding.Mesh`` over a subset of those devices with a single MPI
axis (``AXIS``).  Sub-communicators (comm_split) become sub-meshes over
the split device subsets — the analog of the CID + coll re-selection
path, with the device-order permutation hook playing the role of
``topo/treematch`` rank reordering.

This module is exposed through the MCA ``accelerator`` framework
(component ``accelerator/tpu`` ≈ the north star's ``opal/mca/
accelerator/tpu``), so device handling is selectable/configurable like
every other behavioral unit.
"""

from __future__ import annotations

import threading
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ompi_tpu.core import mca
from ompi_tpu.core.errors import MPIArgError, MPIInternalError
from ompi_tpu.core.registry import Component, register_component

#: the mesh axis name every communicator's collectives run over
AXIS = "mpi"


class CommMesh:
    """A communicator's view of the fabric: an ordered device list and
    the jax Mesh over it."""

    def __init__(self, devices: Sequence[jax.Device]):
        if len(devices) == 0:
            raise MPIArgError("empty device list")
        self.devices = tuple(devices)
        self.device_set = frozenset(self.devices)
        self.mesh = Mesh(np.array(self.devices, dtype=object), (AXIS,))
        self._sharding_cache: dict[tuple, NamedSharding] = {}
        from .arena import HbmArena

        #: staging manager (mpool/rcache analog — SURVEY.md §2.3)
        self.arena = HbmArena()

    @property
    def size(self) -> int:
        return len(self.devices)

    # -- shardings ------------------------------------------------------

    def rank_sharding(self) -> NamedSharding:
        """Leading-axis-over-ranks sharding: rank r's buffer is the r-th
        slice, resident on device r. The canonical layout of every
        rank-major collective input."""
        return self._cached(("rank",), P(AXIS))

    def replicated_sharding(self) -> NamedSharding:
        return self._cached(("rep",), P())

    def _cached(self, key, spec) -> NamedSharding:
        s = self._sharding_cache.get(key)
        if s is None:
            s = NamedSharding(self.mesh, spec)
            self._sharding_cache[key] = s
        return s

    # -- staging (H2D/D2H; ≈ accelerator D2H/H2D + mpool arena) ---------

    def stage_in(self, host_array: np.ndarray) -> jax.Array:
        """Host rank-major (n, ...) buffer → device array sharded one
        rank per device, staged through the HBM arena."""
        if host_array.shape[0] != self.size:
            raise MPIArgError(
                f"rank-major buffer leading dim {host_array.shape[0]} != "
                f"comm size {self.size}"
            )
        return self.arena.stage_in(host_array, self.rank_sharding())

    def stage_out(self, device_array: jax.Array) -> np.ndarray:
        return np.asarray(jax.device_get(device_array))

    def submesh(self, indices: Sequence[int]) -> "CommMesh":
        """Sub-communicator mesh from local rank indices."""
        return CommMesh([self.devices[i] for i in indices])

    def __repr__(self) -> str:  # pragma: no cover
        kinds = {d.platform for d in self.devices}
        return f"<CommMesh {self.size} devices ({','.join(kinds)})>"


@register_component
class TpuAcceleratorComponent(Component):
    """``accelerator/tpu`` — device enumeration + world-mesh bring-up.

    ≈ the north star's new ``opal/mca/accelerator/tpu`` component. Runs on
    any XLA backend (TPU, or the virtual CPU platform used for
    oversubscribed-style testing, SURVEY.md §4).
    """

    FRAMEWORK = "accelerator"
    NAME = "tpu"
    PRIORITY = 50

    def __init__(self):
        super().__init__()
        self._world: CommMesh | None = None
        self._lock = threading.Lock()
        self._device_order: str = "default"

    def register_params(self, store) -> None:
        super().register_params(store)
        self._device_order = store.register(
            "accelerator",
            "tpu",
            "device_order",
            "default",
            help="Device ordering for COMM_WORLD ranks: 'default' (backend "
            "enumeration order, ICI-contiguous on TPU) or 'id' (sort by id)",
            enum=None,
        ).value
        store.register(
            "accelerator", "tpu", "donate_staged", True,
            help="Donate framework-staged input buffers to shape-"
            "preserving compiled collectives so XLA writes results into "
            "the same HBM allocation (mpool-style reuse; user jax "
            "arrays are never donated)",
        )

    def open(self, store) -> bool:
        try:
            return len(jax.devices()) > 0
        except Exception:
            return False

    def world_mesh(self) -> CommMesh:
        """The persistent job-wide mesh (created once, like the persistent
        ICI mesh the north star mandates)."""
        with self._lock:
            if self._world is None:
                devs = list(jax.devices())
                if self._device_order == "id":
                    devs.sort(key=lambda d: d.id)
                self._world = CommMesh(devs)
            return self._world


def world_mesh() -> CommMesh:
    """Module-level accessor: selected accelerator component's world mesh."""
    ctx = mca.default_context()
    fw = ctx.framework("accelerator")
    comp = fw.select_one()
    if not isinstance(comp, TpuAcceleratorComponent):  # future components
        if not hasattr(comp, "world_mesh"):
            raise MPIInternalError(
                f"accelerator component {comp.NAME} lacks world_mesh()"
            )
    return comp.world_mesh()
