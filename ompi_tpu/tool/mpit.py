"""MPI_T — the MPI tool information interface.

≈ ``ompi/mpi/tool/`` (31 ``MPI_T_*`` syms [bin]; SURVEY.md §5(b)):
every MCA var surfaces as a **control variable** (cvar), every SPC /
monitoring counter as a **performance variable** (pvar).  The surface
is the MPI_T session model reduced to its semantic core:

* ``init_thread() / finalize()`` — refcounted tool sessions;
* cvars: ``cvar_get_num / cvar_get_info / cvar_read / cvar_write`` —
  directly over the default context's VarStore (the same uniform var
  system §5-config demands);
* pvars: ``pvar_get_num / pvar_get_info / pvar_read / pvar_reset`` —
  over the SPC counter set (plus monitoring totals);
* categories: ``category_get_num / category_get_info`` — one category
  per framework, as ``ompi_info``'s grouping does.

Handles are plain indices into stable snapshots, matching the MPI_T
index-based C API closely enough that the native shim can bind 1:1.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ompi_tpu.core.errors import MPIArgError, MPIError
from . import spc

# MPI_T verbosity / scope / class constants (values: reference mpi.h)
VERBOSITY_USER_BASIC = 221
SCOPE_ALL_EQ = 60
PVAR_CLASS_COUNTER = 243

_sessions = 0


class MPITNotInitialized(MPIError):
    pass


def init_thread() -> int:
    """MPI_T_init_thread: returns the session nesting level."""
    global _sessions
    _sessions += 1
    return _sessions


def finalize() -> int:
    global _sessions
    if _sessions == 0:
        raise MPITNotInitialized("MPI_T_finalize without init")
    _sessions -= 1
    return _sessions


def _check():
    if _sessions == 0:
        raise MPITNotInitialized("call MPI_T init_thread first")


def _store():
    from ompi_tpu.core import mca

    return mca.default_context().store


# -- control variables (cvars) -----------------------------------------


@dataclass
class CvarInfo:
    name: str
    type: str
    default: Any
    help: str
    scope: int = SCOPE_ALL_EQ
    verbosity: int = VERBOSITY_USER_BASIC


def _cvar_names() -> list[str]:
    return [v.full_name for v in _store().all_vars()]


def cvar_get_num() -> int:
    _check()
    return len(_cvar_names())


def cvar_get_info(index: int) -> CvarInfo:
    _check()
    names = _cvar_names()
    if not 0 <= index < len(names):
        raise MPIArgError(f"cvar index {index} out of range")
    v = _store().get_var(names[index])
    return CvarInfo(v.full_name, v.type, v.default, v.help)


def cvar_index(name: str) -> int:
    """MPI_T_cvar_get_index: name → index."""
    _check()
    try:
        return _cvar_names().index(name)
    except ValueError:
        raise MPIArgError(f"no cvar named {name}") from None


def _at(names: list[str], index: int, kind: str) -> str:
    if not 0 <= index < len(names):
        raise MPIArgError(f"{kind} index {index} out of range")
    return names[index]


def cvar_read(index: int) -> Any:
    _check()
    return _store().get(_at(_cvar_names(), index, "cvar"))


def cvar_write(index: int, value: Any) -> None:
    _check()
    _store().set(_at(_cvar_names(), index, "cvar"), value)


# -- performance variables (pvars) -------------------------------------


@dataclass
class PvarInfo:
    name: str
    var_class: int
    help: str


def _pvar_names() -> list[str]:
    return ["spc_" + k for k in spc.known()]


def pvar_get_num() -> int:
    _check()
    return len(_pvar_names())


def pvar_get_info(index: int) -> PvarInfo:
    _check()
    names = _pvar_names()
    if not 0 <= index < len(names):
        raise MPIArgError(f"pvar index {index} out of range")
    return PvarInfo(names[index], PVAR_CLASS_COUNTER,
                    f"SPC counter {names[index][4:]}")


def pvar_index(name: str) -> int:
    _check()
    try:
        return _pvar_names().index(name)
    except ValueError:
        raise MPIArgError(f"no pvar named {name}") from None


def pvar_read(index: int) -> int:
    _check()
    return spc.get(_at(_pvar_names(), index, "pvar")[4:])


def pvar_reset() -> None:
    _check()
    spc.reset()


def pvar_start() -> None:
    """MPI_T_pvar_start: attach the SPC counters."""
    _check()
    spc.attach(True)


def pvar_stop() -> None:
    _check()
    spc.attach(False)


# -- categories --------------------------------------------------------


def category_get_num() -> int:
    _check()
    return len(_categories())


def category_get_info(index: int) -> tuple[str, int]:
    """(framework name, number of cvars in it)."""
    _check()
    cats = _categories()
    if not 0 <= index < len(cats):
        raise MPIArgError(f"category index {index} out of range")
    return cats[index]


def _categories() -> list[tuple[str, int]]:
    counts: dict[str, int] = {}
    for name in _cvar_names():
        fw = name.split("_", 1)[0]
        counts[fw] = counts.get(fw, 0) + 1
    return sorted(counts.items())
