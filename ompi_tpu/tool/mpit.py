"""MPI_T — the MPI tool information interface.

≈ ``ompi/mpi/tool/`` (31 ``MPI_T_*`` syms [bin]; SURVEY.md §5(b)):
every MCA var surfaces as a **control variable** (cvar), every SPC /
monitoring counter as a **performance variable** (pvar).  The surface
is the MPI_T session model reduced to its semantic core:

* ``init_thread() / finalize()`` — refcounted tool sessions;
* cvars: ``cvar_get_num / cvar_get_info / cvar_read / cvar_write`` —
  directly over the default context's VarStore (the same uniform var
  system §5-config demands);
* pvars: ``pvar_get_num / pvar_get_info / pvar_read / pvar_reset`` —
  over the SPC counter set (plus monitoring totals);
* categories: ``category_get_num / category_get_info`` — one category
  per framework, as ``ompi_info``'s grouping does.

Handles are plain indices into stable snapshots, matching the MPI_T
index-based C API closely enough that the native shim can bind 1:1.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ompi_tpu.core.errors import MPIArgError, MPIError
from . import spc

# MPI_T verbosity / scope / class constants (values: reference mpi.h)
VERBOSITY_USER_BASIC = 221
SCOPE_ALL_EQ = 60
PVAR_CLASS_COUNTER = 243
#: array-valued pvars (the trace latency histograms) — reads return a
#: list of bucket counts, the MPI_T count>1 pvar shape
PVAR_CLASS_AGGREGATE = 246

_sessions = 0


class MPITNotInitialized(MPIError):
    pass


def init_thread() -> int:
    """MPI_T_init_thread: returns the session nesting level."""
    global _sessions
    _sessions += 1
    return _sessions


def finalize() -> int:
    global _sessions
    if _sessions == 0:
        raise MPITNotInitialized("MPI_T_finalize without init")
    _sessions -= 1
    return _sessions


def _check():
    if _sessions == 0:
        raise MPITNotInitialized("call MPI_T init_thread first")


def _store():
    from ompi_tpu.core import mca

    return mca.default_context().store


# -- control variables (cvars) -----------------------------------------


@dataclass
class CvarInfo:
    name: str
    type: str
    default: Any
    help: str
    scope: int = SCOPE_ALL_EQ
    verbosity: int = VERBOSITY_USER_BASIC


def _cvar_names() -> list[str]:
    return [v.full_name for v in _store().all_vars()]


def cvar_get_num() -> int:
    _check()
    return len(_cvar_names())


def cvar_get_info(index: int) -> CvarInfo:
    _check()
    names = _cvar_names()
    if not 0 <= index < len(names):
        raise MPIArgError(f"cvar index {index} out of range")
    v = _store().get_var(names[index])
    return CvarInfo(v.full_name, v.type, v.default, v.help)


def cvar_index(name: str) -> int:
    """MPI_T_cvar_get_index: name → index."""
    _check()
    try:
        return _cvar_names().index(name)
    except ValueError:
        raise MPIArgError(f"no cvar named {name}") from None


def _at(names: list[str], index: int, kind: str) -> str:
    if not 0 <= index < len(names):
        raise MPIArgError(f"{kind} index {index} out of range")
    return names[index]


def cvar_read(index: int) -> Any:
    _check()
    return _store().get(_at(_cvar_names(), index, "cvar"))


def cvar_write(index: int, value: Any) -> None:
    _check()
    _store().set(_at(_cvar_names(), index, "cvar"), value)


# -- performance variables (pvars) -------------------------------------


@dataclass
class PvarInfo:
    name: str
    var_class: int
    help: str


def _pvar_names(refresh: bool = False) -> list[str]:
    """spc counters first (stable indices), then the trace pvars —
    fixed tracer totals plus one count + one latency-histogram pvar
    per (layer, op) with recorded spans — then the metrics pvars:
    the FIXED native transport counter set (``dcn_stall_ns``,
    ``dcn_doorbells``, ``dcn_ring_hwm``, …) and one size-histogram
    pvar per observed op.  Trace and metrics-op names appear in
    first-seen order and each namespace segment only ever GROWS at
    the tail while recording runs (resets zero values in place), so
    an index a tool cached in a pvar handle keeps naming the same
    variable — the index-stability contract C-side handles rely on.
    Segment ORDER enforces that contract: the FIXED sets (spc, dcn)
    come first so the growing tails can never shift them; the trace
    segment precedes the metrics-size segment because it existed
    first (cached trace indices predate metrics), and the size
    segment carries the residual caveat that a trace (layer, op)
    first seen AFTER a size op shifts the size indices — tools that
    cache across warm-up re-resolve by name, as the reference's
    MPI_T_pvar_get_index contract expects."""
    from ompi_tpu import faultsim, metrics
    from ompi_tpu.trace import core as trace

    names = ["spc_" + k for k in spc.known()]
    names += ["dcn_" + k for k in metrics.NATIVE_COUNTERS]
    # faultsim injection counters: a FIXED set (kind catalog is
    # static), placed with the other fixed segments so the growing
    # tails can never shift it
    names += ["faultsim_injected_" + k for k in faultsim.KINDS]
    names += ["trace_events", "trace_dropped"]
    # causal-tracing counters: a FIXED set (PVARS is static), placed
    # with the tracer's fixed pair so the growing tails never shift it
    from ompi_tpu.trace import causal as _tcausal

    names += [f"trace_causal_{k}" for k in _tcausal.PVARS]
    for layer, op in trace.span_ops():
        names.append(f"trace_span_{layer}_{op}_count")
        names.append(f"trace_span_{layer}_{op}_hist")
    for op in metrics.size_ops():
        names.append(f"metrics_size_{op}_hist")
    # straggler profiler: per-op collective call/wait totals (the
    # per-rank leg of arrival-skew attribution) — a grow-only tail
    # like the segments above it
    from ompi_tpu.metrics import straggler as _straggler

    # refresh=True runs one native-provider sweep to DISCOVER new
    # C-fast-path ops; the per-read name lookups pass False so a
    # cached-index pvar_read never pays a sweep per live engine
    for op in _straggler.ops(refresh=refresh):
        names.append(f"straggler_{op}_count")
        names.append(f"straggler_{op}_wait_ns")
    return names


def _trace_key(name: str) -> tuple[str, str]:
    """trace_span_<layer>_<op> → (layer, op); layers never contain an
    underscore, so the first split is unambiguous."""
    layer, _, op = name[len("trace_span_"):].partition("_")
    return layer, op


def _trace_pvar_read(name: str):
    from ompi_tpu.trace import core as trace

    if name == "trace_events":
        return trace.event_count()
    if name == "trace_dropped":
        return trace.dropped()
    if name.startswith("trace_causal_"):
        from ompi_tpu.trace import causal as _tcausal

        return _tcausal.counter(name[len("trace_causal_"):])
    layer, op = _trace_key(name)
    if op.endswith("_count"):
        return trace.span_count(layer, op[: -len("_count")])
    return trace.latency_histogram(layer, op[: -len("_hist")])


def pvar_get_num() -> int:
    _check()
    return len(_pvar_names(refresh=True))


def pvar_get_info(index: int) -> PvarInfo:
    _check()
    names = _pvar_names()
    if not 0 <= index < len(names):
        raise MPIArgError(f"pvar index {index} out of range")
    name = names[index]
    if name.startswith("dcn_"):
        return PvarInfo(name, PVAR_CLASS_COUNTER,
                        f"native DCN transport counter {name[4:]} "
                        "(libtpudcn telemetry block)")
    if name.startswith("faultsim_injected_"):
        return PvarInfo(name, PVAR_CLASS_COUNTER,
                        f"faults of kind {name[len('faultsim_injected_'):]}"
                        " injected by the seeded fault plane")
    if name.startswith("metrics_size_"):
        op = name[len("metrics_size_"):-len("_hist")]
        return PvarInfo(name, PVAR_CLASS_AGGREGATE,
                        f"payload size histogram (log2 byte buckets) {op}")
    if name.startswith("straggler_"):
        op, _, what = name[len("straggler_"):].rpartition("_")
        if name.endswith("_wait_ns"):
            op, what = name[len("straggler_"):-len("_wait_ns")], "wait_ns"
        return PvarInfo(name, PVAR_CLASS_COUNTER,
                        f"collective straggler profiler: {what} for {op} "
                        "(in-op wall time; cross-rank skew joins live)")
    if name.startswith("trace_causal_"):
        return PvarInfo(name, PVAR_CLASS_COUNTER,
                        f"causal tracing {name[len('trace_causal_'):]} "
                        "(per-collective causal records / wire-context "
                        "edges; trace/causal.py)")
    if name.startswith("trace_"):
        if name.endswith("_hist"):
            layer, op = _trace_key(name)
            return PvarInfo(name, PVAR_CLASS_AGGREGATE,
                            f"trace span latency histogram (log2 µs "
                            f"buckets) {layer}/{op[:-len('_hist')]}")
        return PvarInfo(name, PVAR_CLASS_COUNTER, f"trace counter {name[6:]}")
    return PvarInfo(name, PVAR_CLASS_COUNTER, f"SPC counter {name[4:]}")


def pvar_index(name: str) -> int:
    _check()
    try:
        return _pvar_names(refresh=True).index(name)
    except ValueError:
        raise MPIArgError(f"no pvar named {name}") from None


def pvar_read(index: int):
    _check()
    name = _at(_pvar_names(), index, "pvar")
    if name.startswith("dcn_"):
        from ompi_tpu import metrics

        return metrics.native_value(name[4:])
    if name.startswith("faultsim_injected_"):
        from ompi_tpu import faultsim

        return faultsim.injected(name[len("faultsim_injected_"):])
    if name.startswith("metrics_size_"):
        from ompi_tpu import metrics

        return metrics.size_histogram(name[len("metrics_size_"):
                                           -len("_hist")])
    if name.startswith("straggler_"):
        from ompi_tpu.metrics import straggler as _straggler

        if name.endswith("_wait_ns"):
            return _straggler.op_wait_ns(
                name[len("straggler_"):-len("_wait_ns")])
        return _straggler.op_count(name[len("straggler_"):-len("_count")])
    if name.startswith("trace_"):
        return _trace_pvar_read(name)
    return spc.get(name[4:])


def pvar_reset() -> None:
    """Session-wide pvar reset: zero every counter.  Trace aggregates
    zero in place; the event ring, seq counters, and pvar namespace
    survive — resetting counters must not truncate the finalize-time
    timeline, desync cross-rank merge keys, or shift cached indices."""
    _check()
    spc.reset()
    from ompi_tpu.trace import causal as _tcausal
    from ompi_tpu.trace import core as trace

    trace.zero_stats()
    _tcausal.zero_counters()
    from ompi_tpu import metrics
    from ompi_tpu.metrics import straggler as _straggler

    metrics.zero_stats()
    _straggler.zero_stats()


def pvar_reset_one(index: int) -> None:
    """MPI_T_pvar_reset on one handle: zero that variable only (the C
    shim routes here — the namespace owner does the name surgery).

    ``trace_events`` is a buffer watermark whose "reset" would discard
    the recorded timeline (truncating the finalize-time Chrome trace)
    — it is not resettable, like the reference's read-only pvars.  A
    ``_count``/``_hist`` pair are two views of ONE aggregate and reset
    together."""
    _check()
    name = _at(_pvar_names(), index, "pvar")
    from ompi_tpu.trace import core as trace

    if name == "trace_events":
        raise MPIArgError(
            "trace_events is a buffer watermark; resetting it would "
            "discard the recorded timeline (use ompi_tpu.trace.reset())"
        )
    if name == "trace_dropped":
        trace.reset_dropped()
    elif name.startswith("trace_causal_"):
        from ompi_tpu.trace import causal as _tcausal

        _tcausal.reset_counter(name[len("trace_causal_"):])
    elif name.startswith("trace_span_"):
        layer, op = _trace_key(name)
        trace.reset_span_stat(layer, op.rsplit("_", 1)[0])
    elif name.startswith("faultsim_injected_"):
        raise MPIArgError(
            f"{name} is injection evidence for the active fault plan; "
            "it resets with the plan (faultsim.configure), not per pvar"
        )
    elif name.startswith("dcn_"):
        # native counters are append-only in C; reset re-baselines the
        # Python view (reads subtract) — the C plane stays untouched
        from ompi_tpu.metrics import core as _metrics

        _metrics.reset_native(name[4:])
    elif name.startswith("metrics_size_"):
        from ompi_tpu.metrics import core as _metrics

        _metrics.reset_op(name[len("metrics_size_"):-len("_hist")])
    elif name.startswith("straggler_"):
        # _count/_wait_ns are two views of ONE aggregate: reset together
        from ompi_tpu.metrics import straggler as _straggler

        op = (name[len("straggler_"):-len("_wait_ns")]
              if name.endswith("_wait_ns")
              else name[len("straggler_"):-len("_count")])
        _straggler.reset_op(op)
    else:
        spc.reset_one(name[len("spc_"):])


def pvar_start() -> None:
    """MPI_T_pvar_start: attach the SPC counters."""
    _check()
    spc.attach(True)


def pvar_stop() -> None:
    _check()
    spc.attach(False)


# -- categories --------------------------------------------------------


def category_get_num() -> int:
    _check()
    return len(_categories())


def category_get_info(index: int) -> tuple[str, int]:
    """(framework name, number of cvars in it)."""
    _check()
    cats = _categories()
    if not 0 <= index < len(cats):
        raise MPIArgError(f"category index {index} out of range")
    return cats[index]


def _categories() -> list[tuple[str, int]]:
    counts: dict[str, int] = {}
    for name in _cvar_names():
        fw = name.split("_", 1)[0]
        counts[fw] = counts.get(fw, 0) + 1
    return sorted(counts.items())
