"""Monitoring — per-peer traffic accounting at the MCA module layer.

≈ the reference's monitoring components ([bin] ``mca_pml_monitoring.so``,
``mca_coll_monitoring.so``, ``mca_osc_monitoring.so``; SURVEY.md §5(c)):
interpose at the module layer, count messages/bytes per peer per class,
dump matrices at finalize (``mca_pml_monitoring_dump``).

Two interposers:

* :class:`MonitoredEngine` wraps a pml matching engine: every ``send``
  adds (1 message, payload bytes) to the ``(source, dest)`` cell of the
  pt2pt matrix;
* :class:`MonitoringCollComponent` is a coll component at the TOP of
  the stack (priority 99, ``wants_table``) whose module wraps every
  already-stacked slot with a counting shim — the exact stacking trick
  ``coll/monitoring`` uses (provide every op, delegate to the module
  below, account on the way through).

Both are enabled with ``--mca monitoring_base_enable 1``; matrices are
fetched with :func:`flush` (and dumped to the path in
``monitoring_base_output`` at finalize, the ``common/monitoring``
behavior).
"""

from __future__ import annotations

import json
import threading
from typing import Any

import numpy as np

from ompi_tpu.core.registry import Component, register_component

_lock = threading.Lock()
#: (class, comm_name) → size×size [messages, bytes] matrices
_matrices: dict[tuple[str, str], dict[str, np.ndarray]] = {}
#: coll op counts: (comm_name, op) → [calls, bytes]
_coll_counts: dict[tuple[str, str], list[int]] = {}


def _matrix(cls: str, comm_name: str, size: int) -> dict[str, np.ndarray]:
    key = (cls, comm_name)
    with _lock:
        m = _matrices.get(key)
        if m is None:
            m = {
                "messages": np.zeros((size, size), np.int64),
                "bytes": np.zeros((size, size), np.int64),
            }
            _matrices[key] = m
        return m


def account_p2p(comm_name: str, size: int, source: int, dest: int, nbytes: int) -> None:
    m = _matrix("pml", comm_name, size)
    with _lock:
        m["messages"][source, dest] += 1
        m["bytes"][source, dest] += nbytes


def account_coll(comm_name: str, op: str, nbytes: int) -> None:
    key = (comm_name, op)
    with _lock:
        cell = _coll_counts.setdefault(key, [0, 0])
        cell[0] += 1
        cell[1] += nbytes


def flush() -> dict[str, Any]:
    """All accumulated accounting, JSON-shaped (≈ the dump matrices)."""
    with _lock:
        return {
            "p2p": {
                f"{cls}:{comm}": {k: v.tolist() for k, v in m.items()}
                for (cls, comm), m in _matrices.items()
            },
            "coll": {
                f"{comm}:{op}": {"calls": c, "bytes": b}
                for (comm, op), (c, b) in _coll_counts.items()
            },
        }


def reset() -> None:
    with _lock:
        _matrices.clear()
        _coll_counts.clear()


def dump(path: str) -> None:
    """Write the matrices (finalize-time behavior of common/monitoring)."""
    with open(path, "w") as f:
        json.dump(flush(), f, indent=1)


def _register_vars(store) -> None:
    """Shared var registration: either interposer (pml or coll) may open
    first, so both register the common monitoring vars (idempotent)."""
    store.register(
        "monitoring", "base", "enable", False,
        help="Account per-peer pt2pt/coll traffic (≈ --mca pml monitoring)",
    )
    store.register(
        "monitoring", "base", "output", "", type="string",
        help="Path to dump accounting matrices at finalize",
    )


class MonitoredEngine:
    """pml/monitoring: proxy around a matching engine, accounting sends."""

    def __init__(self, inner, comm_name: str, comm_size: int):
        self._inner = inner
        self._comm_name = comm_name
        self._comm_size = comm_size

    def send(self, source: int, dest: int, payload, tag: int,
             dest_device=None, _account: bool = True) -> None:
        from .spc import payload_nbytes

        # deliver first: the engine validates ranks/tag; only a message
        # that was actually sent is accounted
        self._inner.send(source, dest, payload, tag, dest_device,
                         _account=_account)
        if _account and 0 <= dest < self._comm_size:
            account_p2p(self._comm_name, self._comm_size, source, dest,
                        payload_nbytes(payload))

    def __getattr__(self, name):
        return getattr(self._inner, name)


@register_component
class MonitoringPmlComponent(Component):
    """pml/monitoring: outbids pml/eager when enabled, returning a
    counting proxy over the engine it builds underneath (the reference's
    monitoring pml is exactly this shim over the real pml)."""

    FRAMEWORK = "pml"
    NAME = "monitoring"
    PRIORITY = 80  # above eager (50); open() gates on the enable var

    def register_params(self, store) -> None:
        super().register_params(store)
        self._store = store
        _register_vars(store)

    def open(self, store) -> bool:
        self._store = store
        return bool(store.get("monitoring_base_enable", False))

    def make_engine(self, comm_size: int, comm_name: str = "?"):
        from ompi_tpu.p2p.pml import MatchingEngine

        return MonitoredEngine(MatchingEngine(comm_size), comm_name, comm_size)


class MonitoringCollModule:
    """coll/monitoring's module: wraps every stacked slot."""

    def __init__(self, comm, table):
        self.comm = comm
        self._table = table

    def enable(self) -> None:
        pass

    def disable(self) -> None:
        pass

    def provided(self) -> dict[str, Any]:
        out = {}
        for slot, fn in self._table.slots.items():
            out[slot] = self._wrap(slot, fn)
        return out

    def _wrap(self, slot: str, fn):
        comm_name = self.comm.name

        def shim(*args, **kwargs):
            from .spc import payload_nbytes

            account_coll(comm_name, slot, payload_nbytes(args[0]) if args else 0)
            return fn(*args, **kwargs)

        shim.__name__ = f"monitored_{slot}"
        return shim


@register_component
class MonitoringCollComponent(Component):
    FRAMEWORK = "coll"
    NAME = "monitoring"
    PRIORITY = 99  # top of the stack: wraps tuned/xla/basic slots

    def register_params(self, store) -> None:
        super().register_params(store)
        self._store = store
        _register_vars(store)  # either framework may open first

    def open(self, store) -> bool:
        self._store = store
        return bool(store.get("monitoring_base_enable", False))

    def query(self, comm, table=None):
        if table is None or not table.slots:
            return None
        return MonitoringCollModule(comm, table)

    query.wants_table = True
