"""Memchecker-lite — API-level buffer-state validation.

≈ ``opal/mca/memchecker/valgrind`` (SURVEY.md §5b): the reference marks
user buffers defined/undefined across MPI calls under Valgrind so that
races like *mutating a sendbuf owned by an in-flight nonblocking
operation* surface as diagnostics instead of silent corruption.  The
TPU-native analog guards host (numpy) buffers handed to asynchronous
operations whose implementation reads them over a window of time — the
DCN-level i-collectives and partitioned sends (single-controller
i-collectives copy to HBM synchronously at issue, so there is no
mutation window to guard):

* **write-protect** — the buffer's ``writeable`` flag is cleared for
  the in-flight window, so a mutation raises ``ValueError`` AT THE
  MUTATION SITE (the valgrind-style early report).  Restored on
  completion (only if the guard cleared it — a buffer the user already
  made read-only stays read-only).
* **checksum** — an adler32 snapshot at issue, re-verified at
  completion: catches mutations that bypass the flag (a second view of
  the same memory, ``writeable`` flipped back by the user) and raises
  :class:`MPIBufferError` with the operation name.

Opt-in like the reference (``--enable-memchecker``): enable with
``--mca memchecker_base_enable 1`` /
``OMPI_MCA_memchecker_base_enable=1`` or programmatically via
:func:`attach`.  Off = literally zero work (one module-flag test per
issue).
"""

from __future__ import annotations

import zlib

import numpy as np

from ompi_tpu.core.errors import MPIInternalError


class MPIBufferError(MPIInternalError):
    """A buffer owned by an in-flight operation was mutated."""


_attached = False


def attach(flag: bool = True) -> None:
    global _attached
    _attached = flag


def attached() -> bool:
    return _attached


def register_var(store) -> None:
    store.register(
        "memchecker", "base", "enable", False,
        help="Guard host buffers owned by in-flight nonblocking "
        "operations: write-protect for the in-flight window and "
        "checksum-verify at completion (≈ --enable-memchecker)",
    )


def sync_from_store(store) -> None:
    attach(bool(store.get("memchecker_base_enable", False)))


def checksum(arr: np.ndarray) -> int:
    """The snapshot checksum every guard uses (one definition, so the
    i-collective and partitioned-send guards can never diverge)."""
    return zlib.adler32(
        np.ascontiguousarray(arr).view(np.uint8).reshape(-1))


class Guard:
    """One in-flight buffer guard; ``release()`` exactly once."""

    __slots__ = ("buf", "opname", "checksum", "_cleared_flag")

    def __init__(self, buf: np.ndarray, opname: str):
        self.buf = buf
        self.opname = opname
        self.checksum = checksum(buf)
        self._cleared_flag = False
        if buf.flags.writeable:
            try:
                buf.flags.writeable = False
                self._cleared_flag = True
            except ValueError:
                pass  # view of a non-owning base: checksum still guards

    def abandon(self) -> None:
        """Restore writeability without verifying (operation failed —
        its own exception is the diagnostic)."""
        if self._cleared_flag:
            try:
                self.buf.flags.writeable = True
            except ValueError:
                pass

    def release(self) -> None:
        self.abandon()
        if checksum(self.buf) != self.checksum:
            raise MPIBufferError(
                f"buffer owned by in-flight {self.opname} was mutated "
                f"before completion (MPI forbids touching a pending "
                f"operation's buffer; enable-memchecker diagnostic)"
            )


def guard(buf, opname: str) -> Guard | None:
    """Guard ``buf`` for an in-flight window; None when detached or the
    buffer is not host memory."""
    if not _attached or not isinstance(buf, np.ndarray):
        return None
    return Guard(buf, opname)
