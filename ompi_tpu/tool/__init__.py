"""Tool/observability layer: SPC counters, MPI_T, monitoring.

≈ SURVEY.md §5 "Tracing / profiling": PMPI interposition lives in the
native shim (mpi.h weak symbols); this package holds the Python-side
surface — :mod:`spc` (software performance counters), :mod:`mpit`
(MPI_T cvar/pvar introspection), :mod:`monitoring` (per-peer traffic
matrices at the pml/coll module layer).
"""

from . import monitoring, mpit, spc  # noqa: F401
