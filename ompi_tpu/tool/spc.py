"""SPC — software performance counters.

≈ ``ompi/runtime/ompi_spc.c`` (SURVEY.md §5(d): "cheap in-path counters
exposed via MPI_T pvars", present since 4.0).  The reference counts at
the MPI API layer (MPI_Allreduce increments ``OMPI_SPC_ALLREDUCE``);
here the api/comm entry points call :func:`inc` the same way.  Counters
cost one dict update when attached and one boolean check when not (the
reference's compile-time gate becomes a runtime flag — ``--mca
runtime_spc_attach all`` ≈ the ``mpi_spc_attach_all`` var).

Every counter surfaces as an MPI_T pvar through
:mod:`ompi_tpu.tool.mpit`.

Reset semantics follow the metrics core's grow-only pvar index rule
(:mod:`ompi_tpu.metrics.core`): counters zero IN PLACE — a key once
touched stays in :func:`snapshot` forever, so a tool diffing two
snapshots across a reset never sees a name vanish, and cached pvar
handles keep naming the same variable.  ``*_bytes`` increments also
route their payload size through the metrics core's shared log2
histogram buckets when metrics are enabled — one bucket convention
across SPC, the per-op histograms, and the Prometheus export.
"""

from __future__ import annotations

import threading

from ompi_tpu.metrics import core as _metrics

_lock = threading.Lock()
_counters: dict[str, int] = {}
_attached = False

#: non-collective counter names (the reference's OMPI_SPC_* set trimmed
#: to events this framework actually increments; collective counters are
#: one per coll-table slot, appended by :func:`known`)
_BASE_KNOWN = (
    "send", "send_bytes", "irecv",
    "put", "put_bytes", "get", "get_bytes", "accumulate",
    "file_write_bytes", "file_read_bytes",
    "arena_stage_in", "arena_stage_bytes", "arena_donations",
    "arena_pool_alloc",
)

_known_cache: tuple[str, ...] | None = None


def known() -> tuple[str, ...]:
    """Every counter name this build can increment — the MPI_T pvar
    namespace.  Collective names are the coll-table slots (allreduce,
    iallreduce, allreduce_init, …), incremented by CollTable.lookup."""
    global _known_cache
    if _known_cache is None:
        from ompi_tpu.coll.module import all_slots  # lazy: import cycle

        _known_cache = tuple(all_slots()) + _BASE_KNOWN
    return _known_cache


def payload_nbytes(p) -> int:
    """Byte size of a send/collective payload (shared accounting helper)."""
    nb = getattr(p, "nbytes", None)
    if nb is not None:
        return int(nb)
    try:
        import numpy as _np

        return int(_np.asarray(p).nbytes)
    except Exception:
        return 0


def attach(flag: bool = True) -> None:
    """Enable/disable counting (≈ mpi_spc_attach_all)."""
    global _attached
    _attached = flag


def attached() -> bool:
    return _attached


def inc(name: str, n: int = 1) -> None:
    """Hot-path increment: one flag check when detached."""
    if not _attached:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + n
    if _metrics._enabled and name.endswith("_bytes"):
        _metrics.observe_size("spc_" + name[:-len("_bytes")], n)


# -- C-ABI fast-path merge ----------------------------------------------
# The shim's C collective fast path never crosses embedded Python, so
# its MPI_Allreduce/Bcast/... calls cannot tick inc() — they accrue in
# a C-side per-op array instead (shim.c g_fp_coll_spc) and merge here
# at READ time: zero hot-path cost, and the spc_* pvars keep ticking
# under stock C programs.  Outside a shim-hosted process the symbol
# probe fails once and the merge is a no-op.

_NATIVE_SLOTS = ("barrier", "bcast", "reduce", "allreduce", "allgather")
_native_fn = None
_native_probed = False
_native_base: dict[str, int] = {}


def _native_counts() -> dict[str, int]:
    global _native_fn, _native_probed
    if not _native_probed:
        _native_probed = True
        try:
            import ctypes

            lib = ctypes.CDLL(None)
            fn = lib.tpumpi_coll_spc
            fn.argtypes = [ctypes.c_longlong * len(_NATIVE_SLOTS)]
            fn.restype = None
            _native_fn = fn
        except (OSError, AttributeError, TypeError):
            _native_fn = None
    if _native_fn is None:
        return {}
    import ctypes

    buf = (ctypes.c_longlong * len(_NATIVE_SLOTS))()
    _native_fn(buf)
    return {n: int(buf[i]) for i, n in enumerate(_NATIVE_SLOTS)}


def get(name: str) -> int:
    nat = 0
    if name in _NATIVE_SLOTS:
        nc = _native_counts()
        if nc:
            nat = max(0, nc[name] - _native_base.get(name, 0))
    with _lock:
        return _counters.get(name, 0) + nat


def snapshot() -> dict[str, int]:
    with _lock:
        out = dict(_counters)
    nc = _native_counts()
    for n, v in nc.items():
        d = max(0, v - _native_base.get(n, 0))
        if d or n in out:
            out[n] = out.get(n, 0) + d
    return out


def reset() -> None:
    """Zero every counter IN PLACE — touched keys stay visible in
    :func:`snapshot` (the grow-only index rule; dropping keys made
    post-reset snapshot diffs silently lose names).  The monotone
    C-side counts are re-baselined (the C plane is never written)."""
    with _lock:
        for k in _counters:
            _counters[k] = 0
    for n, v in _native_counts().items():
        _native_base[n] = v


def reset_one(name: str) -> None:
    """Zero a single counter (MPI_T pvar_reset on one handle); the key
    stays registered — index/name stability across resets."""
    with _lock:
        if name in _counters:
            _counters[name] = 0
    if name in _NATIVE_SLOTS:
        nc = _native_counts()
        if nc:
            _native_base[name] = nc[name]


def clear() -> None:
    """Drop all counter STATE including keys (tests only — never a
    pvar-reset path)."""
    with _lock:
        _counters.clear()
