"""faultsim — deterministic fault injection for the DCN transports.

The reference validates its fault-tolerance story (ULFM,
``--with-ft=ulfm``, SURVEY.md §5) by externally killing ranks; the
transport failure paths themselves — a peer socket dying mid-frame, a
CTS that never comes, a wedged shared-memory ring — are only ever
exercised by real production incidents.  This subsystem makes those
paths testable in CI: a seeded, MCA-gated plan of scripted faults
(drop / delay / duplicate / truncate frames, kill connections, stall
or fail native ring writes, fail dials) that both DCN transports
consult at their choke points.

Contract (the trace/metrics discipline):

* **default off, zero hot-path cost** — every hook is one module-bool
  test (``core._enabled``); a run without ``--mca faultsim_enable 1``
  never constructs a plan, draws a random number, or takes a lock;
* **deterministic by seed** — every decision is a pure function of
  ``(seed, proc, site, event-index, rule)`` via a splitmix64-style
  hash (no RNG stream, no ``PYTHONHASHSEED`` sensitivity), so the
  same seed over the same workload injects the same faults, run after
  run, rank after rank — the reproducibility the chaos soak asserts;
* **observable** — every Python-plane injection bumps
  ``faultsim_injected_<kind>`` (MPI_T pvars + the metrics snapshot)
  and flight-records the transport counter state at the moment of
  injection; C-plane ring injections (``stall``/``ringfail``, armed
  via ``tdcn_fault_set``) count in the merged ``dcn_injected_faults``
  aggregate instead — ring writes never cross back into Python.

Plan grammar (``--mca faultsim_plan``)::

    plan  := rule ("," rule)*
    rule  := kind (":" arg (";" arg)*)?
    arg   := key "=" value

e.g. ``drop:p=0.01,delay:ms=50,connkill:at=100,stall:ms=200`` — see
:data:`core.KINDS` for the kind catalog and :class:`core.Rule` for
the per-kind argument semantics.
"""

from .core import (  # noqa: F401
    KINDS,
    FaultPlanError,
    actions,
    check_dial,
    configure,
    counters,
    disable,
    enabled,
    injected,
    native_dup_args,
    native_ring_args,
    parse_plan,
    reset,
    sync_from_store,
)
from . import core  # noqa: F401
