"""Fault-injection core: plan parsing, deterministic decisions,
injection counters.

The decision function is counter-hashed, not stream-random: event
``k`` at site ``s`` under rule ``r`` hits iff
``u01(seed, proc, s, k, r) < p`` (or the rule's ``at``/``every``/``n``
match ``k`` exactly).  Two consequences the chaos tests rely on:

* thread interleaving cannot perturb outcomes — a site's events are
  numbered under a lock, and each event's decision depends only on
  its own number;
* replaying the same workload with the same seed replays the same
  faults (the property ``tools/chaos.py`` verifies end-to-end).

Control frames (heartbeats, failure gossip) are exempt at the hook
sites: injecting into the detector's own traffic would make failure
*detection* nondeterministic and the injected-fault counts
timing-dependent.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from dataclasses import dataclass

#: the in-path gate — transport hooks read this attribute directly
#: (one boolean test per hook when disabled)
_enabled = False

#: fault kinds, in the stable order the MPI_T pvar namespace uses
#: (``faultsim_injected_<kind>``).  Semantics:
#:
#: ``drop``      lose an outbound message (site send) or inbound eager
#:               frame (site recv) — recovery is the receiver's
#:               deadline escalation, exactly like real frame loss;
#: ``delay``     sleep ``ms`` before the frame moves (latency spike);
#: ``dup``       send the message twice (at-least-once wire duplicate);
#: ``trunc``     send a partial frame then kill the connection (peer
#:               crash mid-frame — exercises the receiver's framing
#:               error + abandon path and the sender's reconnect);
#: ``connkill``  close the cached peer socket before the send (link
#:               death — exercises reconnect/backoff + resend);
#: ``stall``     inject ``ms`` of backpressure per native ring write
#:               (site ring; armed into libtpudcn via tdcn_fault_set);
#: ``ringfail``  fail the ``at``-th native ring write outright;
#: ``dialfail``  refuse the first ``n`` connect() attempts (site dial
#:               — exercises the exponential-backoff dial loop);
#: ``daemonkill`` SIGKILL the tpud serving daemon at the ``at``-th
#:               directive-publish attempt (site daemon — the control-
#:               plane hook in serve/daemon.py; drives the restart-
#:               hygiene soak deterministically from one seed);
#: ``agentkill`` SIGKILL a per-host launch agent at the ``at``-th
#:               command it executes (site agent — the hook in
#:               serve/agent.py; the multi-host chaos harness's
#:               deterministic agent-death lever).
#:
#: Device-plane sites (rules opt in with ``site=device`` /
#: ``site=device_recv`` — the hooks in dcn/device.py): at ``device``
#: (the window stage path) ``drop`` aborts the stage as a simulated
#: DMA failure (the send degrades to the host plane and strikes the
#: plane-health table), ``trunc`` publishes a short DMA length the
#: receiver detects and escalates, ``delay``/``stall`` sleep ``ms``
#: before the RTS publish; at ``device_recv`` (materialize)
#: ``delay``/``stall`` sleep before the semaphore wait, driving the
#: receiver's Deadline toward expiry.
#:
#: The tuple is grow-only: the ``faultsim_injected_<kind>`` MPI_T pvar
#: namespace is derived from it in order.
KINDS = ("drop", "delay", "dup", "trunc", "connkill", "stall",
         "ringfail", "dialfail", "daemonkill", "agentkill")

#: default hook site per kind (rules may override with ``site=``)
_DEFAULT_SITE = {
    "drop": "send", "delay": "send", "dup": "send", "trunc": "send",
    "connkill": "send", "stall": "ring", "ringfail": "ring",
    "dialfail": "dial", "daemonkill": "daemon", "agentkill": "agent",
}

_M64 = (1 << 64) - 1


class FaultPlanError(ValueError):
    """Malformed ``faultsim_plan`` text."""


@dataclass(frozen=True)
class Rule:
    """One parsed plan rule.  Exactly one trigger applies, checked in
    this order: ``at`` (1-based event index, one-shot), ``n`` (every
    event ≤ n — dialfail's "first n attempts"), ``every`` (periodic),
    ``p`` (hashed probability), else unconditional.  ``proc`` (when
    set) restricts the rule to that rank — the straggler-attribution
    tests use it to slow exactly one rank deterministically."""

    kind: str
    site: str
    p: float = 0.0
    at: int | None = None
    every: int | None = None
    n: int | None = None
    ms: float = 0.0
    proc: int | None = None

    def hits(self, seed: int, proc: int, k: int, idx: int) -> bool:
        if self.at is not None:
            return k == self.at
        if self.n is not None:
            return k <= self.n
        if self.every is not None:
            return self.every > 0 and k % self.every == 0
        if self.p:
            return _u01(seed, proc, self.site, k, idx) < self.p
        return True


def _mix(*parts) -> int:
    """splitmix64-style finalizer over FNV-folded inputs — stable
    across processes and Python versions (unlike ``hash``)."""
    x = 0xCBF29CE484222325
    for p in parts:
        if isinstance(p, str):
            p = zlib.crc32(p.encode())
        x = ((x ^ (int(p) & _M64)) * 0x100000001B3) & _M64
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def _u01(*parts) -> float:
    return _mix(*parts) / 2.0**64


def parse_plan(text: str) -> tuple[Rule, ...]:
    """Parse the plan grammar (see the package docstring)."""
    rules: list[Rule] = []
    for chunk in (text or "").split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        kind, _, argtext = chunk.partition(":")
        kind = kind.strip()
        if kind not in KINDS:
            raise FaultPlanError(
                f"unknown fault kind {kind!r} (known: {', '.join(KINDS)})")
        kw: dict = {"kind": kind, "site": _DEFAULT_SITE[kind]}
        for arg in argtext.split(";"):
            arg = arg.strip()
            if not arg:
                continue
            key, eq, val = arg.partition("=")
            key = key.strip()
            if not eq:
                raise FaultPlanError(f"malformed arg {arg!r} in {chunk!r}")
            try:
                if key == "p":
                    kw["p"] = float(val)
                elif key in ("at", "every", "n", "proc"):
                    kw[key] = int(val)
                elif key == "ms":
                    kw["ms"] = float(val)
                elif key == "site":
                    kw["site"] = val.strip()
                else:
                    raise FaultPlanError(
                        f"unknown arg {key!r} in {chunk!r}")
            except ValueError as e:
                if isinstance(e, FaultPlanError):
                    raise
                raise FaultPlanError(
                    f"bad value {val!r} for {key!r} in {chunk!r}") from e
        rules.append(Rule(**kw))
    return tuple(rules)


class FaultPlan:
    """Seeded plan instance for one process: site-indexed rules plus
    the per-site event counters the decisions key on."""

    def __init__(self, rules: tuple[Rule, ...], seed: int, proc: int):
        self.rules = rules
        self.seed = int(seed)
        self.proc = int(proc)
        self._by_site: dict[str, list[tuple[int, Rule]]] = {}
        for idx, r in enumerate(rules):
            self._by_site.setdefault(r.site, []).append((idx, r))
        self._events: dict[str, int] = {}
        self.injected: dict[str, int] = {k: 0 for k in KINDS}
        self._lock = threading.Lock()

    def decide(self, site: str,
               kinds: frozenset | set | None = None) -> tuple[Rule, ...]:
        """Number this site event and return the rules that fire on it
        (usually empty).  ``kinds`` names the fault kinds the calling
        hook can actually PERFORM on this event (e.g. the recv loop
        can only drop eager frames): unsupported rules are excluded
        before evaluation, so the injected counters record faults that
        happened, never phantom hits — and since each rule draws an
        independent hash stream, the filter cannot perturb other
        rules' decisions.  Injection counters update here so every
        consumer of a returned action is already counted."""
        rules = self._by_site.get(site)
        if not rules:
            return ()
        with self._lock:
            k = self._events[site] = self._events.get(site, 0) + 1
        out = []
        for idx, r in rules:
            if kinds is not None and r.kind not in kinds:
                continue
            if r.proc is not None and r.proc != self.proc:
                continue  # rank-targeted rule: other ranks never fire it
            if r.hits(self.seed, self.proc, k, idx):
                with self._lock:
                    self.injected[r.kind] += 1
                out.append(r)
        if out:
            # flight-record the transport state at the injection point
            # (no-op unless metrics are enabled — the recorder's gate)
            from ompi_tpu.metrics import flight as _flight

            _flight.record("fault_injected", site=site, event=k,
                           kinds=",".join(r.kind for r in out))
        return tuple(out)


_plan: FaultPlan | None = None


def enabled() -> bool:
    return _enabled


def configure(plan_text: str, seed: int = 0, proc: int | None = None) -> None:
    """Arm the fault plane (parses eagerly so a bad plan aborts at
    init, not mid-run)."""
    global _enabled, _plan
    rules = parse_plan(plan_text)
    if proc is None:
        proc = int(os.environ.get("OMPI_TPU_PROC", "0"))
    _plan = FaultPlan(rules, seed, proc)
    _enabled = True
    # contribute the injected total to the shared dcn_* counter schema
    from ompi_tpu.metrics import core as _mcore

    _mcore.register_provider(_plan, _injected_provider)


def _injected_provider() -> dict[str, int]:
    plan = _plan
    if plan is None:
        return {}
    with plan._lock:
        return {"injected_faults": sum(plan.injected.values())}


def disable() -> None:
    global _enabled, _plan
    _enabled = False
    _plan = None
    # the native knobs (ring writer, tcp-send connkill, blocking-recv
    # delay) are process-wide C state armed at engine creation — disarm
    # them too (only if the library is already loaded; never trigger a
    # build from a teardown path)
    try:
        from ompi_tpu.dcn import native as _native

        if _native._lib is not None:
            _native._lib.tdcn_fault_set(0, 1, -1)
            _native._lib.tdcn_fault_set_conn(-1)
            _native._lib.tdcn_fault_set_dup(-1)
            _native._lib.tdcn_fault_set_recv(0, 1)
    except Exception:  # noqa: BLE001 — teardown must not raise
        pass


def reset() -> None:
    """Test hook: drop all state."""
    disable()


def sync_from_store(store) -> None:
    """MCA wiring (``--mca faultsim_enable 1 faultsim_seed N
    faultsim_plan <plan>``) — same register+sync shape as trace and
    metrics; vars are centrally registered by core.var."""
    if not bool(store.get("faultsim_enable", False)):
        disable()
        return
    configure(str(store.get("faultsim_plan", "") or ""),
              seed=int(store.get("faultsim_seed", 0) or 0))


# -- hook-site helpers (callers gate on ``_enabled``) -------------------


def actions(site: str,
            kinds: frozenset | set | None = None) -> tuple[Rule, ...]:
    """The rules firing on this site event (empty when unarmed);
    ``kinds`` restricts to what the caller can perform (see
    :meth:`FaultPlan.decide`)."""
    plan = _plan
    if plan is None:
        return ()
    return plan.decide(site, kinds)


def apply_delay(rule: Rule) -> None:
    if rule.ms > 0:
        time.sleep(rule.ms / 1000.0)


def check_dial(address: str) -> None:
    """Dial-site hook: raise for injected connect failures."""
    for r in actions("dial", kinds={"dialfail", "delay"}):
        if r.kind == "dialfail":
            raise ConnectionRefusedError(
                f"faultsim: injected dial failure to {address}")
        if r.kind == "delay":
            apply_delay(r)


def native_ring_args() -> tuple[int, int, int]:
    """(stall_ns, stall_every, fail_at) for ``tdcn_fault_set`` — how
    the seeded plan reaches the C ring-write path.  The C side keeps
    its own event counter (ring writes never reach Python), so ring
    rules support ``ms``/``every``/``at`` but not ``p`` — and C-plane
    injections count ONLY in the merged ``dcn_injected_faults``
    aggregate (the engine's stats block), not the per-kind
    ``faultsim_injected_stall/ringfail`` counters, which track the
    Python hook sites."""
    stall_ns, every, fail_at = 0, 1, -1
    plan = _plan
    if plan is None:
        return stall_ns, every, fail_at
    for r in plan.rules:
        if r.proc is not None and r.proc != plan.proc:
            continue
        if r.kind == "stall":
            stall_ns = int(r.ms * 1e6)
            if r.every:
                every = r.every
        elif r.kind == "ringfail" and r.at is not None:
            fail_at = r.at
    return stall_ns, every, fail_at


def native_conn_args() -> int:
    """``connkill_at`` for ``tdcn_fault_set_conn`` — how the seeded
    plan reaches the C tcp-send path (the native twin of the Python
    transport's connkill site).  The C side keeps its own send-event
    counter, so only ``at`` rules map; -1 = disarmed.  Like the ring
    knobs, C-plane hits count only in the engine's merged
    ``dcn_injected_faults``, not the per-kind Python counters."""
    plan = _plan
    if plan is None:
        return -1
    for r in plan.rules:
        if r.proc is not None and r.proc != plan.proc:
            continue
        if r.kind == "connkill" and r.at is not None:
            return r.at
    return -1


def native_dup_args() -> int:
    """``dup_at`` for ``tdcn_fault_set_dup`` — the seeded plan's wire-
    duplicate rule on the native plane: the Nth seq-carrying eager tcp
    send is transmitted twice, so the receiver's dedup watermark must
    absorb a true duplicate.  Only ``at`` rules map (the C side keeps
    its own event counter); -1 = disarmed."""
    plan = _plan
    if plan is None:
        return -1
    for r in plan.rules:
        if r.proc is not None and r.proc != plan.proc:
            continue
        if r.kind == "dup" and r.at is not None:
            return r.at
    return -1


def native_recv_args() -> tuple[int, int]:
    """(delay_ns, every) for ``tdcn_fault_set_recv`` — injected latency
    at the C blocking-receive entry (``tdcn_precv``: the native pml
    fast path AND the C-ABI shim's MPI_Recv).  Only periodic
    (``every``) or unconditional ``delay;site=recv`` rules map — the
    C side counts events itself, so ``p=``/``at=`` triggers cannot be
    honored there and are skipped rather than silently widened to
    every receive; the first matching rule wins (no mixing of one
    rule's delay with another's period)."""
    plan = _plan
    if plan is None:
        return 0, 1
    for r in plan.rules:
        if r.proc is not None and r.proc != plan.proc:
            continue
        if (r.kind == "delay" and r.site == "recv" and r.ms > 0
                and r.at is None and not r.p):
            return int(r.ms * 1e6), (r.every or 1)
    return 0, 1


def counters() -> dict[str, int]:
    """Per-kind injected-fault counts (the chaos tally + snapshot
    section + ``faultsim_injected_<kind>`` pvar values)."""
    plan = _plan
    if plan is None:
        return {k: 0 for k in KINDS}
    with plan._lock:
        return dict(plan.injected)


def injected(kind: str | None = None) -> int:
    c = counters()
    return sum(c.values()) if kind is None else c.get(kind, 0)
