"""MPI reduction operations (MPI_Op) and their kernels.

TPU-native re-design of ``ompi/op/`` + ``ompi/mca/op/`` (SURVEY.md §2.2
"op — reduction kernels"; [bin] ``mca_op_avx_component``).  The reference
provides a C-loop kernel per (op × datatype) with an AVX component
selected by CPUID; here each op carries

* a **jax kernel** (elementwise monoid ``f(a, b)``) — XLA fuses it into
  the collective; the MXU/VPU replaces the AVX unit;
* a **numpy kernel** — host/golden-reference path, also what a CPU-only
  install of the reference would execute, so bit-parity is checked
  against it;
* optionally a **direct lax collective** name (``psum``/``pmax``/
  ``pmin``) enabling the fused single-dispatch fast path.

Bit-exactness: ``ordered_reduce`` applies a fixed rank-sequential left
fold ``((r0 ⊕ r1) ⊕ r2) …`` — the order the reference's linear/basic
reduction uses and what ``mca_coll_han_allreduce_reproducible`` pins —
implemented with ``lax.fori_loop`` on device and a python loop on host,
yielding identical fp32 results (IEEE ops are deterministic given order).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ompi_tpu.core.errors import MPIOpError
from ompi_tpu.ddt.datatype import Datatype


def _dtype_kind(d: np.dtype) -> str:
    """numpy kind, with ml_dtypes extension floats (bfloat16 etc., which
    numpy reports as kind 'V') normalized to 'f'. numpy's finfo rejects
    extension dtypes, so probe via ml_dtypes' finfo."""
    d = np.dtype(d)
    if d.kind == "V":
        try:
            import ml_dtypes

            ml_dtypes.finfo(d)
            return "f"
        except (ValueError, TypeError, ImportError):
            return "V"
    return d.kind


@dataclass(frozen=True, eq=False)
class Op:
    """An MPI reduction operation.

    ``eq=False`` keeps object-identity hashing: ops are singletons
    (predefined) or user-created handles (MPI_Op_create), never
    value-compared — and identity hash makes them O(1) dispatch-cache
    keys on the hot path."""

    name: str
    jax_fn: Callable[[Any, Any], Any] | None
    np_fn: Callable[[Any, Any], Any] | None
    commutative: bool = True
    #: name of the fused lax collective for the direct path, if any
    lax_collective: str | None = None
    #: dtype-kind gate per MPI's op/type compatibility table
    kinds: tuple[str, ...] = ("i", "u", "f", "c", "b")
    #: True for MAXLOC/MINLOC — operates on (value, index) pair datatypes
    is_loc: bool = False
    #: identity element factory (dtype -> scalar), for padding/degenerate cases
    identity: Callable[[np.dtype], Any] | None = None

    def allowed_on(self, dt: Datatype) -> bool:
        if self.is_loc:
            # pair types: exactly two leaves, second is the index
            return len(dt.typemap) == 2
        leaf = dt.uniform_leaf
        if leaf is None:
            return False
        return _dtype_kind(leaf) in self.kinds

    def check(self, dt: Datatype) -> None:
        if self.np_fn is None and self.jax_fn is None:
            raise MPIOpError(f"{self.name} is not a reducing op")
        if not self.allowed_on(dt):
            raise MPIOpError(
                f"op {self.name} not defined for datatype {dt.name or dt}"
            )

    def __repr__(self) -> str:  # pragma: no cover
        return f"<MPI_Op {self.name}>"


# Logical-op kernels are polymorphic over numpy/jax arrays, so one
# definition serves both the np_fn and jax_fn slots.
def _land(a, b):
    return ((a != 0) & (b != 0)).astype(a.dtype)


def _lor(a, b):
    return ((a != 0) | (b != 0)).astype(a.dtype)


def _lxor(a, b):
    return ((a != 0) ^ (b != 0)).astype(a.dtype)


SUM = Op(
    "MPI_SUM",
    jax_fn=lambda a, b: a + b,
    np_fn=lambda a, b: a + b,
    lax_collective="psum",
    kinds=("i", "u", "f", "c"),
    identity=lambda dt: np.zeros((), dt),
)
PROD = Op(
    "MPI_PROD",
    jax_fn=lambda a, b: a * b,
    np_fn=lambda a, b: a * b,
    kinds=("i", "u", "f", "c"),
    identity=lambda dt: np.ones((), dt),
)
MAX = Op(
    "MPI_MAX",
    jax_fn=jnp.maximum,
    np_fn=np.maximum,
    lax_collective="pmax",
    kinds=("i", "u", "f"),
    identity=lambda dt: (
        np.array(-np.inf, dt) if _dtype_kind(dt) == "f" else np.iinfo(dt).min
    ),
)
MIN = Op(
    "MPI_MIN",
    jax_fn=jnp.minimum,
    np_fn=np.minimum,
    lax_collective="pmin",
    kinds=("i", "u", "f"),
    identity=lambda dt: (
        np.array(np.inf, dt) if _dtype_kind(dt) == "f" else np.iinfo(dt).max
    ),
)
LAND = Op("MPI_LAND", jax_fn=_land, np_fn=_land, kinds=("i", "u", "b"))
LOR = Op("MPI_LOR", jax_fn=_lor, np_fn=_lor, kinds=("i", "u", "b"))
LXOR = Op("MPI_LXOR", jax_fn=_lxor, np_fn=_lxor, kinds=("i", "u", "b"))
BAND = Op("MPI_BAND", jax_fn=lambda a, b: a & b, np_fn=np.bitwise_and, kinds=("i", "u", "b"))
BOR = Op("MPI_BOR", jax_fn=lambda a, b: a | b, np_fn=np.bitwise_or, kinds=("i", "u", "b"))
BXOR = Op("MPI_BXOR", jax_fn=lambda a, b: a ^ b, np_fn=np.bitwise_xor, kinds=("i", "u", "b"))

# MAXLOC/MINLOC: value+index pairs; MPI tie-break = lower index wins.
def _maxloc_np(a, b):
    val_a, idx_a = a
    val_b, idx_b = b
    take_a = (val_a > val_b) | ((val_a == val_b) & (idx_a <= idx_b))
    return np.where(take_a, val_a, val_b), np.where(take_a, idx_a, idx_b)


def _minloc_np(a, b):
    val_a, idx_a = a
    val_b, idx_b = b
    take_a = (val_a < val_b) | ((val_a == val_b) & (idx_a <= idx_b))
    return np.where(take_a, val_a, val_b), np.where(take_a, idx_a, idx_b)


def _maxloc_jax(a, b):
    val_a, idx_a = a
    val_b, idx_b = b
    take_a = (val_a > val_b) | ((val_a == val_b) & (idx_a <= idx_b))
    return jnp.where(take_a, val_a, val_b), jnp.where(take_a, idx_a, idx_b)


def _minloc_jax(a, b):
    val_a, idx_a = a
    val_b, idx_b = b
    take_a = (val_a < val_b) | ((val_a == val_b) & (idx_a <= idx_b))
    return jnp.where(take_a, val_a, val_b), jnp.where(take_a, idx_a, idx_b)


MAXLOC = Op("MPI_MAXLOC", jax_fn=_maxloc_jax, np_fn=_maxloc_np, is_loc=True)
MINLOC = Op("MPI_MINLOC", jax_fn=_minloc_jax, np_fn=_minloc_np, is_loc=True)

#: RMA accumulate ops (no reduction semantics of their own)
REPLACE = Op("MPI_REPLACE", jax_fn=lambda a, b: b, np_fn=lambda a, b: b)
NO_OP = Op("MPI_NO_OP", jax_fn=lambda a, b: a, np_fn=lambda a, b: a)

PREDEFINED_OPS = {
    op.name: op
    for op in [SUM, PROD, MAX, MIN, LAND, LOR, LXOR, BAND, BOR, BXOR, MAXLOC, MINLOC, REPLACE, NO_OP]
}


def create_op(fn: Callable[[Any, Any], Any], commute: bool = True, name: str = "user_op") -> Op:
    """MPI_Op_create: user-defined reduction.

    ``fn(a, b) -> c`` must be elementwise over arrays; it is used for
    both host (numpy in) and device (traced jax in) execution, matching
    the single user-function model of the reference (the user function
    there receives raw buffers; here it receives arrays).
    """
    return Op(name, jax_fn=fn, np_fn=fn, commutative=commute, kinds=("i", "u", "f", "c", "b"))


# -- ordered (bit-exact) reduction kernels -----------------------------


def ordered_reduce_np(stacked: np.ndarray, op: Op) -> np.ndarray:
    """Rank-sequential left fold on host: ((r0 ⊕ r1) ⊕ r2) …

    ``stacked``: (nranks, ...) array. This IS the golden order the
    reference's basic linear reduce applies (ompi/mca/coll/base
    coll_base_reduce.c accumulates rank-by-rank in ascending order for
    the in-order path / MPI_Op application order), so fp32 results here
    define bit-parity.
    """
    acc = stacked[0]
    for r in range(1, stacked.shape[0]):
        acc = op.np_fn(acc, stacked[r])
    return acc


def ordered_reduce_jax(stacked, op: Op):
    """Same fold under jit: lax.fori_loop keeps the order data-independent
    and identical to the host fold (IEEE determinism given fixed order)."""
    n = stacked.shape[0]

    def body(i, acc):
        return op.jax_fn(acc, stacked[i])

    return jax.lax.fori_loop(1, n, body, stacked[0])


def pairwise_tree_reduce_jax(stacked, op: Op):
    """Fixed-shape binary-tree fold — the deterministic *fast* order for
    non-commutative-sensitive cases that don't need CPU parity (fewer
    serial steps than the left fold: log2(n) depth)."""
    n = stacked.shape[0]
    while n > 1:
        half = n // 2
        a = stacked[: half * 2 : 2]
        b = stacked[1 : half * 2 : 2]
        merged = op.jax_fn(a, b)
        if n % 2:
            merged = jnp.concatenate([merged, stacked[n - 1 : n]], axis=0)
        stacked = merged
        n = merged.shape[0]
    return stacked[0]
