"""Reduction op layer (≈ ompi/op + ompi/mca/op, SURVEY.md §2.2)."""

from .op import (  # noqa: F401
    BAND,
    BOR,
    BXOR,
    LAND,
    LOR,
    LXOR,
    MAX,
    MAXLOC,
    MIN,
    MINLOC,
    NO_OP,
    PREDEFINED_OPS,
    PROD,
    REPLACE,
    SUM,
    Op,
    create_op,
    ordered_reduce_jax,
    ordered_reduce_np,
    pairwise_tree_reduce_jax,
)
