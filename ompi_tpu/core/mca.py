"""Process-global default MCA context.

The reference keeps MCA state in process globals initialized by
``opal_init`` (SURVEY.md §3.2).  Here the default context is created
lazily and can be replaced by :func:`init` (called from
``ompi_tpu.init`` with ``--mca`` params) — replacement is only allowed
before components hand out live modules, enforced by the caller.
"""

from __future__ import annotations

from .registry import MCAContext, load_external_components

_default: MCAContext | None = None

#: modules whose import registers the in-tree components (≈ the
#: component .so files mca_base scans at startup)
_BUILTIN_COMPONENT_MODULES = (
    "ompi_tpu.mesh.mesh",
    "ompi_tpu.coll",
    "ompi_tpu.p2p.component",
    "ompi_tpu.dcn.component",
    "ompi_tpu.osc.component",
    "ompi_tpu.io.component",
    "ompi_tpu.tool.monitoring",
    "ompi_tpu.ft.detector",
    "ompi_tpu.p2p.vprotocol",
)


def _load_builtin_components() -> None:
    import importlib

    for mod in _BUILTIN_COMPONENT_MODULES:
        importlib.import_module(mod)


def default_context() -> MCAContext:
    global _default
    if _default is None:
        _load_builtin_components()
        load_external_components()
        _default = MCAContext()
    _default.refresh_components()
    return _default


def init(cmdline: dict[str, str] | None = None) -> MCAContext:
    """(Re)create the default context with command-line ``--mca`` params."""
    global _default
    _load_builtin_components()
    load_external_components()
    _default = MCAContext(cmdline=cmdline)
    return _default


def reset() -> None:
    """Drop the default context (tests only)."""
    global _default
    if _default is not None:
        _default.close_all()
    _default = None
