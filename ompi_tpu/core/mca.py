"""Process-global default MCA context.

The reference keeps MCA state in process globals initialized by
``opal_init`` (SURVEY.md §3.2).  Here the default context is created
lazily and can be replaced by :func:`init` (called from
``ompi_tpu.init`` with ``--mca`` params) — replacement is only allowed
before components hand out live modules, enforced by the caller.
"""

from __future__ import annotations

from .registry import MCAContext, load_external_components

_default: MCAContext | None = None


def default_context() -> MCAContext:
    global _default
    if _default is None:
        load_external_components()
        _default = MCAContext()
    return _default


def init(cmdline: dict[str, str] | None = None) -> MCAContext:
    """(Re)create the default context with command-line ``--mca`` params."""
    global _default
    load_external_components()
    _default = MCAContext(cmdline=cmdline)
    return _default


def reset() -> None:
    """Drop the default context (tests only)."""
    global _default
    if _default is not None:
        _default.close_all()
    _default = None
