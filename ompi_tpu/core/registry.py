"""MCA component architecture — frameworks, components, selection.

TPU-native re-design of ``opal/mca/base/mca_base_component_find.c`` /
``mca_base_components_open.c`` / ``mca_base_components_select.c`` and the
framework system (``mca_base_framework_open/register/close`` [bin]; see
SURVEY.md §1).  Preserved semantics:

* every behavioral unit is a **component** inside a **framework**
  (``coll/xla``, ``coll/basic``, ``accelerator/tpu`` …);
* the framework-level selection var (named exactly like the framework,
  e.g. ``--mca coll xla,basic``) is an include list; a leading ``^``
  (``--mca coll ^xla``) makes it an exclude list; mixing forms is an
  error (matching mca_base_component_parse_requested);
* each component registers a ``<fw>_<comp>_priority`` int var; selection
  queries components and orders by priority (desc);
* frameworks either pick ONE winner (pml-style, ``select_one``) or stack
  many (coll-style, ``selectable``), the per-communicator stacking itself
  living in ``ompi_tpu.coll.select``.

Components register in-process via decorators instead of dlopen'd ``.so``
plugins — the dynamic-loading half of MCA is replaced by Python import —
but out-of-tree components still work: any module that defines a Component
subclass and calls ``register_component`` participates identically
(``OMPI_TPU_COMPONENT_MODULES`` env lists extra modules to import).
"""

from __future__ import annotations

import importlib
import os
from typing import Any, Callable, Iterable, Type

from .var import (
    VarStore,
    full_var_name,
    register_device_vars,
    register_observability_vars,
    register_robustness_vars,
    register_schedule_vars,
    register_serving_vars,
    register_transport_vars,
)


class ComponentError(Exception):
    pass


class SelectionError(ComponentError):
    """Raised when an include-list names no usable component
    (≈ the reference's "none of the requested components could be
    selected" show_help abort)."""


class Component:
    """Base class for all MCA components.

    Subclasses set ``FRAMEWORK`` and ``NAME`` and usually override
    ``register_params`` / ``open`` / ``query``.
    """

    FRAMEWORK: str = ""
    NAME: str = ""
    #: Default selection priority; overridable via <fw>_<comp>_priority.
    PRIORITY: int = 0
    #: Version triple, surfaced by info dumps (≈ MCA_BASE_VERSION).
    VERSION = (1, 0, 0)

    def __init__(self) -> None:
        self.priority: int = self.PRIORITY
        self.opened: bool = False

    # -- lifecycle (mirrors mca_base_component open/close/register) ----

    def register_params(self, store: VarStore) -> None:
        """Register this component's MCA vars. Called before open().
        Always registers the common ``priority`` var."""
        var = store.register(
            self.FRAMEWORK,
            self.NAME,
            "priority",
            self.PRIORITY,
            type="int",
            help=f"Selection priority of the {self.FRAMEWORK}/{self.NAME} component",
        )
        self.priority = var.value

    def open(self, store: VarStore) -> bool:
        """Return True if the component is usable in this process
        (hardware present, deps importable …). False → silently skipped,
        like a component whose open() returns OMPI_ERR_NOT_AVAILABLE."""
        return True

    def close(self) -> None:
        pass

    def __repr__(self) -> str:  # pragma: no cover
        return f"<{self.FRAMEWORK}/{self.NAME} prio={self.priority}>"


def parse_selection(value: str | None) -> tuple[bool, list[str]]:
    """Parse a framework selection string.

    Returns (is_exclude, names). ``None``/empty → include-all
    (``(True, [])``: exclude nothing).
    Raises ComponentError on mixed ``^`` usage, matching the reference's
    refusal of e.g. ``--mca coll tuned,^sm``.
    """
    if not value:
        return True, []
    value = value.strip()
    exclude = value.startswith("^")
    if exclude:
        value = value[1:]
    names = [n.strip() for n in value.split(",") if n.strip()]
    for n in names:
        if n.startswith("^"):
            raise ComponentError(
                f"selection list may not mix include and exclude: {value!r}"
            )
    return exclude, names


class Framework:
    """One MCA framework: a named slot holding competing components."""

    def __init__(self, name: str, store: VarStore, description: str = ""):
        self.name = name
        self.description = description
        self.store = store
        self._component_classes: dict[str, Type[Component]] = {}
        self.components: dict[str, Component] = {}  # opened, post-selection
        self._opened = False

    def add_component_class(self, cls: Type[Component]) -> None:
        if cls.FRAMEWORK != self.name:
            raise ComponentError(
                f"component {cls.NAME} declares framework {cls.FRAMEWORK!r}, "
                f"registered into {self.name!r}"
            )
        self._component_classes[cls.NAME] = cls

    @property
    def known_component_names(self) -> list[str]:
        return sorted(self._component_classes)

    def open(self) -> None:
        """Apply the selection var, register params, open survivors.

        ≈ mca_base_framework_open: filter by include/exclude list, then
        component register + open, dropping unusable ones.
        """
        if self._opened:
            return
        self._opened = True
        raw = self.store.lookup_unregistered(self.name)
        # Register the selection var itself so it shows up in info dumps.
        self.store.register(
            self.name,
            "",
            "",
            raw if raw is not None else "",
            type="string",
            help=f"Component selection list for the {self.name} framework "
            f'("a,b" include list, "^a,b" exclude list)',
        )
        # every framework gets its verbose-stream var (mca_base_framework
        # _open registers <fw>_base_verbose the same way)
        from ompi_tpu.core import output as _output

        _output.register_verbose_var(self.store, self.name)
        exclude, names = parse_selection(raw)
        requested: list[str] = []
        for comp_name, cls in sorted(self._component_classes.items()):
            if exclude:
                if comp_name in names:
                    continue
            else:
                if comp_name not in names:
                    continue
            requested.append(comp_name)
        if not exclude and not requested and names:
            raise SelectionError(
                f"--mca {self.name} {','.join(names)}: no such component "
                f"(known: {', '.join(self.known_component_names) or 'none'})"
            )
        for comp_name in requested:
            comp = self._component_classes[comp_name]()
            comp.register_params(self.store)
            try:
                usable = comp.open(self.store)
            except Exception:
                usable = False
            if usable:
                comp.opened = True
                self.components[comp_name] = comp
            else:
                comp.close()
        if not exclude and names and not self.components:
            raise SelectionError(
                f"--mca {self.name} {','.join(names)}: requested component(s) "
                f"found but not usable in this process"
            )

    def selectable(self) -> list[Component]:
        """Opened components ordered by priority desc, name asc (the order
        coll-style stacking iterates; deterministic tie-break)."""
        self.open()
        return sorted(
            self.components.values(), key=lambda c: (-c.priority, c.NAME)
        )

    def select_one(self) -> Component:
        """pml-style exclusive selection: highest priority wins."""
        mods = self.selectable()
        if not mods:
            raise SelectionError(
                f"no usable component in framework {self.name!r}"
            )
        return mods[0]

    def close(self) -> None:
        for comp in self.components.values():
            comp.close()
        self.components.clear()
        self._opened = False


class MCAContext:
    """Top-level MCA state: the var store plus all frameworks.

    ≈ the process-global set of ``mca_base_framework_t`` singletons. A
    default context is created at import; ``ompi_tpu.init`` re-creates it
    with cmdline params; tests build private contexts.
    """

    def __init__(
        self,
        cmdline: dict[str, str] | None = None,
        env: dict[str, str] | None = None,
        param_files: Iterable[str] | None = None,
    ):
        self.store = VarStore(cmdline=cmdline, env=env, param_files=param_files)
        # trace/metrics knobs register on EVERY store at construction so
        # --mca-var listings (ompi_tpu.info, MPI_T cvars) show them even
        # when the lazy trace/metrics subsystems were never imported;
        # the dcn deadline + faultsim knobs follow the same rule
        register_observability_vars(self.store)
        register_robustness_vars(self.store)
        register_schedule_vars(self.store)
        register_serving_vars(self.store)
        register_transport_vars(self.store)
        register_device_vars(self.store)
        self.frameworks: dict[str, Framework] = {}
        self._register_builtin_components()

    # Global class-level record of all known component classes, populated
    # by the @register_component decorator at import time.
    _global_component_classes: list[Type[Component]] = []

    def framework(self, name: str, description: str = "") -> Framework:
        fw = self.frameworks.get(name)
        if fw is None:
            fw = Framework(name, self.store, description)
            self.frameworks[name] = fw
        return fw

    def _register_builtin_components(self) -> None:
        for cls in MCAContext._global_component_classes:
            self.framework(cls.FRAMEWORK).add_component_class(cls)

    def refresh_components(self) -> None:
        """Pick up component classes registered after this context was
        built (import-order independence)."""
        for cls in MCAContext._global_component_classes:
            fw = self.framework(cls.FRAMEWORK)
            if cls.NAME not in fw._component_classes:
                fw.add_component_class(cls)

    def open_all(self) -> None:
        self.refresh_components()
        for fw in self.frameworks.values():
            fw.open()

    def close_all(self) -> None:
        for fw in self.frameworks.values():
            fw.close()


def register_component(cls: Type[Component]) -> Type[Component]:
    """Class decorator: make a Component class known to every context.

    ≈ the ``mca_<fw>_<comp>_component`` exported symbol that dlopen finds.
    """
    if not cls.FRAMEWORK or not cls.NAME:
        raise ComponentError(f"{cls.__name__} must set FRAMEWORK and NAME")
    existing = [
        c
        for c in MCAContext._global_component_classes
        if c.FRAMEWORK == cls.FRAMEWORK and c.NAME == cls.NAME
    ]
    for c in existing:
        MCAContext._global_component_classes.remove(c)
    MCAContext._global_component_classes.append(cls)
    return cls


def load_external_components() -> None:
    """Import extra component modules named in OMPI_TPU_COMPONENT_MODULES
    (colon-separated) — the dlopen path for out-of-tree components."""
    mods = os.environ.get("OMPI_TPU_COMPONENT_MODULES", "")
    for mod in mods.split(":"):
        mod = mod.strip()
        if mod:
            importlib.import_module(mod)
