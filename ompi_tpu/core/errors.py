"""MPI error classes and codes.

Mirrors the error-class surface of the reference's ``ompi/errhandler/`` and
the ``MPI_ERR_*`` constants from ``ompi/include/mpi.h.in`` [src]. The
reference attaches an errhandler to every communicator/window/file object
(MPI_ERRORS_ARE_FATAL default); here the Python-native design raises typed
exceptions and lets per-communicator errhandlers translate them
(``Comm.set_errhandler``).
"""

from __future__ import annotations

# MPI error classes (values match the MPI standard / mpi.h ordering so a
# future C shim can pass them through unchanged).
MPI_SUCCESS = 0
MPI_ERR_BUFFER = 1
MPI_ERR_COUNT = 2
MPI_ERR_TYPE = 3
MPI_ERR_TAG = 4
MPI_ERR_COMM = 5
MPI_ERR_RANK = 6
MPI_ERR_REQUEST = 7
MPI_ERR_ROOT = 8
MPI_ERR_GROUP = 9
MPI_ERR_OP = 10
MPI_ERR_TOPOLOGY = 11
MPI_ERR_DIMS = 12
MPI_ERR_ARG = 13
MPI_ERR_UNKNOWN = 14
MPI_ERR_TRUNCATE = 15
MPI_ERR_OTHER = 16
MPI_ERR_INTERN = 17
MPI_ERR_IN_STATUS = 18
MPI_ERR_PENDING = 19
MPI_ERR_KEYVAL = 36
MPI_ERR_NO_MEM = 34
# RMA / window error classes (MPI-3 one-sided)
MPI_ERR_WIN = 53
MPI_ERR_ASSERT = 22
MPI_ERR_LOCKTYPE = 37
MPI_ERR_DISP = 26
MPI_ERR_RMA_CONFLICT = 46
MPI_ERR_RMA_SYNC = 47
MPI_ERR_RMA_RANGE = 55
MPI_ERR_RMA_ATTACH = 56
MPI_ERR_RMA_FLAVOR = 58
# ULFM fault-tolerance error classes (MPIX_*, the --with-ft=ulfm ext)
MPIX_ERR_PROC_FAILED = 75
MPIX_ERR_PROC_FAILED_PENDING = 76
MPIX_ERR_REVOKED = 77
# MPI-IO error classes
MPI_ERR_FILE = 30
MPI_ERR_ACCESS = 20
MPI_ERR_AMODE = 21
MPI_ERR_NO_SUCH_FILE = 42
MPI_ERR_FILE_EXISTS = 28
MPI_ERR_FILE_IN_USE = 29
MPI_ERR_READ_ONLY = 45
MPI_ERR_IO = 35


class MPIError(Exception):
    """Base error carrying an MPI error class."""

    error_class = MPI_ERR_OTHER

    def __init__(self, message: str = "", error_class: int | None = None):
        super().__init__(message)
        if error_class is not None:
            self.error_class = error_class


class MPICommError(MPIError):
    error_class = MPI_ERR_COMM


class MPIRankError(MPIError):
    error_class = MPI_ERR_RANK


class MPIRootError(MPIError):
    error_class = MPI_ERR_ROOT


class MPITypeError(MPIError):
    error_class = MPI_ERR_TYPE


class MPICountError(MPIError):
    error_class = MPI_ERR_COUNT


class MPIOpError(MPIError):
    error_class = MPI_ERR_OP


class MPIArgError(MPIError):
    error_class = MPI_ERR_ARG


class MPIRequestError(MPIError):
    error_class = MPI_ERR_REQUEST


class MPITruncateError(MPIError):
    error_class = MPI_ERR_TRUNCATE


class MPIInternalError(MPIError):
    error_class = MPI_ERR_INTERN


class MPIBufferError(MPIError):
    error_class = MPI_ERR_BUFFER


class MPIGroupError(MPIError):
    error_class = MPI_ERR_GROUP


class MPITopologyError(MPIError):
    error_class = MPI_ERR_TOPOLOGY


class MPIDimsError(MPIError):
    error_class = MPI_ERR_DIMS


class MPIKeyvalError(MPIError):
    error_class = MPI_ERR_KEYVAL


class MPIPendingError(MPIError):
    error_class = MPI_ERR_PENDING


class MPIInStatusError(MPIError):
    error_class = MPI_ERR_IN_STATUS


class MPIWinError(MPIError):
    error_class = MPI_ERR_WIN


class MPILockError(MPIError):
    error_class = MPI_ERR_LOCKTYPE


class MPIRMASyncError(MPIError):
    error_class = MPI_ERR_RMA_SYNC


class MPIRMAConflictError(MPIError):
    error_class = MPI_ERR_RMA_CONFLICT


class MPIRMARangeError(MPIError):
    error_class = MPI_ERR_RMA_RANGE


class MPIRMAAttachError(MPIError):
    error_class = MPI_ERR_RMA_ATTACH


class DeadlineExpiredError(MPIError):
    """A blocking DCN wait ran out its registered ``dcn_*_timeout``
    (the unified deadline policy in :mod:`ompi_tpu.core.var`).  An
    internal signal: transport/engine layers catch it and escalate to
    :class:`MPIProcFailedError` + detector notification — it should
    never surface to MPI callers."""

    error_class = MPI_ERR_INTERN


class MPIProcFailedError(MPIError):
    """MPIX_ERR_PROC_FAILED: operation touched a failed process."""

    error_class = MPIX_ERR_PROC_FAILED

    def __init__(self, msg: str, failed: tuple[int, ...] = ()):  # noqa: D401
        super().__init__(msg)
        self.failed = tuple(failed)


class MPIProcFailedPendingError(MPIError):
    """MPIX_ERR_PROC_FAILED_PENDING: a potential matching sender failed
    while an ANY_SOURCE receive was outstanding; the receive cannot be
    satisfied until the failure is acknowledged (MPIX_Comm_ack_failed)."""

    error_class = MPIX_ERR_PROC_FAILED_PENDING

    def __init__(self, msg: str, failed: tuple[int, ...] = ()):
        super().__init__(msg)
        self.failed = tuple(failed)


class MPIRevokedError(MPIError):
    """MPIX_ERR_REVOKED: communicator was revoked."""

    error_class = MPIX_ERR_REVOKED


class MPIFileError(MPIError):
    error_class = MPI_ERR_FILE


class MPIAmodeError(MPIError):
    error_class = MPI_ERR_AMODE


class MPIIOError(MPIError):
    error_class = MPI_ERR_IO


def error_string(error_class: int) -> str:
    """MPI_Error_string equivalent."""
    names = {v: k for k, v in globals().items() if k.startswith("MPI_ERR") or k == "MPI_SUCCESS"}
    return names.get(error_class, f"MPI error class {error_class}")


class Errhandler:
    """An MPI errhandler object (≈ ompi/errhandler/errhandler.h).

    The Python surface raises typed exceptions for every error — the
    idiomatic form of MPI_ERRORS_RETURN — so ERRORS_RETURN is the
    default on Python-created communicators.  ERRORS_ARE_FATAL aborts
    the process (the standard's default, honored by the C ABI where
    conforming programs expect it).  ``fn`` supports
    MPI_Comm_create_errhandler-style user callbacks: called with
    (comm, error_class) before the fatal/return action."""

    __slots__ = ("name", "fatal", "fn")

    def __init__(self, name: str, fatal: bool, fn=None):
        self.name = name
        self.fatal = bool(fatal)
        self.fn = fn

    def __repr__(self) -> str:  # pragma: no cover
        return f"<Errhandler {self.name}>"


ERRORS_ARE_FATAL = Errhandler("MPI_ERRORS_ARE_FATAL", fatal=True)
ERRORS_RETURN = Errhandler("MPI_ERRORS_RETURN", fatal=False)


def create_errhandler(fn) -> Errhandler:
    """MPI_Comm_create_errhandler: wrap a user callback."""
    return Errhandler(getattr(fn, "__name__", "user_errhandler"),
                      fatal=False, fn=fn)
