"""``ompi_tpu_info`` — introspection dump (≈ the reference's ``ompi_info``).

The reference's ``ompi_info`` tool lists every framework, component, and
MCA var with value + source (``ompi_info --all --parsable``). This module
renders the same content from an :class:`MCAContext`; the console entry
point lives in ``ompi_tpu/__main__.py`` (``python -m ompi_tpu info``).
"""

from __future__ import annotations

import io
import sys

from .registry import MCAContext


def render_info(ctx: MCAContext, parsable: bool = False, all_vars: bool = True) -> str:
    ctx.open_all()
    out = io.StringIO()
    if parsable:
        for name, fw in sorted(ctx.frameworks.items()):
            for comp in fw.selectable():
                v = ".".join(str(x) for x in comp.VERSION)
                print(f"mca:{name}:{comp.NAME}:version:{v}", file=out)
        if all_vars:
            for var in ctx.store.all_vars():
                print(
                    f"mca:var:{var.full_name}:value:{var.value}:source:{var.source}",
                    file=out,
                )
        return out.getvalue()

    print("Package: ompi_tpu (TPU-native MPI framework)", file=out)
    import ompi_tpu

    print(f"Version: {ompi_tpu.__version__}", file=out)
    print(file=out)
    print("Frameworks / components:", file=out)
    for name, fw in sorted(ctx.frameworks.items()):
        comps = fw.selectable()
        names = ", ".join(f"{c.NAME} (prio {c.priority})" for c in comps) or "(none usable)"
        print(f"  {name:<14} {names}", file=out)
        if fw.description:
            print(f"  {'':<14} {fw.description}", file=out)
    if all_vars:
        print(file=out)
        print("MCA variables (value [source]):", file=out)
        for var in ctx.store.all_vars():
            src = var.source if not var.source_detail else f"{var.source}:{var.source_detail}"
            enum_note = ""
            if var.enum is not None:
                ename = var.enum_name()
                opts = ",".join(var.enum)
                enum_note = f"  enum{{{opts}}}" + (f" = {ename}" if ename else "")
            print(f"  {var.full_name:<40} = {var.value!r} [{src}]{enum_note}", file=out)
            if var.help:
                print(f"  {'':<40}   {var.help}", file=out)
    return out.getvalue()


def main(argv: list[str] | None = None) -> int:
    import argparse

    from . import mca

    p = argparse.ArgumentParser(prog="ompi_tpu info")
    p.add_argument("--parsable", action="store_true")
    p.add_argument("--no-vars", action="store_true", help="omit the MCA var dump")
    args = p.parse_args(argv)
    sys.stdout.write(render_info(mca.default_context(), args.parsable, not args.no_vars))
    return 0
