"""Spawn-on-demand worker pool — thread reuse for the NBC/RTS paths.

≈ ``opal/mca/threads`` (SURVEY.md §2.3): the reference funnels
asynchronous work through reusable progress threads; round 2 here
spawned one OS thread per i-collective instance and per inbound RTS
grant, which is thousands of pthread creations per second at
training-loop rates (VERDICT r2 weak #6).

The pool preserves the no-deadlock argument that justified
thread-per-instance: a FIXED-width pool can park the task a peer is
blocked on behind busy workers and deadlock a legal MPI program, so
this pool NEVER queues behind a busy worker — ``submit`` hands the
task to an idle worker when one is parked, and spawns a fresh thread
otherwise ("spawn on depth").  Liveness is therefore identical to
thread-per-task; what changes is that workers park for ``idle_ttl``
seconds after finishing and get reused, so steady-state issue rates
reuse a small warm set instead of churning pthreads.
"""

from __future__ import annotations

import queue
import threading


class SpawnPool:
    """Reusable daemon workers with spawn-on-demand overflow."""

    def __init__(self, name: str = "ompi-pool", idle_ttl: float = 10.0):
        self.name = name
        self.idle_ttl = idle_ttl
        self._q: queue.Queue = queue.Queue()
        self._idle = 0
        self._lock = threading.Lock()
        #: total threads ever created (the soak-test meter)
        self.spawned = 0
        #: tasks handed to an already-warm worker
        self.reused = 0

    def submit(self, fn) -> None:
        """Run ``fn()`` on an idle worker if one is parked, else on a
        fresh thread.  Never blocks, never queues behind busy work."""
        with self._lock:
            if self._idle > 0:
                self._idle -= 1  # reserve the parked worker
                self.reused += 1
                self._q.put(fn)
                return
            self.spawned += 1
        threading.Thread(
            target=self._run, args=(fn,), daemon=True, name=self.name
        ).start()

    def _run(self, fn) -> None:
        import traceback

        while True:
            try:
                fn()
            except BaseException:  # noqa: BLE001 — keep the worker
                # alive, but never silently: thread-per-task surfaced
                # stray exceptions via threading.excepthook, so the
                # pool preserves that diagnostic on stderr
                traceback.print_exc()
            with self._lock:
                self._idle += 1
            try:
                fn = self._q.get(timeout=self.idle_ttl)
            except queue.Empty:
                with self._lock:
                    # a submit may have reserved us between the timeout
                    # and this lock: drain once more before retiring
                    try:
                        fn = self._q.get_nowait()
                    except queue.Empty:
                        self._idle -= 1
                        return

    def stats(self) -> dict:
        with self._lock:
            return {
                "spawned": self.spawned,
                "reused": self.reused,
                "idle": self._idle,
            }


#: process-wide pools: one for non-blocking collective instances, one
#: for transport-side grants (separate so a storm of blocked NBC
#: instances cannot starve RTS grants of warm workers)
nbc_pool = SpawnPool("ompi-nbc")
rts_pool = SpawnPool("ompi-rts-grant")
