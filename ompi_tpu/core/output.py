"""Verbose-stream output + show_help — the opal util layer.

≈ ``opal/util/output`` + ``opal/util/show_help`` (SURVEY.md §2.1 "opal
util" row, §5): every framework gets a numbered output stream whose
verbosity is an MCA var (``--mca coll_base_verbose 10``), and operator-
facing diagnostics go through :func:`show_help` — a formatted, DEDUPED
message block (the reference aggregates identical help messages across
ranks; per-process dedup is the single-host analog).

Usage (framework code)::

    from ompi_tpu.core import output
    output.verbose(1, "coll", "comm %s selected module %s", name, mod)

Levels follow the reference's convention: 0 = silent, 1 = component
selection, 10 = per-call tracing, 100 = firehose.
"""

from __future__ import annotations

import sys
import threading

_lock = threading.Lock()
_levels: dict[str, int] = {}
_shown: set[tuple] = set()


def set_verbosity(framework: str, level: int) -> None:
    with _lock:
        _levels[framework] = int(level)


def _level(framework: str) -> int:
    with _lock:
        lvl = _levels.get(framework)
    if lvl is not None:
        return lvl
    # lazily resolve <framework>_base_verbose from the MCA store
    lvl = 0
    try:
        from ompi_tpu.core import mca

        ctx = mca._default
        if ctx is not None:
            try:
                lvl = int(ctx.store.get(f"{framework}_base_verbose", 0))
            except Exception:  # noqa: BLE001 — unregistered var
                lvl = 0
    except Exception:  # noqa: BLE001 — before mca init
        lvl = 0
    with _lock:
        _levels[framework] = lvl
    return lvl


def register_verbose_var(store, framework: str) -> None:
    """Register ``<framework>_base_verbose`` (frameworks call this from
    a component's register_params, matching mca_base_framework_open's
    automatic verbose var)."""
    store.register(
        framework, "base", "verbose", 0, type="int",
        help=f"Verbosity for the {framework} framework's output stream "
        f"(0 silent, 1 selection, 10 per-call, 100 firehose)",
    )
    with _lock:
        _levels.pop(framework, None)  # re-resolve from the store


def verbose(level: int, framework: str, fmt: str, *args) -> None:
    """opal_output_verbose: emit when the framework's stream is at or
    above ``level``.  Zero-cost when silent (one dict hit)."""
    if _level(framework) < level:
        return
    msg = fmt % args if args else fmt
    sys.stderr.write(f"[ompi_tpu:{framework}] {msg}\n")
    sys.stderr.flush()


def show_help(topic: str, key: str, fmt: str, *args, dedup: bool = True) -> None:
    """opal_show_help: operator-facing diagnostic block, deduped by
    (topic, key) so repeated causes print once (the aggregation role)."""
    if dedup:
        with _lock:
            if (topic, key) in _shown:
                return
            _shown.add((topic, key))
    msg = fmt % args if args else fmt
    bar = "-" * 64
    sys.stderr.write(
        f"{bar}\n[ompi_tpu] {topic}: {key}\n\n{msg}\n{bar}\n"
    )
    sys.stderr.flush()


def reset() -> None:
    """Test hook: clear cached levels and dedup state."""
    with _lock:
        _levels.clear()
        _shown.clear()
