"""Hook framework — callbacks around MPI lifecycle events.

≈ ``ompi/mca/hook`` (SURVEY.md §2.2 hook row): components register
functions fired at the top and bottom of MPI_Init and MPI_Finalize
(the reference's ``mpi_init_top/mpi_init_bottom/mpi_finalize_top/
mpi_finalize_bottom`` hook slots).  Used for tool attach points,
environment validation, and the demo hook the reference ships.

``register(event, fn)`` from anywhere (a component's ``open()``, user
code, a sitecustomize); :func:`fire` is invoked by ``api.init`` /
``api.finalize``.  Hook errors are contained — a broken tool hook must
not take down the job (reference behavior).
"""

from __future__ import annotations

import threading
from typing import Callable

from ompi_tpu.core.errors import MPIArgError

EVENTS = (
    "mpi_init_top",
    "mpi_init_bottom",
    "mpi_finalize_top",
    "mpi_finalize_bottom",
)

_lock = threading.Lock()
_hooks: dict[str, list[Callable]] = {e: [] for e in EVENTS}


def register(event: str, fn: Callable) -> None:
    if event not in _hooks:
        raise MPIArgError(f"unknown hook event {event!r} (know {EVENTS})")
    with _lock:
        _hooks[event].append(fn)


def unregister(event: str, fn: Callable) -> None:
    with _lock:
        try:
            _hooks[event].remove(fn)
        except (KeyError, ValueError):
            pass


def fire(event: str, **kw) -> None:
    with _lock:
        fns = list(_hooks.get(event, ()))
    for fn in fns:
        try:
            fn(**kw)
        except Exception:  # noqa: BLE001 — tool hooks must not kill the job
            import traceback

            traceback.print_exc()


def reset() -> None:
    """Test hook."""
    with _lock:
        for e in EVENTS:
            _hooks[e].clear()
