"""MCA variable system — the single config/flag system for the framework.

TPU-native re-design of the reference's ``opal/mca/base/mca_base_var.c``
(symbols ``mca_base_var_register``, ``mca_base_var_enum_create``,
``mca_base_var_cache_files``, ``mca_base_var_build_env`` [bin]; see
SURVEY.md §5-config).  Semantics preserved exactly:

* every tunable is registered as ``<framework>_<component>_<name>``
  (component/framework may be empty → names collapse, e.g. ``coll`` is the
  framework-level selection var, ``coll_xla_priority`` a component var);
* value resolution precedence (highest wins)::

      cmdline ``--mca k v``  >  env ``OMPI_MCA_k``  >  param files
      (user ``~/.ompi_tpu/mca-params.conf`` then system
      ``$OMPI_TPU_SYSCONF/ompi_tpu-mca-params.conf``)  >  default

* enums constrain string values and map to ints;
* everything is introspectable (``ompi_tpu.info`` ≈ ``ompi_info --all``,
  and the MPI_T cvar surface reads straight from this store).

Unlike the reference (registration mutates global state at component dlopen
time), registration here is idempotent and re-resolution is cheap, so tests
can rebuild stores freely.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

ENV_PREFIXES = ("OMPI_MCA_", "OMPI_TPU_MCA_")

# Where param files are looked up, in precedence order (user before system,
# mirroring mca_base_var_cache_files' $HOME/.openmpi/mca-params.conf then
# $sysconfdir/openmpi-mca-params.conf).
def default_param_files() -> list[str]:
    files = []
    home = os.path.expanduser("~")
    files.append(os.path.join(home, ".ompi_tpu", "mca-params.conf"))
    sysconf = os.environ.get("OMPI_TPU_SYSCONF", "/etc/ompi_tpu")
    files.append(os.path.join(sysconf, "ompi_tpu-mca-params.conf"))
    return files


# Value sources, low to high precedence. Matches mca_base_var_source_t
# ordering in spirit: DEFAULT < FILE < ENV < COMMAND_LINE < SET(API).
SOURCE_DEFAULT = "default"
SOURCE_FILE = "file"
SOURCE_ENV = "env"
SOURCE_CMDLINE = "cmdline"
SOURCE_SET = "api"

_SOURCE_RANK = {
    SOURCE_DEFAULT: 0,
    SOURCE_FILE: 1,
    SOURCE_ENV: 2,
    SOURCE_CMDLINE: 3,
    SOURCE_SET: 4,
}

_TRUE_STRINGS = {"1", "true", "yes", "on", "enabled", "t", "y"}
_FALSE_STRINGS = {"0", "false", "no", "off", "disabled", "f", "n"}


def full_var_name(framework: str, component: str, name: str) -> str:
    """``<framework>_<component>_<name>`` with empty parts elided."""
    parts = [p for p in (framework, component, name) if p]
    return "_".join(parts)


class VarConversionError(ValueError):
    pass


def _convert(raw: Any, typ: str, enum: dict[str, int] | None) -> Any:
    """Convert a raw (usually string) value to the var's type."""
    if typ == "string":
        return str(raw)
    if typ == "bool":
        if isinstance(raw, bool):
            return raw
        s = str(raw).strip().lower()
        if s in _TRUE_STRINGS:
            return True
        if s in _FALSE_STRINGS:
            return False
        raise VarConversionError(f"cannot parse {raw!r} as bool")
    if typ == "int":
        if isinstance(raw, bool):
            return int(raw)
        if isinstance(raw, int):
            return raw
        s = str(raw).strip()
        if enum is not None and s in enum:
            return enum[s]
        try:
            return int(s, 0)  # accepts 0x.., 0o.. like the C strtol path
        except ValueError as e:
            raise VarConversionError(f"cannot parse {raw!r} as int") from e
    if typ == "float":
        try:
            return float(raw)
        except (TypeError, ValueError) as e:
            raise VarConversionError(f"cannot parse {raw!r} as float") from e
    raise VarConversionError(f"unknown var type {typ!r}")


@dataclass
class Var:
    """One registered MCA variable."""

    framework: str
    component: str
    name: str
    default: Any
    type: str = "string"  # string | int | bool | float
    help: str = ""
    enum: dict[str, int] | None = None  # e.g. {"ring": 4, "rdbl": 3}
    read_only: bool = False

    value: Any = field(init=False, default=None)
    source: str = field(init=False, default=SOURCE_DEFAULT)
    source_detail: str = field(init=False, default="")

    @property
    def full_name(self) -> str:
        return full_var_name(self.framework, self.component, self.name)

    def enum_name(self) -> str | None:
        """Reverse-map an int value to its enum name (for info dumps)."""
        if self.enum is None:
            return None
        for k, v in self.enum.items():
            if v == self.value:
                return k
        return None


class VarStore:
    """Registry + resolver for MCA variables.

    One global instance lives on the MCA context (``ompi_tpu.core.mca``);
    tests construct private stores.
    """

    def __init__(
        self,
        cmdline: dict[str, str] | None = None,
        env: dict[str, str] | None = None,
        param_files: Iterable[str] | None = None,
    ):
        self._vars: dict[str, Var] = {}
        #: mutation generation — bumped on any change that can alter a
        #: resolved value.  Fast-path dispatch caches (api/comm) key on
        #: this to stay coherent with --mca/set() reconfiguration
        #: without re-reading vars per call.
        self.version = 0
        self._cmdline = dict(cmdline or {})
        self._env = env  # None → live os.environ
        self._file_values: dict[str, tuple[str, str]] = {}  # name -> (value, path)
        self._files_loaded = False
        self._param_files = list(param_files) if param_files is not None else None
        # Deprecated-name aliases: alias -> canonical (for renamed vars).
        self._aliases: dict[str, str] = {}

    # -- param files ---------------------------------------------------

    def _load_files(self) -> None:
        if self._files_loaded:
            return
        self._files_loaded = True
        files = self._param_files if self._param_files is not None else default_param_files()
        # Later files must NOT override earlier ones (user file wins over
        # system file) — first hit sticks, like mca_base_var_cache_files.
        for path in files:
            try:
                with open(path, "r") as f:
                    lines = f.readlines()
            except OSError:
                continue
            for line in lines:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                if "=" not in line:
                    continue
                k, _, v = line.partition("=")
                k, v = k.strip(), v.strip()
                if k and k not in self._file_values:
                    self._file_values[k] = (v, path)

    # -- registration --------------------------------------------------

    def register(
        self,
        framework: str,
        component: str,
        name: str,
        default: Any,
        type: str | None = None,
        help: str = "",
        enum: dict[str, int] | None = None,
        read_only: bool = False,
        aliases: Iterable[str] = (),
    ) -> Var:
        """Register (or re-fetch) a variable and resolve its value.

        Idempotent: re-registering an existing full name returns the
        existing Var (matching mca_base_var_register's dedup behavior).
        """
        if type is None:
            if isinstance(default, bool):
                type = "bool"
            elif isinstance(default, int):
                type = "int"
            elif isinstance(default, float):
                type = "float"
            else:
                type = "string"
        var = Var(framework, component, name, default, type, help, enum, read_only)
        existing = self._vars.get(var.full_name)
        if existing is not None:
            return existing
        self._vars[var.full_name] = var
        for a in aliases:
            self._aliases[a] = var.full_name
        self._resolve(var)
        self.version += 1
        return var

    # -- resolution ----------------------------------------------------

    def _lookup_raw(self, full_name: str) -> tuple[Any, str, str] | None:
        """Find the highest-precedence raw value for a name.

        Returns (raw_value, source, source_detail) or None.
        """
        names = [full_name] + [a for a, c in self._aliases.items() if c == full_name]
        for n in names:
            if n in self._cmdline:
                return self._cmdline[n], SOURCE_CMDLINE, "--mca"
        env = self._env if self._env is not None else os.environ
        for n in names:
            for prefix in ENV_PREFIXES:
                key = prefix + n
                if key in env:
                    return env[key], SOURCE_ENV, key
        self._load_files()
        for n in names:
            if n in self._file_values:
                v, path = self._file_values[n]
                return v, SOURCE_FILE, path
        return None

    def _resolve(self, var: Var) -> None:
        hit = self._lookup_raw(var.full_name)
        if hit is None:
            var.value = var.default
            var.source = SOURCE_DEFAULT
            var.source_detail = ""
            return
        raw, source, detail = hit
        if var.read_only:
            # Read-only vars ignore external settings (INFORMATION-level
            # vars in the reference); keep the default.
            var.value = var.default
            var.source = SOURCE_DEFAULT
            var.source_detail = ""
            return
        var.value = _convert(raw, var.type, var.enum)
        var.source = source
        var.source_detail = detail

    # -- access --------------------------------------------------------

    def get(self, full_name: str, default: Any = None) -> Any:
        full_name = self._aliases.get(full_name, full_name)
        var = self._vars.get(full_name)
        if var is None:
            return default
        return var.value

    def get_var(self, full_name: str) -> Var | None:
        full_name = self._aliases.get(full_name, full_name)
        return self._vars.get(full_name)

    def lookup_unregistered(self, full_name: str) -> str | None:
        """Peek at the configured raw value for a name that may not be
        registered yet (used by framework selection before components
        register, like mca_base_var_find + the component include lists)."""
        hit = self._lookup_raw(full_name)
        return None if hit is None else str(hit[0])

    def set(self, full_name: str, value: Any, source: str = SOURCE_SET) -> None:
        """API-level override (highest precedence)."""
        full_name = self._aliases.get(full_name, full_name)
        var = self._vars.get(full_name)
        if var is None:
            # Stash as cmdline-equivalent so a later register() sees it.
            self._cmdline[full_name] = str(value)
            self.version += 1
            return
        if var.read_only:
            raise VarConversionError(f"{full_name} is read-only")
        if _SOURCE_RANK[source] >= _SOURCE_RANK[var.source]:
            var.value = _convert(value, var.type, var.enum)
            var.source = source
            var.source_detail = ""
            self.version += 1

    def set_cmdline(self, params: dict[str, str]) -> None:
        """Install ``--mca k v`` pairs and re-resolve affected vars.

        API-level set() values outrank cmdline (SET is the highest
        precedence source) and are therefore left untouched."""
        self._cmdline.update(params)
        self.version += 1
        for k in params:
            canonical = self._aliases.get(k, k)
            var = self._vars.get(canonical)
            if var is not None and _SOURCE_RANK[var.source] <= _SOURCE_RANK[SOURCE_CMDLINE]:
                self._resolve(var)

    def all_vars(self) -> list[Var]:
        return sorted(self._vars.values(), key=lambda v: v.full_name)

    # -- env propagation (≈ mca_base_var_build_env) --------------------

    def build_env(self, only_non_default: bool = True) -> dict[str, str]:
        """Serialize current values to OMPI_MCA_* env vars, so spawned
        child processes (tpurun → workers) inherit the resolved config."""
        out: dict[str, str] = {}
        for var in self._vars.values():
            if only_non_default and var.source == SOURCE_DEFAULT:
                continue
            val = var.value
            if isinstance(val, bool):
                val = "1" if val else "0"
            out[ENV_PREFIXES[0] + var.full_name] = str(val)
        for k, v in self._cmdline.items():
            out.setdefault(ENV_PREFIXES[0] + k, v)
        return out


# -- observability variables (central registration) ---------------------
#
# The trace/metrics knobs are consumed by subsystems that only import
# lazily (ompi_tpu.trace / ompi_tpu.metrics sync at MPI_Init), but the
# vars must appear in every ``--mca``-var listing (``ompi_tpu.info``,
# the MPI_T cvar surface) even before — and without — an init.  They
# are therefore registered HERE, on every store at construction
# (MCAContext.__init__), with the subsystems' register_vars functions
# delegating to this table.  One source of truth for name, default,
# type, and description.

#: (framework, component, name, default, type, help)
OBSERVABILITY_VARS = (
    ("trace", "", "enable", False, "bool",
     "Record cross-layer event spans into the trace ring buffer "
     "(api/coll/p2p/dcn timelines; default off — zero-cost hooks)"),
    ("trace", "", "buffer_events", 65536, "int",
     "Trace ring-buffer capacity in events; the oldest events "
     "are dropped (and counted) once full"),
    ("trace", "", "output", "", "string",
     "Chrome trace-event JSON path written at finalize; a "
     "multi-process job writes <output>.<proc>.json per process "
     "(merge with tools/trace_report.py)"),
    ("trace", "", "causal", False, "bool",
     "Cross-rank causal tracing: stamp a compact versioned context "
     "(comm/op/seq + hop) onto collective frames on all three DCN "
     "planes, record per-collective causal records (schedule "
     "sends/recvs with measured waits + stall-cause deltas), and "
     "feed the critical-path/blame surfaces (/critical, "
     "trace_report.py --critical-path, the finalize causal export).  "
     "Implies trace_enable.  Default off — zero wire bytes, zero "
     "hot-path work"),
    ("metrics", "", "enable", False, "bool",
     "Record transport telemetry (native-plane DCN counters, per-op "
     "size/latency histograms, flight recorder); default off — one "
     "boolean test per Python hook, one relaxed atomic per native "
     "event"),
    ("metrics", "", "output", "", "string",
     "Telemetry export base path: finalize writes <output>.<proc>.prom "
     "(Prometheus text format) and <output>.<proc>.jsonl (snapshots + "
     "flight records; analyze with tools/metrics_report.py); flight "
     "records also append live to <output>.flight.<proc>.jsonl"),
    ("metrics", "", "flight_records", 64, "int",
     "Flight-recorder ring capacity: how many counter snapshots "
     "(timeouts, aborts, watermark crossings) are retained in memory"),
    ("telemetry", "", "enable", False, "bool",
     "Live telemetry plane: every rank streams periodic counter/"
     "straggler frames to an aggregator in tpurun, which serves a "
     "mid-job Prometheus scrape endpoint (/metrics), a JSON state "
     "feed (/json — the tools/top.py input), and a JSONL history "
     "ring (/history); implies the metrics hooks.  Default off — no "
     "socket, no thread, no frames"),
    ("telemetry", "", "port", 0, "int",
     "HTTP port the tpurun aggregator serves scrapes on (0 = pick an "
     "ephemeral port and print the URL at launch)"),
    ("telemetry", "", "interval_ms", 500, "int",
     "Milliseconds between a rank's telemetry frames (each frame is "
     "one counter snapshot + the collectives completed since the "
     "last frame)"),
    ("telemetry", "", "history", 256, "int",
     "Frames retained in the aggregator's /history JSONL ring"),
    ("telemetry", "", "relay", False, "bool",
     "Per-group telemetry relays (the np>=16 fan-in fix): each "
     "detector group's leader rank hosts a batching relay; group "
     "members ship their frames there and the relay forwards one "
     "batched frame per interval to the root aggregator, so the "
     "root's ingest socket sees O(groups) connections instead of "
     "O(P).  Off (default): every rank dials the root directly"),
    ("hang", "", "diag_enable", True, "bool",
     "Hang diagnosis (the mesh doctor): every Deadline-bounded wait "
     "site registers its blocked identity (site, plane, awaited peer, "
     "op key) lazily — only after a wait slice already expired — and "
     "on-demand snapshots feed the cross-rank wait-graph solver "
     "(GET /waitgraph, the tpud deadline hang report, trace_report.py "
     "--hangs).  Default on: registration is cold-path only, so a "
     "healthy run does zero extra work and ships zero extra wire "
     "bytes; off drops even the slice-expiry bookkeeping"),
    ("hang", "", "snapshot_timeout_ms", 2000, "int",
     "Milliseconds the tpud deadline path waits for fresh per-rank "
     "blocked-state snapshots (one telemetry interval usually "
     "suffices) before assembling the pre-revoke hang report from "
     "whatever frames it holds"),
)


def register_observability_vars(store: "VarStore") -> None:
    """Register the trace/metrics knobs on a store (idempotent)."""
    for fw, comp, name, default, typ, help_ in OBSERVABILITY_VARS:
        store.register(fw, comp, name, default, type=typ, help=help_)


# -- robustness variables (central registration, same pattern) -----------
#
# The DCN deadline family and the fault-injection knobs.  Like the
# observability vars, these are consumed by lazily-imported subsystems
# (the transports read timeouts per blocking wait; ompi_tpu.faultsim
# syncs at MPI_Init) but must be introspectable on every store.

#: (framework, component, name, default, type, help)
ROBUSTNESS_VARS = (
    ("dcn", "", "cts_timeout", 600.0, "float",
     "Seconds a rendezvous sender waits for the peer's CTS grant "
     "before escalating the peer as failed (MPIProcFailedError + "
     "detector notification) — was a hard-coded 600 s RuntimeError"),
    ("dcn", "", "ring_timeout", 600.0, "float",
     "Seconds a shared-memory ring write blocks on backpressure "
     "(receiver stalled) before escalating the peer as failed"),
    ("dcn", "", "recv_timeout", 120.0, "float",
     "Seconds a blocking DCN receive waits for the peer's frame "
     "before escalating (peer dead or collective order mismatch); "
     "expiry flight-records the transport counters first"),
    ("dcn", "", "connect_timeout", 30.0, "float",
     "Deadline for (re)dialing a peer, spanning every exponential-"
     "backoff attempt (both planes: the Python transports and the "
     "native C dialer via tdcn_set_connect_timeout); control frames "
     "(heartbeats) always fail fast so in-band detection stays prompt"),
    ("dcn", "", "anysrc_timeout", 0.0, "float",
     "Opt-in (default 0 = unbounded, plain MPI blocking semantics): "
     "seconds an ANY_SOURCE receive blocks before escalating to a "
     "communicator-wide liveness check — a failed member raises "
     "MPIProcFailedPendingError, an all-alive membership re-arms the "
     "wait"),
    ("ft", "", "respawn_timeout", 60.0, "float",
     "Seconds replace() waits for a failed rank's respawned "
     "incarnation to re-publish its endpoint (tpurun --respawn) "
     "before giving up on restoration"),
    ("ft", "", "remote_respawn_timeout", 120.0, "float",
     "The rsh-leg twin of ft_respawn_timeout: the await-respawn "
     "deadline replace() (and a reborn worker's rejoin grace) uses "
     "when the job was launched over the plm/rsh leg (tpurun marks "
     "workers with OMPI_TPU_RSH) — a remote relaunch pays the launch-"
     "agent round-trip on top of the boot, so the local deadline is "
     "too tight"),
    ("ft", "", "group_size", 8, "int",
     "Hierarchical failure-detection group width: ranks partition "
     "into groups of this size (or by host id when the launcher "
     "published OMPI_TPU_HOST_IDS); members heartbeat only their "
     "group's leader + successor, leaders heartbeat each other — "
     "per-process control traffic stays O(group + groups) instead of "
     "O(P).  The same groups shard the boot modex and place the "
     "telemetry relays.  <= 0 collapses to one group (full-mesh "
     "heartbeats, the pre-hierarchical shape)"),
    ("ft", "", "gossip_digest", True, "bool",
     "Piggyback an anti-entropy digest of the versioned failure-"
     "record set on leader<->leader heartbeats: a digest mismatch "
     "triggers one flrsync record exchange, so survivor knowledge "
     "converges in O(log groups) periods even when a gossip frame "
     "was lost.  Off: convergence relies on the direct flr flood "
     "alone"),
    ("faultsim", "", "enable", False, "bool",
     "Arm the deterministic fault-injection plane (default off — "
     "every transport hook is one boolean test when disabled)"),
    ("faultsim", "", "seed", 0, "int",
     "Fault-plan seed: decisions are a pure function of (seed, proc, "
     "site, event index), so one seed replays one fault schedule"),
    ("faultsim", "", "plan", "", "string",
     "Fault plan, e.g. 'drop:p=0.01,delay:ms=50,connkill:at=100,"
     "stall:ms=200' — comma-separated <kind>[:k=v[;k=v]] rules "
     "(kinds: drop delay dup trunc connkill stall ringfail dialfail "
     "daemonkill; "
     "'proc=N' restricts a rule to one rank, e.g. "
     "'delay:ms=30;site=recv;proc=1' slows only rank 1; "
     "'site=device'/'site=device_recv' target the device-window "
     "stage / materialize paths for plane-failover drills)"),
    ("dcn", "", "plane_strikes", 3, "int",
     "Consecutive per-(peer, plane) failures (deadline escalation, "
     "injected device fault, failed stage) before the plane-health "
     "table demotes that peer's traffic off the plane — device-window "
     "sends degrade to the host ring/TCP plane while demoted.  One "
     "success resets the strike count (the btl exclude-and-reroute "
     "rule, made per-peer)"),
    ("dcn", "", "plane_heal_interval", 5.0, "float",
     "Seconds after a demotion before the arbitration layer routes "
     "ONE eligible send back through the demoted plane as a heal "
     "probe: a consumed probe window re-promotes the (peer, plane) "
     "pair, a failed one re-arms the interval.  <= 0 disables heal "
     "probes (a demotion then sticks until replace()/respawn clears "
     "the health marks)"),
)


def register_robustness_vars(store: "VarStore") -> None:
    """Register the deadline/faultsim knobs on a store (idempotent)."""
    for fw, comp, name, default, typ, help_ in ROBUSTNESS_VARS:
        store.register(fw, comp, name, default, type=typ, help=help_)


# -- serving variables (central registration, same pattern) --------------
#
# The tpud persistent-serving plane's tenant quotas and daemon knobs.
# Consumed by ompi_tpu.serve (lazily imported by tools/tpud.py and the
# tpurun --daemon path) but introspectable on every store, exactly like
# the observability/robustness sets.

#: (framework, component, name, default, type, help)
SERVING_VARS = (
    ("serve", "", "max_pending", 8, "int",
     "Per-tenant admission quota: a tpud submit is rejected (HTTP 429) "
     "while the tenant already has this many jobs queued or running "
     "(admission control; 0 = unlimited)"),
    ("serve", "", "cid_block", 4096, "int",
     "CID-space block reserved per served job: every job's communicator "
     "world (and any comms it derives) lives in a disjoint "
     "[base, base+block) CID range, so per-(comm, op) sequence counters "
     "start clean without re-dialing anything"),
    ("serve", "", "cid_base", 1 << 20, "int",
     "First CID block handed to a served job (above anything the boot "
     "rendezvous or a normal run allocates)"),
    ("serve", "", "port", 0, "int",
     "HTTP port the tpud ops/scrape endpoint serves on (0 = pick an "
     "ephemeral port and print the URL at daemon start)"),
    ("serve", "", "poll_ms", 50, "int",
     "Milliseconds between a resident worker's polls of the job stream "
     "while idle (the KVS long-poll quantum)"),
    ("serve", "", "tenant", "default", "string",
     "Default tenant name a tpud submit is accounted against when the "
     "client names none"),
    ("serve", "", "job_timeout", 0.0, "float",
     "Seconds the daemon lets one job run before marking it failed and "
     "freeing its rank-set (0 = unbounded)"),
    ("serve", "", "pidfile", "", "string",
     "Path of the tpud pidfile — arms the crash-safe control plane: "
     "the daemon records its pid/KVS/ops addresses there (stale locks "
     "from a SIGKILLed daemon are reaped and taken over), journals "
     "the job stream next to it, and resident workers use it to find "
     "a restarted daemon and re-attach instead of orphaning (empty = "
     "off, the pre-PR-10 one-shot daemon lifecycle)"),
    ("serve", "", "journal", "", "string",
     "Job-stream journal path (append-only JSONL): submissions, "
     "published directives, completions, and worker pids — replayed "
     "by a restarted daemon so queued and in-flight jobs survive a "
     "daemon SIGKILL and execute exactly once (empty = "
     "<serve_pidfile>.journal when a pidfile is configured)"),
    ("serve", "", "agent_poll_ms", 50, "int",
     "Milliseconds between a per-host launch agent's polls of its "
     "command stream while idle (the multi-host DVM leg: tpurun "
     "--daemon with a host map runs one agent per remote host)"),
    ("serve", "", "agent_hb_ms", 500, "int",
     "Milliseconds between a launch agent's heartbeat records "
     "(serve.agent.hb.<hid>: agent pid + per-worker pid/liveness "
     "table — the daemon's remote view of a host it shares no pid "
     "namespace with)"),
    ("serve", "", "agent_timeout", 10.0, "float",
     "Seconds of agent-heartbeat silence (with the agent's launch "
     "process also gone) after which the daemon declares the agent "
     "dead and respawns it over the rsh leg — the reborn agent "
     "re-adopts still-live workers from the last-known pid table and "
     "reports the dead ones for the normal respawn+repair leg"),
    ("serve", "", "journal_max_kb", 0, "int",
     "Journal rotation size bound: once the crash journal grows past "
     "this many KiB the daemon rewrites it in place as one compacted "
     "snapshot line (current replayed state) plus an empty tail, so a "
     "long-lived daemon's journal stops growing without bound "
     "(0 = no size-triggered rotation)"),
    ("serve", "", "journal_max_age_s", 0.0, "float",
     "Journal rotation age bound: rotate (compact-in-place) once the "
     "current journal segment is older than this many seconds, "
     "regardless of size — bounds replay work after a crash even "
     "under a slow event trickle (0 = no age-triggered rotation)"),
    ("serve", "", "agent_hb_only", False, "bool",
     "Judge launch-agent liveness by heartbeat staleness alone, "
     "ignoring rsh-launcher exit: for backgrounding agent templates "
     "(rsh wrappers that daemonize and exit immediately) the launch "
     "process dying is normal, so only serve_agent_timeout seconds "
     "of heartbeat silence declares the agent dead (default off: "
     "either signal — rsh exit or hb silence — triggers respawn)"),
    ("serve", "", "reattach_timeout", 30.0, "float",
     "Crash-safe control plane window, both sides: how long a "
     "resident worker that lost its daemon parks and polls the "
     "pidfile for a restarted one before self-terminating with full "
     "teardown (no orphans), and how long the restarted daemon waits "
     "for a live worker's re-adoption record before treating the "
     "rank as dead and respawning it"),
    ("serve", "", "max_concurrent", 0, "int",
     "Concurrency cap for the gang scheduler: at most this many jobs "
     "run on the mesh at once even when disjoint rank-sets are free "
     "(0 = unlimited — any job whose full rank-set is free launches)"),
    ("serve", "", "admission_stall_ns", 0, "int",
     "Telemetry-driven admission threshold (0 = off): when one daemon "
     "monitor tick's summed ring/cts/DMA stall delta across the mesh "
     "exceeds this many nanoseconds (or the detector reports the mesh "
     "unhealthy), the scheduler queues instead of dispatching; "
     "serve_shed_policy decides what SUSTAINED overload does to new "
     "submits"),
    ("serve", "", "shed_policy", "shed", "string",
     "Graceful-degradation policy under sustained overload (three "
     "consecutive over-threshold admission ticks): 'shed' rejects "
     "submits from tenants that already have work queued or running "
     "with HTTP 429 + a Retry-After hint (an idle tenant still gets "
     "one job in — overload must not lock a tenant out entirely); "
     "'queue' only holds dispatch and keeps admitting"),
    ("serve", "", "job_deadline_s", 0.0, "float",
     "Per-job wall deadline, Deadline-bounded (0 = none): an expired "
     "job gets a revoke directive — its workers revoke the job "
     "communicator ULFM-style, the job fails with a typed "
     "DeadlineExpired error on /job/<id>, and concurrently running "
     "disjoint gangs are untouched — instead of wedging its gang "
     "(serve_job_timeout remains the harder kill-and-repair bound)"),
    ("serve", "", "retry_budget", 0, "int",
     "Automatic re-enqueues for a job killed by mesh repair (a rank "
     "died under it; 0 = none): each retry is journaled as one atomic "
     "record, so a daemon crash mid-retry replays to exactly one "
     "re-run; budget exhaustion fails the job with a typed "
     "RetryBudgetExhausted error on /job/<id>"),
)


def register_serving_vars(store: "VarStore") -> None:
    """Register the tpud serving knobs on a store (idempotent)."""
    for fw, comp, name, default, typ, help_ in SERVING_VARS:
        store.register(fw, comp, name, default, type=typ, help=help_)


# -- transport tuning variables (central registration, same pattern) -----
#
# The native streaming send engine's knobs (the large-message ring
# path: pipelined chunking, per-peer in-flight caps, doorbell
# coalescing).  Consumed by ompi_tpu.dcn.native at engine creation
# (forwarded to the C engine via tdcn_set_stream) but introspectable
# on every store like the other central sets.

#: (framework, component, name, default, type, help)
TRANSPORT_VARS = (
    ("dcn", "", "chunk_bytes", 512 << 10, "int",
     "Streaming-engine FRAG granularity AND streaming threshold on the "
     "shared-memory ring path: payloads above it leave the caller's "
     "thread as a send descriptor and stream cooperatively through the "
     "per-engine sender thread; the adaptive controller shrinks the "
     "effective chunk (floor 64 KiB) under sustained ring backpressure "
     "and grows it back when the stall clears"),
    ("dcn", "", "inflight_limit", 32 << 20, "int",
     "Per-peer cap on queued-unsent streaming bytes: an isend enqueue "
     "over the cap blocks (bounded by dcn_ring_timeout) until the "
     "sender thread drains below it — graceful backpressure instead of "
     "unbounded buffered-send memory growth (0 = unlimited)"),
    ("dcn", "", "doorbell_coalesce", True, "bool",
     "Pay the ring-doorbell futex_wake syscall only when a consumer is "
     "actually parked (the doorbell word is still bumped every record, "
     "so no wakeup is ever lost); suppressed wakes are counted in "
     "doorbells_suppressed.  Off restores the unconditional per-record "
     "wake"),
)


def register_transport_vars(store: "VarStore") -> None:
    """Register the streaming-send-engine knobs on a store
    (idempotent)."""
    for fw, comp, name, default, typ, help_ in TRANSPORT_VARS:
        store.register(fw, comp, name, default, type=typ, help=help_)


# -- device-plane variables (central registration, same pattern) ----------
#
# The third DCN plane: the device-resident zero-copy transport
# (ompi_tpu/dcn/device.py).  Large contiguous payloads stay in device
# memory end-to-end (HBM→HBM DMA windows on TPU; deterministic
# shared-memory window emulation on CPU so tier-1 exercises the
# RTS/CTS↔semaphore protocol and the plane arbitration), while the
# host planes keep carrying control frames and non-contiguous
# datatypes.  Consumed by the DCN engines at creation but
# introspectable on every store like the other central sets.

#: (framework, component, name, default, type, help)
DEVICE_VARS = (
    ("dcn", "device", "enable", True, "bool",
     "Arm the device-resident zero-copy DCN plane: payloads at or "
     "above dcn_device_min_size that are contiguous and device-"
     "stageable move through per-transfer device windows (HBM→HBM "
     "DMA on TPU; shared-memory window emulation on CPU) while the "
     "host plane carries only the RTS/fin control frames.  Off: "
     "every byte keeps the host shm/tcp rings"),
    ("dcn", "device", "min_size", 1 << 20, "int",
     "Smallest payload (bytes) the plane arbitration routes onto the "
     "device plane (the btl-priority/reachability analog: below it "
     "the host ring's lower setup cost wins; at or above it the "
     "zero-copy window wins).  Non-contiguous or object-dtype "
     "payloads stay on the host plane at every size"),
    ("dcn", "device", "interpret", False, "bool",
     "Force the Pallas ring-collective kernels through interpret "
     "mode (CPU-debuggable execution of the same kernel bodies); "
     "default off — real TPU lowering on TPU, the structured "
     "ring-permute emulation elsewhere"),
)


def register_device_vars(store: "VarStore") -> None:
    """Register the device-plane knobs on a store (idempotent)."""
    for fw, comp, name, default, typ, help_ in DEVICE_VARS:
        store.register(fw, comp, name, default, type=typ, help=help_)


# -- compiled-schedule-cache variables (central registration) ------------
#
# The persistent-collective plan store (ompi_tpu/coll/sched.py + the C
# plan cache in native/src/dcn.cc): schedules — algorithm choice, chunk
# plan, compiled program — are built once at *_init and replayed by
# MPI_Start; these knobs bound and gate the store.

#: (framework, component, name, default, type, help)
SCHEDULE_VARS = (
    ("coll", "sched", "cache_enable", True, "bool",
     "Cache compiled persistent-collective schedules (algorithm choice, "
     "chunk plan, compiled program) keyed (comm shape, op, dtype, count, "
     "root) and replay them on MPI_Start with zero per-call planning; "
     "the process-wide store survives across jobs in a resident tpud "
     "worker like the warm mesh.  Off = every lookup plans afresh"),
    ("coll", "sched", "cache_max", 256, "int",
     "Upper bound on cached compiled schedules (FIFO eviction): plans "
     "are cheap to rebuild, unbounded growth in a month-resident worker "
     "is not"),
)


def register_schedule_vars(store: "VarStore") -> None:
    """Register the compiled-schedule-cache knobs on a store
    (idempotent)."""
    for fw, comp, name, default, typ, help_ in SCHEDULE_VARS:
        store.register(fw, comp, name, default, type=typ, help=help_)


def dcn_timeout(name: str) -> float:
    """Resolve one ``dcn_<name>_timeout`` against the default MCA
    context — the single lookup every blocking DCN wait shares.  Falls
    back to the table default when no context exists (bare transports
    in unit tests)."""
    full = f"dcn_{name}_timeout"
    try:
        from ompi_tpu.core import mca

        v = mca.default_context().store.get(full)
        if v is not None:
            return float(v)
    except Exception:  # noqa: BLE001 — pre-init / teardown: use default
        pass
    for fw, comp, vname, default, _typ, _h in ROBUSTNESS_VARS:
        if full_var_name(fw, comp, vname) == full:
            return float(default)
    raise KeyError(f"unknown dcn timeout {name!r}")


class Deadline:
    """The one deadline policy every blocking DCN wait converges on
    (CTS waits, ring writes, blocking receives, dial backoff).

    Monotonic-clock based; ``slice()`` yields the poll quantum for
    loops that must stay sensitive to failure detection between
    checks; ``check()`` raises :class:`ompi_tpu.core.errors.
    DeadlineExpiredError` — callers translate expiry into the ULFM
    escalation (``MPIProcFailedError`` + detector notification)
    appropriate to their layer."""

    __slots__ = ("seconds", "_t0")

    def __init__(self, seconds: float):
        import time

        self.seconds = float(seconds)
        self._t0 = time.monotonic()

    @classmethod
    def for_timeout(cls, name: str) -> "Deadline":
        return cls(dcn_timeout(name))

    def elapsed(self) -> float:
        import time

        return time.monotonic() - self._t0

    def remaining(self) -> float:
        return max(0.0, self.seconds - self.elapsed())

    def expired(self) -> bool:
        return self.elapsed() > self.seconds

    def slice(self, quantum: float = 0.25) -> float:
        """Bounded wait quantum: never overshoots the deadline, never
        returns a non-positive wait."""
        return max(0.001, min(quantum, self.remaining()))

    def check(self, what: str = "") -> None:
        if self.expired():
            from ompi_tpu.core.errors import DeadlineExpiredError

            raise DeadlineExpiredError(
                f"deadline expired after {self.seconds}s"
                + (f": {what}" if what else ""))
