"""Core layer: MCA var system, component registry, errors.

≈ the reference's ``opal/mca/base`` + ``opal/class`` + ``opal/util``
(SURVEY.md §2.1). The OO object system (``OBJ_NEW/RETAIN/RELEASE``) is
replaced by Python object semantics; the var system and component
architecture are reproduced faithfully (see var.py / registry.py).
"""

from .errors import MPIError  # noqa: F401
from .registry import (  # noqa: F401
    Component,
    ComponentError,
    Framework,
    MCAContext,
    SelectionError,
    register_component,
)
from .var import VarStore  # noqa: F401
