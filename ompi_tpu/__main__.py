"""CLI: ``python -m ompi_tpu <command>``.

Commands (≈ the reference's tool surface):
  info    — frameworks/components/vars dump (≈ ompi_info)
  run     — job launcher (≈ mpirun); see ``run --help``
  mpicc   — compile a stock MPI C program against libtpumpi
"""

from __future__ import annotations

import sys


def main() -> int:
    if len(sys.argv) < 2 or sys.argv[1] in ("-h", "--help"):
        print(__doc__)
        return 0
    cmd, rest = sys.argv[1], sys.argv[2:]
    if cmd == "info":
        from ompi_tpu.core.info import main as info_main

        return info_main(rest)
    if cmd in ("run", "tpurun"):
        from ompi_tpu.boot.tpurun import main as run_main

        return run_main(rest)
    if cmd == "mpicc":
        from ompi_tpu.native import mpicc_main

        return mpicc_main(rest)
    print(f"unknown command {cmd!r}; try 'info', 'run', or 'mpicc'", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
