"""``rmaps`` — rank-to-host mapping policies.

≈ the reference's ``prte/orte rmaps`` framework (SURVEY.md §2.4:
``round_robin``, ``ppr``, ``rank_file``, ``seq`` [bin]): given an
allocation (hosts with slot counts) and a process count, produce the
rank → host table the launcher (plm) executes.  Pure functions —
the mapping is testable without launching anything, the same way the
reference dry-runs mappers with ``prte --display map --do-not-launch``
(SURVEY.md §4).

Policies (``--map-by``):

* ``slot`` (default) — fill each host's slots before moving to the
  next (the reference's byslot round-robin);
* ``node`` — one rank per host, cycling (bynode);
* ``ppr:N`` — N processes per round per host (processes-per-resource);
* ``seq`` — the host list IS the per-rank sequence (rank r on
  hosts[r]; requires len(hosts) >= np).
"""

from __future__ import annotations

from ompi_tpu.core.errors import MPIArgError


def _slots(text: str, context: str) -> int:
    try:
        n = int(text)
    except ValueError:
        raise MPIArgError(f"bad slot count {text!r} in {context}")
    if n < 1:
        raise MPIArgError(f"slot count must be >= 1 in {context}")
    return n


def parse_hostfile(text: str) -> list[tuple[str, int]]:
    """``host [slots=N]`` lines (comments/blank lines skipped) — the
    reference's hostfile grammar subset."""
    hosts: list[tuple[str, int]] = []
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        name = parts[0]
        slots = 1
        for p in parts[1:]:
            if p.startswith("slots="):
                slots = _slots(p.split("=", 1)[1], f"hostfile line {line!r}")
        hosts.append((name, slots))
    return hosts


def parse_host_list(spec: str) -> list[tuple[str, int]]:
    """``--host a,b:4,c`` — ``:N`` is the slot count (default 1; the
    suffix is only a slot count when it is numeric, so IPv6 literals
    like ``::1`` stay whole)."""
    hosts = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        name, sep, suffix = item.rpartition(":")
        if sep and suffix.isdigit() and name:
            hosts.append((name, _slots(suffix, f"--host entry {item!r}")))
        else:
            hosts.append((item, 1))
    return hosts


def map_ranks(hosts: list[tuple[str, int]], np_: int,
              policy: str = "slot", oversubscribe: bool = False) -> list[str]:
    """rank → hostname table for ``np_`` ranks.

    Slots bound the per-host rank count unless ``oversubscribe``
    (matching ``mpirun --oversubscribe``); exceeding the allocation
    without it is the same hard error the reference raises.
    """
    if not hosts:
        raise MPIArgError("empty host allocation")
    if np_ < 1:
        raise MPIArgError(f"np must be >= 1, got {np_}")
    total_slots = sum(s for _, s in hosts)

    if policy == "seq":
        if len(hosts) < np_:
            raise MPIArgError(
                f"seq mapping needs one host entry per rank "
                f"({len(hosts)} < {np_})"
            )
        return [hosts[r][0] for r in range(np_)]

    if policy.startswith("ppr:"):
        try:
            per_round = int(policy.split(":", 1)[1])
        except ValueError:
            raise MPIArgError(f"bad ppr policy {policy!r} (want ppr:N)")
        if per_round < 1:
            raise MPIArgError("ppr count must be >= 1")
    elif policy == "node":
        per_round = 1
    elif policy == "slot":
        per_round = None  # fill slots
    else:
        raise MPIArgError(
            f"unknown mapping policy {policy!r} (slot|node|ppr:N|seq)"
        )

    if not oversubscribe and np_ > total_slots:
        raise MPIArgError(
            f"{np_} ranks exceed the {total_slots}-slot allocation; "
            f"use --oversubscribe to allow it"
        )

    out: list[str] = []
    if per_round is None:  # byslot: fill each host's slots in order,
        while len(out) < np_:  # wrapping only under --oversubscribe
            for name, slots in hosts:
                for _ in range(slots):
                    if len(out) < np_:
                        out.append(name)
            if not oversubscribe:
                break
        return out

    # bynode / ppr: per_round ranks per host each cycle, slot-bounded
    # (counts keyed by allocation-entry index: duplicate host names are
    # distinct slot pools, as in a hostfile that repeats a host)
    counts = [0] * len(hosts)
    while len(out) < np_:
        progressed = False
        for i, (name, slots) in enumerate(hosts):
            for _ in range(per_round):
                if len(out) >= np_:
                    break
                if not oversubscribe and counts[i] >= slots:
                    continue
                counts[i] += 1
                out.append(name)
                progressed = True
        if not progressed:
            break
    if len(out) < np_:
        raise MPIArgError(
            f"mapping stalled at {len(out)}/{np_} ranks over "
            f"{sum(s for _, s in hosts)} slots (policy {policy})"
        )
    return out


def render_map(table: list[str]) -> str:
    """``--display-map`` text (≈ prte --display map)."""
    lines = ["JOB MAP"]
    byhost: dict[str, list[int]] = {}
    for r, h in enumerate(table):
        byhost.setdefault(h, []).append(r)
    for h, ranks in byhost.items():
        lines.append(f"  host {h}: ranks {','.join(map(str, ranks))}")
    return "\n".join(lines)
