"""``tpurun`` — the job launcher (mpirun/prterun-equivalent).

≈ the reference's launch path (SURVEY.md §3.1): ``mpirun`` parses the
schizo/ompi CLI (``-np``, ``--mca k v``), hosts the PMIx server, maps
ranks, forks workers, forwards their stdio, tracks job state, and kills
the job on first failure (errmgr default).  Here:

* KVS server in the launcher process (≈ mpirun's embedded PMIx server);
* local fork of N worker processes (``plm`` ≈ odls fork/exec; remote
  nodes would add an ssh leg — single-host in this environment);
* ``--mca`` params propagated via ``OMPI_MCA_*`` env
  (≈ mca_base_var_build_env);
* stdio forwarding with ``[rank]`` prefixes (≈ iof);
* first nonzero exit → terminate the job, propagate the code.

Usage::

    python -m ompi_tpu run -np 4 [--mca k v ...] [--cpu-devices K] script.py [args...]
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading

from .kvs import KVSServer
from .proc import (ENV_HOST_IDS, ENV_INCARNATION, ENV_KVS, ENV_NPROCS,
                   ENV_PROC, ENV_RSH)


def _forward(stream, prefix: str, out) -> None:
    for line in iter(stream.readline, b""):
        out.write(f"[{prefix}] ".encode() + line)
        out.flush()


#: env keys reproduced on the remote side of an rsh launch
_REMOTE_ENV_KEYS = ("PYTHONPATH", "JAX_PLATFORMS", "XLA_FLAGS")


def _final_cmd(launch_agent: str, cmd: list[str], env: dict,
               target: str | None) -> list[str]:
    """The command actually executed for one rank (re-evaluated on
    every respawn: the rsh payload bakes the env exports into the
    command string, so a reborn remote rank must rebuild it or lose
    the bumped OMPI_TPU_INCARNATION)."""
    if target is not None and not _is_local_host(target):
        keys = sorted(
            k for k in env
            if k.startswith(("OMPI_TPU_", "OMPI_MCA_"))
            or k in _REMOTE_ENV_KEYS
        )
        return _remote_cmd(launch_agent, target, env, keys, cmd)
    return cmd


def _truthy(v) -> bool:
    """MCA-style bool for launcher-side flags — the workers' VarStore
    accepts exactly this string set, so the launcher-side gate cannot
    drift from the worker-side parse."""
    from ompi_tpu.core.var import _TRUE_STRINGS

    return str(v or "").strip().lower() in _TRUE_STRINGS


def worker_cmd(argv: list[str]) -> list[str]:
    """The per-rank exec vector: native executables (compiled against
    libtpumpi) run directly; .py scripts go through the interpreter.
    Absolute path for executables: a bare filename would hit execvp
    PATH lookup instead of the file we just stat'ed."""
    first = argv[0]
    if first.endswith(".py") or not (
        os.path.isfile(first) and os.access(first, os.X_OK)
    ):
        return [sys.executable] + argv
    return [os.path.abspath(first)] + argv[1:]


def worker_env(rank: int, np_: int, kvs_address: str,
               mca: dict[str, str] | None = None,
               cpu_devices: int | None = None,
               extra_env: dict[str, str] | None = None,
               telemetry_addr: str | None = None) -> dict[str, str]:
    """One rank's environment (shared by ``run_job`` and the tpud
    daemon's resident-worker spawn path): framework on PYTHONPATH
    (≈ mpirun's LD_LIBRARY_PATH forwarding for libmpi), rank/size/
    rendezvous coordinates, ``--mca`` params as ``OMPI_MCA_*``, and
    the CPU-device virtualization for TPU-less testing."""
    import ompi_tpu

    pkg_root = os.path.dirname(
        os.path.dirname(os.path.abspath(ompi_tpu.__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = pkg_root + (
        ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    env[ENV_PROC] = str(rank)
    env[ENV_NPROCS] = str(np_)
    env[ENV_KVS] = kvs_address
    if telemetry_addr:
        from ompi_tpu.metrics.live import ENV_TELEMETRY

        env[ENV_TELEMETRY] = telemetry_addr
    for k, v in (mca or {}).items():
        env[f"OMPI_MCA_{k}"] = v
    if cpu_devices is not None:
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={cpu_devices}"
        ).strip()
        # CPU-only workers must not touch TPU plugin site hooks:
        # some PJRT plugin sitecustomize modules dial the device
        # service at interpreter start regardless of JAX_PLATFORMS
        # and can block the whole job on a wedged fabric.
        env["PYTHONPATH"] = ":".join(
            p for p in env["PYTHONPATH"].split(":")
            if p and "axon" not in p
        )
    env.update(extra_env or {})
    return env


#: host names the plm treats as THIS machine (fork instead of rsh)
_LOCAL_NAMES = {"localhost", "127.0.0.1"}


def _is_local_host(name: str) -> bool:
    import socket as _socket

    return name in _LOCAL_NAMES or name == _socket.gethostname()


def _remote_cmd(agent: str, host: str, env: dict, keys: list[str],
                cmd: list[str]) -> list[str]:
    """plm/rsh command line: the launch agent template (default
    ``ssh {host} {cmd}``) wrapping an env-exporting sh -c payload —
    the reference's rsh tree-launch collapsed to one level (no daemon
    on the remote side; workers dial the KVS directly, exactly like
    the local fork leg)."""
    import shlex

    exports = " ".join(
        f"{k}={shlex.quote(env[k])}" for k in keys if k in env
    )
    payload = f"cd {shlex.quote(os.getcwd())} && env {exports} " + " ".join(
        shlex.quote(c) for c in cmd
    )
    out = []
    used_cmd = False
    for tok in shlex.split(agent):
        if tok == "{host}":
            out.append(host)
        elif tok == "{cmd}":
            out.append(payload)
            used_cmd = True
        else:
            out.append(tok)
    if not used_cmd:
        out.append(payload)
    return out


def run_job(
    np_: int,
    argv: list[str],
    mca: dict[str, str] | None = None,
    cpu_devices: int | None = None,
    extra_env: dict[str, str] | None = None,
    ft: bool = False,
    hosts: list[tuple[str, int]] | None = None,
    map_by: str = "slot",
    launch_agent: str = "ssh {host} {cmd}",
    oversubscribe: bool = False,
    display_map: bool = False,
    kvs_host: str | None = None,
    respawn: bool = False,
    max_respawns: int = 2,
) -> int:
    """``ft=True`` ≈ ``mpirun --with-ft ulfm``: worker death does NOT
    kill the job (survivors run ULFM recovery); the heartbeat detector
    is enabled in every worker and the job's exit code is rank 0's.

    ``respawn=True`` (requires ``ft``) adds the PRRTE restart leg: a
    worker that dies is relaunched with the same rank and environment
    under a bumped ``OMPI_TPU_INCARNATION`` (at most ``max_respawns``
    times per rank).  The reborn process replays the boot rendezvous —
    re-publishing its endpoint under the new incarnation — and the
    survivors' ``replace()`` rebuilds the communicator at full size.

    ``hosts`` engages the plm/rsh leg: ranks map onto the allocation
    via the rmaps policy (``map_by``); non-local hosts launch through
    ``launch_agent`` (``ssh {host} {cmd}``; any template works — e.g.
    ``bash -c {cmd}`` exercises the full rsh path against this host).
    ``kvs_host``: address the KVS server binds/advertises (must be
    reachable from every host; default loopback is single-host only).
    """
    if ft:
        mca = dict(mca or {})
        mca.setdefault("ft_detector_enable", "1")
    if respawn and not ft:
        raise SystemExit("tpurun: --respawn requires --ft (a non-FT job "
                         "kills the world on first failure)")
    rank_host: list[str] | None = None
    if hosts:
        from .rmaps import map_ranks, render_map

        rank_host = map_ranks(hosts, np_, policy=map_by,
                              oversubscribe=oversubscribe)
        if display_map:
            print(render_map(rank_host), flush=True)
        if kvs_host is None and any(
            not _is_local_host(h) for h in rank_host
        ):
            raise SystemExit(
                "tpurun: remote hosts in the map but no --kvs-host — the "
                "rendezvous server would advertise 127.0.0.1, unreachable "
                "from the remote side; pass --kvs-host <routable address>"
            )
    server = KVSServer(host=kvs_host or "127.0.0.1")
    # live telemetry plane (--mca telemetry_enable 1): the launcher
    # hosts the aggregator — workers stream counter/straggler frames
    # to its ingest socket (address via env) and anything can scrape
    # the job MID-RUN at the printed HTTP endpoint (≈ mpirun hosting
    # the PMIx server, extended with a Prometheus shop window)
    telemetry = None
    env_all = os.environ
    if _truthy((mca or {}).get("telemetry_enable")
               or env_all.get("OMPI_MCA_telemetry_enable")):
        from ompi_tpu.metrics.live import TelemetryAggregator

        telemetry = TelemetryAggregator(
            http_port=int((mca or {}).get("telemetry_port")
                          or env_all.get("OMPI_MCA_telemetry_port")
                          or 0),
            history=int((mca or {}).get("telemetry_history")
                        or env_all.get("OMPI_MCA_telemetry_history")
                        or 256),
        )
        print(f"[tpurun] telemetry: {telemetry.url}/metrics "
              f"(json: {telemetry.url}/json, watch: python tools/top.py "
              f"--url {telemetry.url})", flush=True)
    procs: list[subprocess.Popen] = []
    threads: list[threading.Thread] = []
    #: per-rank (cmd, env, target host) for the --respawn restart leg
    launch_specs: list[tuple[list[str], dict[str, str], str | None]] = []

    def spawn_rank(rank: int, cmd: list[str], env: dict,
                   target: str | None) -> subprocess.Popen:
        """One rank's process + stdio-forward thread (shared by first
        launch and the --respawn restart leg)."""
        p = subprocess.Popen(
            _final_cmd(launch_agent, cmd, env, target),
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        t = threading.Thread(
            target=_forward, args=(p.stdout, str(rank), sys.stdout.buffer),
            daemon=True,
        )
        t.start()
        threads.append(t)
        return p
    # rsh leg marker: ranks mapped onto remote hosts switch every
    # await-respawn deadline to ft_remote_respawn_timeout (a remote
    # relaunch pays the launch-agent round-trip; the env key is
    # OMPI_TPU_-prefixed, so _remote_cmd bakes it into the payload)
    rsh_job = bool(rank_host) and any(
        not _is_local_host(h) for h in rank_host)
    # rank→host map for the workers: detector groups, the sharded
    # modex, and the telemetry relays partition by REAL host when the
    # launcher knows one (the env key is OMPI_TPU_-prefixed so the rsh
    # payload carries it to remote ranks)
    host_ids = ""
    if rank_host:
        order: dict[str, int] = {}
        for h in rank_host:
            order.setdefault(h, len(order))
        host_ids = ",".join(str(order[h]) for h in rank_host)
    try:
        for rank in range(np_):
            env = worker_env(
                rank, np_, server.address, mca=mca,
                cpu_devices=cpu_devices, extra_env=extra_env,
                telemetry_addr=(telemetry.ingest_address
                                if telemetry is not None else None),
            )
            if rsh_job:
                env[ENV_RSH] = "1"
            if host_ids:
                env[ENV_HOST_IDS] = host_ids
            cmd = worker_cmd(argv)
            target = rank_host[rank] if rank_host else None
            # plm/rsh: _final_cmd reproduces the worker env on the
            # remote host (and is re-evaluated on every respawn)
            launch_specs.append((cmd, env, target))
            procs.append(spawn_rank(rank, cmd, env, target))

        # job state machine: poll ALL children so a failure anywhere
        # kills the job even while other ranks block (errmgr default);
        # under --ft, deaths are survivable events the workers' ULFM
        # machinery handles (and under --respawn, the rank is reborn —
        # the PRRTE restart-the-failed-proc leg)
        exit_code = 0
        live = set(range(np_))
        incarnations = [0] * np_
        import time as _time

        while live:
            for i in sorted(live):
                rc = procs[i].poll()
                if rc is None:
                    continue
                live.discard(i)
                if (ft and respawn and rc != 0
                        and incarnations[i] < max_respawns):
                    # restart leg: same rank, same env, bumped
                    # incarnation — the reborn proc replays the boot
                    # rendezvous and re-publishes its endpoint
                    incarnations[i] += 1
                    cmd_i, env_i, target_i = launch_specs[i]
                    env_i = dict(env_i)
                    env_i[ENV_INCARNATION] = str(incarnations[i])
                    print(f"[tpurun] rank {i} died (rc={rc}); "
                          f"respawning (incarnation {incarnations[i]})",
                          flush=True)
                    procs[i] = spawn_rank(i, cmd_i, env_i, target_i)
                    live.add(i)
                    continue
                if rc != 0 and exit_code == 0 and not ft:
                    exit_code = rc
                    for q in procs:
                        if q.poll() is None:
                            q.send_signal(signal.SIGTERM)
            if live:
                _time.sleep(0.05)
        if ft:
            exit_code = procs[0].returncode or 0
        for t in threads:
            # every writer is dead → readline hits EOF; the join bound
            # only guards against pathological scheduler starvation
            t.join(timeout=10)
        return exit_code
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        if telemetry is not None:
            telemetry.close()
        server.close()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpurun", description="Launch an ompi_tpu job (mpirun-equivalent)"
    )
    parser.add_argument("-np", type=int, required=True, help="number of processes")
    parser.add_argument(
        "--mca", nargs=2, action="append", default=[], metavar=("KEY", "VALUE"),
        help="MCA parameter (repeatable), e.g. --mca coll xla",
    )
    parser.add_argument(
        "--cpu-devices", type=int, default=None,
        help="per-process virtual CPU device count (testing without TPU)",
    )
    parser.add_argument(
        "--daemon", action="store_true",
        help="start a persistent serving daemon (tpud) instead of one "
        "job: the rank workers, their DCN endpoints, and the boot KVS "
        "stay warm across jobs submitted via tools/tpud_ctl.py or "
        "ompi_tpu.api.tpud_submit (no script argument; see "
        "ompi_tpu/serve/)",
    )
    parser.add_argument(
        "--ft", action="store_true",
        help="fault-tolerant job: worker death does not kill the job; "
        "heartbeat failure detection + ULFM recovery in the workers",
    )
    parser.add_argument(
        "--respawn", action="store_true",
        help="with --ft: relaunch a dead worker with the same rank and "
        "a bumped incarnation (the PRRTE restart leg); survivors' "
        "replace() restores the communicator to full size",
    )
    parser.add_argument(
        "--max-respawns", type=int, default=2,
        help="respawn budget per rank (default 2)",
    )
    parser.add_argument(
        "--host", default=None, metavar="H1[:S],H2[:S],...",
        help="host allocation (':S' = slots); engages the rsh launch leg "
        "for non-local hosts",
    )
    parser.add_argument(
        "--hostfile", default=None,
        help="hostfile ('host [slots=N]' per line)",
    )
    parser.add_argument(
        "--map-by", default="slot", metavar="slot|node|ppr:N|seq",
        help="rank mapping policy over the allocation (rmaps)",
    )
    parser.add_argument(
        "--launch-agent", default="ssh {host} {cmd}",
        help="remote launch template; {host}/{cmd} substituted "
        "(default 'ssh {host} {cmd}')",
    )
    parser.add_argument(
        "--ras", default="auto",
        choices=["auto", "slurm", "gridengine", "none"],
        help="resource-allocation reader: adopt a SLURM/Grid Engine "
        "allocation from the environment when no --host/--hostfile is "
        "given ('auto' detects, 'slurm'/'gridengine' require one, "
        "'none' disables adoption)",
    )
    parser.add_argument(
        "--oversubscribe", action="store_true",
        help="allow more ranks than allocated slots",
    )
    parser.add_argument(
        "--display-map", action="store_true",
        help="print the rank->host map before launching",
    )
    parser.add_argument(
        "--kvs-host", default=None,
        help="address the KVS/rendezvous server binds (must be reachable "
        "from every host; default 127.0.0.1 is single-host)",
    )
    parser.add_argument("script", nargs="?", default=None,
                        help="python script to run (omitted with --daemon)")
    parser.add_argument("args", nargs=argparse.REMAINDER)
    ns = parser.parse_args(argv)
    mca = {k: v for k, v in ns.mca}
    if ns.daemon:
        # persistent serving plane: delegate to the tpud daemon (the
        # one-shot path below stays byte-identical when --daemon is
        # absent — no new threads, no new sockets)
        from ompi_tpu.serve.daemon import run_daemon

        if ns.script is not None:
            parser.error("--daemon takes no script (submit jobs via "
                         "tools/tpud_ctl.py)")
        # flags the daemon path does not honor must fail loudly, not
        # come up silently non-ft (--ft is implied: the daemon always
        # runs the detector + respawn plane).  A host map IS honored:
        # the daemon becomes a DVM — one launch agent per remote host
        # over the rsh leg owns that host's worker spawn/respawn/
        # pid-liveness (serve/agent.py)
        for flag, val in (("--ft", ns.ft), ("--respawn", ns.respawn)):
            if val:
                parser.error(f"{flag} is not supported with --daemon "
                             "(ft/respawn are built in)")
        hosts = None
        if ns.hostfile:
            from .rmaps import parse_hostfile

            with open(ns.hostfile) as f:
                hosts = parse_hostfile(f.read())
        elif ns.host:
            from .rmaps import parse_host_list

            hosts = parse_host_list(ns.host)
        if hosts and ns.kvs_host is None and any(
                not _is_local_host(h) for h, _slots in hosts):
            parser.error(
                "--daemon with remote hosts needs --kvs-host <routable "
                "address> (the control plane binds it; 127.0.0.1 is "
                "unreachable from the remote side)")
        return run_daemon(ns.np, mca=mca, cpu_devices=ns.cpu_devices,
                          max_respawns=ns.max_respawns, hosts=hosts,
                          map_by=ns.map_by,
                          launch_agent=ns.launch_agent,
                          kvs_host=ns.kvs_host,
                          oversubscribe=ns.oversubscribe)
    if ns.script is None:
        parser.error("the following arguments are required: script")
    hosts = None
    if ns.hostfile:
        from .rmaps import parse_hostfile

        with open(ns.hostfile) as f:
            hosts = parse_hostfile(f.read())
    elif ns.host:
        from .rmaps import parse_host_list

        hosts = parse_host_list(ns.host)
    elif ns.ras != "none":
        # ras: adopt a resource manager's allocation (SURVEY §2.4
        # ras/slurm + ras/gridengine)
        from . import ras as ras_mod

        if ns.ras == "auto":
            hosts = ras_mod.detect(os.environ)
        elif ns.ras == "slurm":
            hosts = ras_mod.read_slurm(os.environ)
        else:  # argparse choices guarantees: gridengine
            hosts = ras_mod.read_gridengine(os.environ)
    return run_job(ns.np, [ns.script] + ns.args, mca, ns.cpu_devices,
                   ft=ns.ft, hosts=hosts, map_by=ns.map_by,
                   launch_agent=ns.launch_agent,
                   oversubscribe=ns.oversubscribe,
                   display_map=ns.display_map, kvs_host=ns.kvs_host,
                   respawn=ns.respawn, max_respawns=ns.max_respawns)


if __name__ == "__main__":
    sys.exit(main())
