"""``ras`` — resource-allocation readers (SLURM / Grid Engine).

≈ the reference's ``prte/orte ras`` framework (SURVEY.md §2.4:
``ras/slurm``, ``ras/gridengine`` [bin]): when the job was started
inside a resource manager's allocation, adopt that allocation as the
host table instead of requiring ``--host``/``--hostfile``.  Pure
environment/file parsing — testable with a fabricated allocation, the
same dry-run technique the rmaps tests use.

SLURM grammar handled (the subset ras/slurm parses):

* ``SLURM_JOB_NODELIST`` (fallback ``SLURM_NODELIST``) — compressed
  node expressions: ``n[001-003,007],login1,gpu[2,4-5]`` with
  zero-padded numeric ranges;
* ``SLURM_TASKS_PER_NODE`` (fallback ``SLURM_JOB_CPUS_PER_NODE``) —
  per-node slot counts with repetition: ``2(x3),1`` pairs with the
  expanded node list positionally.

Grid Engine: ``PE_HOSTFILE`` points at a file of
``host slots queue processor`` lines.
"""

from __future__ import annotations

import re

from ompi_tpu.core.errors import MPIArgError


def expand_nodelist(spec: str) -> list[str]:
    """Expand a SLURM compressed node expression into host names."""
    hosts: list[str] = []
    i, n = 0, len(spec)
    while i < n:
        # one item: prefix possibly followed by ONE [ranges] group
        # (SLURM emits per-prefix groups; nested brackets don't occur)
        j = i
        while j < n and spec[j] not in ",[":
            j += 1
        prefix = spec[i:j]
        if j < n and spec[j] == "[":
            k = spec.index("]", j)  # ValueError → caller's MPIArgError
            body = spec[j + 1 : k]
            for part in body.split(","):
                if "-" in part:
                    lo, hi = part.split("-", 1)
                    width = len(lo) if lo.startswith("0") else 0
                    for v in range(int(lo), int(hi) + 1):
                        hosts.append(f"{prefix}{v:0{width}d}" if width
                                     else f"{prefix}{v}")
                else:
                    hosts.append(prefix + part)
            i = k + 1
            if i < n and spec[i] == ",":
                i += 1
        else:
            if prefix:
                hosts.append(prefix)
            i = j + 1
    return hosts


def expand_tasks_per_node(spec: str) -> list[int]:
    """``2(x3),1`` → [2, 2, 2, 1]."""
    out: list[int] = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        m = re.fullmatch(r"(\d+)(?:\(x(\d+)\))?", item)
        if not m:
            raise MPIArgError(f"bad SLURM_TASKS_PER_NODE item {item!r}")
        out.extend([int(m.group(1))] * int(m.group(2) or 1))
    return out


def read_slurm(env) -> list[tuple[str, int]]:
    """(host, slots) allocation from a SLURM job environment."""
    nodelist = env.get("SLURM_JOB_NODELIST") or env.get("SLURM_NODELIST")
    if not nodelist:
        raise MPIArgError(
            "--ras slurm: no SLURM allocation in the environment "
            "(SLURM_JOB_NODELIST unset)"
        )
    try:
        hosts = expand_nodelist(nodelist)
    except ValueError as e:
        raise MPIArgError(f"bad SLURM nodelist {nodelist!r}: {e}")
    if not hosts:
        raise MPIArgError(f"empty SLURM nodelist {nodelist!r}")
    tasks = env.get("SLURM_TASKS_PER_NODE") or env.get(
        "SLURM_JOB_CPUS_PER_NODE")
    if tasks:
        counts = expand_tasks_per_node(tasks)
        if len(counts) < len(hosts):
            # SLURM pads the last group; be permissive, repeat the tail
            counts.extend([counts[-1]] * (len(hosts) - len(counts)))
        return list(zip(hosts, counts[: len(hosts)]))
    return [(h, 1) for h in hosts]


def read_gridengine(env) -> list[tuple[str, int]]:
    """(host, slots) from a Grid Engine ``PE_HOSTFILE``."""
    path = env.get("PE_HOSTFILE")
    if not path:
        raise MPIArgError(
            "--ras gridengine: PE_HOSTFILE unset in the environment"
        )
    hosts: list[tuple[str, int]] = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            slots = 1
            if len(parts) > 1:
                try:
                    slots = max(1, int(parts[1]))
                except ValueError:
                    pass
            hosts.append((parts[0], slots))
    if not hosts:
        raise MPIArgError(f"empty PE_HOSTFILE {path}")
    return hosts


def detect(env) -> list[tuple[str, int]] | None:
    """``--ras auto``: adopt whichever manager's allocation is present
    (SLURM first, then Grid Engine); None when outside any."""
    if env.get("SLURM_JOB_NODELIST") or env.get("SLURM_NODELIST"):
        return read_slurm(env)
    if env.get("PE_HOSTFILE"):
        return read_gridengine(env)
    return None
