"""Boot layer: rendezvous + launch (≈ PMIx + PRRTE subset, SURVEY.md §2.4)."""

from .kvs import KVSClient, KVSServer  # noqa: F401
from .proc import ProcContext, launched_by_tpurun  # noqa: F401
