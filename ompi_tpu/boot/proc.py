"""Per-process bootstrap — rank/size/rendezvous from the environment.

≈ ``ess`` (environment-specific services) + the PMIx client init +
modex of SURVEY.md §3.2: a worker launched by ``tpurun`` reads its
process index and the coordinator address from env vars, connects the
KVS, publishes its DCN endpoint (``PMIx_Put`` + ``PMIx_Commit``),
fences, and collects peer endpoints.

The collection is **sharded and lazy** on the Python transports (the
PMIx "instant-on" shape): ranks are partitioned into the same groups
the hierarchical failure detector uses (host id when known, else
``ft_group_size`` chunks); each group's *leader* pulls every endpoint
with ONE ``get_prefix`` scan and publishes its group's slice as a
bundle; members issue ONE get for the bundle and resolve any peer
outside their group lazily on first send (one KVS get, cached).  Boot
KVS traffic drops from O(P²) per-rank gets to O(P + groups·P), and
the fence gates only the puts — never on every rank having pulled
every address.  The native C plane (and any reborn incarnation, whose
boot-time bundle may be stale for previously-reborn peers) keeps the
eager per-peer gather.
"""

from __future__ import annotations

import os

from ompi_tpu.dcn.collops import DcnCollEngine
from .kvs import KVSClient

ENV_PROC = "OMPI_TPU_PROC"
ENV_NPROCS = "OMPI_TPU_NPROCS"
ENV_KVS = "OMPI_TPU_KVS_ADDR"
#: KVS key namespace — spawned child worlds share the job's KVS server
#: but live under their own prefix (dynamic process management)
ENV_NS = "OMPI_TPU_KVS_NS"
#: rebirth counter (tpurun --respawn): 0 on first launch; a respawned
#: worker replays the boot rendezvous under a bumped incarnation so
#: survivors can distinguish the reborn endpoint from the corpse's
ENV_INCARNATION = "OMPI_TPU_INCARNATION"
#: set by tpurun when the job maps ranks onto remote hosts (the
#: plm/rsh leg): a remote respawn pays the launch-agent round-trip on
#: top of the boot, so every await-respawn deadline switches from
#: ft_respawn_timeout to ft_remote_respawn_timeout
ENV_RSH = "OMPI_TPU_RSH"
#: comma-separated host index per rank (tpurun publishes it whenever a
#: host map exists): detector groups and the sharded modex partition
#: by real host instead of ft_group_size chunks
ENV_HOST_IDS = "OMPI_TPU_HOST_IDS"


def respawn_timeout(store) -> float:
    """The await-respawn deadline (replace(), the reborn rejoin grace,
    the serve repair wait): ``ft_remote_respawn_timeout`` on the rsh
    leg (:data:`ENV_RSH`), ``ft_respawn_timeout`` locally."""
    if os.environ.get(ENV_RSH):
        return float(
            store.get("ft_remote_respawn_timeout", 120.0) or 120.0)
    return float(store.get("ft_respawn_timeout", 60.0) or 60.0)


def launched_by_tpurun() -> bool:
    return ENV_PROC in os.environ


class ProcContext:
    """This process's place in a tpurun job."""

    def __init__(self, local_size: int | None = None):
        self.proc = int(os.environ[ENV_PROC])
        self.nprocs = int(os.environ[ENV_NPROCS])
        self.ns = os.environ.get(ENV_NS, "")
        #: elastic recovery state: this process's rebirth count, the
        #: highest incarnation we know per peer (replace() polls past
        #: it), and whether a reborn process has rejoined the job yet
        self.incarnation = int(os.environ.get(ENV_INCARNATION, "0"))
        self.incarnations: dict[int, int] = {}
        self.rejoined = self.incarnation == 0
        #: partial-replace beacon keys this reborn incarnation already
        #: consumed — replace_partial walks the (proc, inc, cid) queue
        self.healed_partials: set[str] = set()
        self.kvs = KVSClient(os.environ[ENV_KVS])
        # modex: publish DCN endpoint, fence, gather peers. Transport
        # tunables come from the btl/tcp component's MCA vars (so
        # --mca btl_tcp_eager_limit etc. behave as in the reference).
        from ompi_tpu.core import mca
        from ompi_tpu.core.registry import ComponentError

        ctx = mca.default_context()
        fw = ctx.framework("btl")
        # open() first: a mistyped explicit include (--mca btl tpc) must
        # abort here, as the reference does — only AFTER a clean open is
        # "no component" a legitimate state (^tcp exclusion)
        fw.open()
        try:
            comp = fw.select_one()
        except ComponentError:
            params = {}  # btl excluded (^tcp) → transport defaults
        else:
            # bad --mca btl_tcp_* values propagate (the reference
            # aborts on unparseable MCA values; so do we)
            params = comp.params(ctx.store)
        self.engine = self._make_engine(params)
        addr = self.engine.transport.address
        self.kvs.put(f"{self.ns}dcn.{self.proc}", addr)
        #: per-proc local-rank counts, filled by the sharded modex when
        #: api.init passed ``local_size`` — lets MultiProcComm skip the
        #: boot allgather entirely (no boot collective: instant-on)
        self.wsizes: list[int] | None = None
        if local_size is not None:
            self.kvs.put(f"{self.ns}wsize.{self.proc}", int(local_size))
        if self.incarnation:
            # rebirth rendezvous: the incarnation-suffixed address key
            # plus the incarnation beacon survivors' replace() polls —
            # the plain dcn.<proc> key still holds the CORPSE's address
            # in their caches until replace() refreshes it
            self.kvs.put(f"{self.ns}dcn.{self.proc}.i{self.incarnation}",
                         addr)
            self.kvs.put(f"{self.ns}inc.{self.proc}", self.incarnation)
        # the modex fence is idempotent for a reborn proc (the fence
        # set already contains every rank), so this returns instantly
        # on incarnation > 0 — by design: survivors are mid-job, not
        # waiting at a barrier.  It gates only the PUTS above — never
        # on any rank having pulled any address.
        self.kvs.fence(f"{self.ns}modex", self.proc, self.nprocs)
        # detector-group topology (shared with the sharded modex and
        # the telemetry relays): host ids when the launcher published
        # a map, else ft_group_size chunks
        from ompi_tpu.ft.detector import (FtDetectorComponent,
                                          HeartbeatDetector,
                                          compute_groups, parse_host_ids)

        ftp = FtDetectorComponent().params(ctx.store)
        self.hosts = parse_host_ids(os.environ.get(ENV_HOST_IDS, ""),
                                    self.nprocs)
        # mirror the detector's gate exactly (<= 0 collapses to ONE
        # group): `or` alone would turn a negative into singleton
        # groups and break the shared-topology invariant
        gsz = ftp["group_size"] if ftp["group_size"] > 0 else self.nprocs
        self.groups = compute_groups(self.nprocs, gsz, self.hosts)
        self.group = next(g for g in self.groups if self.proc in g)
        self._mine_native = addr.startswith("ntv:")
        if (self.nprocs == 1 or self.incarnation or local_size is None):
            # reborn incarnations keep the eager gather (a boot-time
            # bundle may be stale for previously-reborn peers); direct
            # construction without a local size has no wsize beacons
            self._modex_eager()
        else:
            # BOTH planes ride the sharded lazy modex now: the native
            # engine accepts an AddressTable too (primed slots install
            # eagerly via tdcn_set_addresses — <= group size of them —
            # and cross-group peers resolve through the table's one
            # KVS get on first send, mirrored into the C table by
            # tdcn_set_address_one / the tdcn_set_resolver callback)
            self._modex_sharded(local_size)
        # failure detector (tpurun --ft / --mca ft_detector_enable 1):
        # hierarchical heartbeats + versioned gossip; detections fan
        # out to every registered communicator's ULFM state (SURVEY.md
        # §5 failure detection)
        import threading
        import weakref

        self._ft_comms: "weakref.WeakSet" = weakref.WeakSet()
        self._ft_lock = threading.Lock()
        self.detector = None
        if ftp["enable"] and self.nprocs > 1:
            # a reborn proc's peers stay silent toward it until their
            # replace() clears its failed mark — grace the first
            # detection window so the rejoin isn't poisoned by its own
            # detector declaring every survivor dead
            grace = 0.0
            if self.incarnation:
                grace = respawn_timeout(ctx.store)
            self.detector = HeartbeatDetector(
                self.engine, period=ftp["period"], timeout=ftp["timeout"],
                grace=grace, group_size=ftp["group_size"],
                hosts=self.hosts, digest=ftp["digest"],
                incarnation=self.incarnation,
            )
            self.detector.on_failure(self._fan_out_failure)
            self.detector.on_heal(self._fan_out_heal)

    # -- modex (eager + sharded legs) ------------------------------------

    def _check_plane(self, pairs) -> None:
        """Wire-plane agreement: the published address reveals each
        peer's plane ("ntv:" = libtpudcn framing).  A mixed job (one
        host lacking the C++ toolchain, a per-process fallback) must
        abort with a clear message — native frames against a Python
        endpoint would otherwise hang the first collective."""
        mixed = sorted(p for p, a in pairs
                       if a.startswith("ntv:") != self._mine_native)
        if mixed:
            from ompi_tpu.core.errors import MPIInternalError

            raise MPIInternalError(
                f"DCN wire-plane mismatch: proc {self.proc} uses the "
                f"{'native' if self._mine_native else 'Python'} "
                f"transport but procs {mixed} published the other "
                f"plane (a host without the C++ toolchain?); force "
                f"one with --mca btl tcp|sm|bml on every host"
            )

    def _modex_eager(self) -> None:
        """The pre-hierarchical gather: P−1 gets per rank.  Kept for
        single-proc jobs, reborn incarnations (a boot-time bundle may
        be stale for previously-reborn peers), and direct ProcContext
        construction without a local size.  (The native C plane rides
        the sharded leg since the incremental-install surface —
        tdcn_set_address_one + the lazy-resolver callback — landed.)"""
        addresses = [self.kvs.get(f"{self.ns}dcn.{p}")
                     for p in range(self.nprocs)]
        self._check_plane(enumerate(addresses))
        self.engine.set_addresses(addresses)

    def _resolve_addr(self, p: int) -> str:
        """Lazy modex get — first send to an out-of-group peer."""
        a = self.kvs.get(f"{self.ns}dcn.{p}")
        self._check_plane([(p, a)])
        return a

    def _modex_sharded(self, local_size: int) -> None:
        """The instant-on leg: the group leader's ONE ``get_prefix``
        scan primes a per-group bundle (own-group addresses + every
        rank's local size); members issue ONE get for it; everything
        else resolves lazily on first send (:class:`~ompi_tpu.dcn.
        collops.AddressTable`).  A leader that died at boot degrades
        members to the eager gather after the bundle get times out."""
        from ompi_tpu.dcn.collops import AddressTable

        gi = self.groups.index(self.group)
        key = f"{self.ns}modex.g{gi}"
        primed: dict[int, str] = {}
        #: native leader only: cross-group addresses from the scan,
        #: cached into the table AFTER the engine install so the C
        #: plane's eager-install count stays <= group size without
        #: re-paying a KVS get per cross-group peer (the C-side lazy
        #: resolver reads the cached slot instead)
        cache_after: dict[int, str] = {}
        if self.proc == self.group[0]:
            scan = self.kvs.get_prefix(f"{self.ns}dcn.")
            base = len(f"{self.ns}dcn.")
            allmap = {int(k[base:]): v for k, v in scan.items()
                      if k[base:].isdigit()}
            wscan = self.kvs.get_prefix(f"{self.ns}wsize.")
            wbase = len(f"{self.ns}wsize.")
            wsizes = {int(k[wbase:]): int(v) for k, v in wscan.items()
                      if k[wbase:].isdigit()}
            self._check_plane(sorted(allmap.items()))
            self.kvs.put(key, {
                "addrs": {str(p): allmap[p] for p in self.group
                          if p in allmap},
                "wsizes": {str(p): wsizes[p] for p in sorted(wsizes)},
            })
            if self._mine_native:
                # native plane: install only the group slice eagerly,
                # so the C engine's addr_installs counter reads
                # <= group size on EVERY rank; the scan's cross-group
                # addresses are NOT discarded — they cache into the
                # table after the install, where the C lazy resolver
                # finds them without re-paying a KVS get
                primed = {p: allmap[p] for p in self.group
                          if p in allmap}
                cache_after = {p: a for p, a in allmap.items()
                               if p not in primed}
            else:
                primed = allmap  # the leader paid for the scan: keep it
            self.wsizes = ([wsizes[p] for p in range(self.nprocs)]
                           if len(wsizes) == self.nprocs else None)
        else:
            try:
                bundle = self.kvs.get(key)
                primed = {int(p): a
                          for p, a in (bundle.get("addrs") or {}).items()}
                ws = {int(p): int(w)
                      for p, w in (bundle.get("wsizes") or {}).items()}
                self.wsizes = ([ws[p] for p in range(self.nprocs)]
                               if len(ws) == self.nprocs else None)
                self._check_plane(sorted(primed.items()))
            except (KeyError, ValueError):
                # group leader never published (died at boot?): degrade
                self._modex_eager()
                return
        primed[self.proc] = self.engine.transport.address
        table = AddressTable(self.nprocs, self._resolve_addr, primed)
        self.engine.set_addresses(table)
        for p, a in cache_after.items():
            # cached slots read like primed ones (no resolver call,
            # no KVS get) but were never eagerly installed in C — the
            # engine's lazy-resolver callback pulls them on demand
            list.__setitem__(table, p, a)

    def _make_engine(self, params: dict):
        """Engine selection: the native C++ data plane when the btl
        picked it AND libtpudcn builds on this machine; otherwise the
        Python transports (also the fallback when the toolchain is
        absent — same graceful degradation as a reference build
        without a btl's prerequisites)."""
        params = dict(params)
        if params.get("transport") == "native":
            params.pop("transport")
            try:
                from ompi_tpu.dcn import native as dcn_native

                if dcn_native.available():
                    return dcn_native.NativeDcnEngine(
                        self.proc, self.nprocs, **params)
            except Exception as e:  # noqa: BLE001 — degrade, loudly
                import sys

                print(
                    f"[ompi_tpu] native data plane unavailable "
                    f"({type(e).__name__}: {e}); falling back to the "
                    f"Python bml transport", file=sys.stderr,
                )
            params.pop("ring_bytes", None)
            params["transport"] = "bml"
        params.pop("ring_bytes", None)
        return DcnCollEngine(self.proc, self.nprocs, **params)

    def _fan_out_failure(self, root_proc: int) -> None:
        with self._ft_lock:  # registration races the detector thread
            comms = list(self._ft_comms)
        for comm in comms:
            comm._on_proc_failed(root_proc)

    def _fan_out_heal(self, root_proc: int) -> None:
        """False-positive heal: the un-fail fan-out — every registered
        communicator's ULFM failed marks for the proc's ranks clear,
        so per-op guards stop raising about a peer that was never
        actually dead."""
        with self._ft_lock:
            comms = list(self._ft_comms)
        for comm in comms:
            heal = getattr(comm, "_on_proc_healed", None)
            if heal is not None:
                heal(root_proc)

    def register_comm(self, comm) -> None:
        """Track a MultiProcComm for failure fan-out; replay known
        failures so comms created post-failure start consistent."""
        with self._ft_lock:
            self._ft_comms.add(comm)
        if self.detector is not None:
            for p in self.detector.failed():
                comm._on_proc_failed(p)

    def adopt_incarnation_floors(self, incs) -> None:
        """Fold a recovery beacon's incarnation floors in: the
        ``incarnations`` map (await_respawn polls past them) AND the
        detector's rebirth floor — a reborn process boots with both
        empty, and without the detector half a fellow reborn peer's
        current-incarnation heartbeats would read as a rebirth
        detection and falsely re-mark it (the multi-victim case a
        whole-host kill produces).  A proc the beacon names restored
        that THIS process currently marks failed was marked against
        the corpse (the reborn fellows boot in parallel, and an early
        send can hit a corpse address and strike before the floors
        arrive) — clear the mark everywhere, or it replays into every
        comm registered afterwards (a plain member receives no
        heartbeats from the proc, so the live-heartbeat self-heal
        never fires for it)."""
        for k, v in (incs or {}).items():
            k, v = int(k), int(v)
            self.incarnations[k] = max(v, self.incarnations.get(k, 0))
            if k == self.proc:
                continue
            if v > 0:
                # the boot's eager gather raced the fellow reborn's
                # re-publish: refresh from the incarnation-suffixed
                # key (authoritative for the reborn lineage) so sends
                # stop dialing the corpse endpoint
                try:
                    addr = self.kvs.get(f"{self.ns}dcn.{k}.i{v}",
                                        wait=False)
                    if addr:
                        self.engine.update_address(k, addr)
                except (KeyError, ConnectionError, OSError):
                    pass
            if self.detector is None:
                continue
            if k in self.detector.failed() or self.engine.proc_failed(k):
                self.engine.note_proc_recovered(k, incarnation=v)
            else:
                self.detector.note_incarnation(k, v)

    def await_respawn(self, root_proc: int, timeout: float) -> tuple[int, str]:
        """Block until a NEW incarnation of ``root_proc`` (> the last
        one we integrated) has re-published its endpoint; returns
        (incarnation, address).  The restart leg's rendezvous: tpurun
        --respawn relaunches the rank, whose boot publishes
        ``inc.<proc>`` and ``dcn.<proc>.i<k>`` (see __init__)."""
        import time

        last = self.incarnations.get(root_proc, 0)
        deadline = time.monotonic() + float(timeout)
        while True:
            try:
                inc = int(self.kvs.get(f"{self.ns}inc.{root_proc}",
                                       wait=False))
            except KeyError:
                inc = 0
            if inc > last:
                break
            if time.monotonic() > deadline:
                from ompi_tpu.core.errors import MPIProcFailedError

                raise MPIProcFailedError(
                    f"replace: no respawned incarnation of proc "
                    f"{root_proc} within ft_respawn_timeout={timeout}s "
                    f"(launched without tpurun --respawn, or the rank "
                    f"exhausted --max-respawns?)")
            time.sleep(0.05)
        address = self.kvs.get(
            f"{self.ns}dcn.{root_proc}.i{inc}",
            timeout=max(1.0, deadline - time.monotonic()))
        self.incarnations[root_proc] = inc
        return inc, address

    def fence(self, name: str) -> None:
        self.kvs.fence(f"{self.ns}{name}", self.proc, self.nprocs)

    def close(self) -> None:
        if self.detector is not None:
            self.detector.close()
        self.engine.close()
        self.kvs.close()
