"""Per-process bootstrap — rank/size/rendezvous from the environment.

≈ ``ess`` (environment-specific services) + the PMIx client init +
modex of SURVEY.md §3.2: a worker launched by ``tpurun`` reads its
process index and the coordinator address from env vars, connects the
KVS, publishes its DCN endpoint (``PMIx_Put`` + ``PMIx_Commit``),
fences, and collects peer endpoints (lazy ``PMIx_Get`` collapsed to an
eager exchange — process counts are small).
"""

from __future__ import annotations

import os

from ompi_tpu.dcn.collops import DcnCollEngine
from .kvs import KVSClient

ENV_PROC = "OMPI_TPU_PROC"
ENV_NPROCS = "OMPI_TPU_NPROCS"
ENV_KVS = "OMPI_TPU_KVS_ADDR"
#: KVS key namespace — spawned child worlds share the job's KVS server
#: but live under their own prefix (dynamic process management)
ENV_NS = "OMPI_TPU_KVS_NS"
#: rebirth counter (tpurun --respawn): 0 on first launch; a respawned
#: worker replays the boot rendezvous under a bumped incarnation so
#: survivors can distinguish the reborn endpoint from the corpse's
ENV_INCARNATION = "OMPI_TPU_INCARNATION"
#: set by tpurun when the job maps ranks onto remote hosts (the
#: plm/rsh leg): a remote respawn pays the launch-agent round-trip on
#: top of the boot, so every await-respawn deadline switches from
#: ft_respawn_timeout to ft_remote_respawn_timeout
ENV_RSH = "OMPI_TPU_RSH"


def respawn_timeout(store) -> float:
    """The await-respawn deadline (replace(), the reborn rejoin grace,
    the serve repair wait): ``ft_remote_respawn_timeout`` on the rsh
    leg (:data:`ENV_RSH`), ``ft_respawn_timeout`` locally."""
    if os.environ.get(ENV_RSH):
        return float(
            store.get("ft_remote_respawn_timeout", 120.0) or 120.0)
    return float(store.get("ft_respawn_timeout", 60.0) or 60.0)


def launched_by_tpurun() -> bool:
    return ENV_PROC in os.environ


class ProcContext:
    """This process's place in a tpurun job."""

    def __init__(self):
        self.proc = int(os.environ[ENV_PROC])
        self.nprocs = int(os.environ[ENV_NPROCS])
        self.ns = os.environ.get(ENV_NS, "")
        #: elastic recovery state: this process's rebirth count, the
        #: highest incarnation we know per peer (replace() polls past
        #: it), and whether a reborn process has rejoined the job yet
        self.incarnation = int(os.environ.get(ENV_INCARNATION, "0"))
        self.incarnations: dict[int, int] = {}
        self.rejoined = self.incarnation == 0
        self.kvs = KVSClient(os.environ[ENV_KVS])
        # modex: publish DCN endpoint, fence, gather peers. Transport
        # tunables come from the btl/tcp component's MCA vars (so
        # --mca btl_tcp_eager_limit etc. behave as in the reference).
        from ompi_tpu.core import mca
        from ompi_tpu.core.registry import ComponentError

        ctx = mca.default_context()
        fw = ctx.framework("btl")
        # open() first: a mistyped explicit include (--mca btl tpc) must
        # abort here, as the reference does — only AFTER a clean open is
        # "no component" a legitimate state (^tcp exclusion)
        fw.open()
        try:
            comp = fw.select_one()
        except ComponentError:
            params = {}  # btl excluded (^tcp) → transport defaults
        else:
            # bad --mca btl_tcp_* values propagate (the reference
            # aborts on unparseable MCA values; so do we)
            params = comp.params(ctx.store)
        self.engine = self._make_engine(params)
        self.kvs.put(f"{self.ns}dcn.{self.proc}", self.engine.transport.address)
        if self.incarnation:
            # rebirth rendezvous: the incarnation-suffixed address key
            # plus the incarnation beacon survivors' replace() polls —
            # the plain dcn.<proc> key still holds the CORPSE's address
            # in their caches until replace() refreshes it
            self.kvs.put(f"{self.ns}dcn.{self.proc}.i{self.incarnation}",
                         self.engine.transport.address)
            self.kvs.put(f"{self.ns}inc.{self.proc}", self.incarnation)
        # the modex fence is idempotent for a reborn proc (the fence
        # set already contains every rank), so this returns instantly
        # on incarnation > 0 — by design: survivors are mid-job, not
        # waiting at a barrier
        self.kvs.fence(f"{self.ns}modex", self.proc, self.nprocs)
        addresses = [self.kvs.get(f"{self.ns}dcn.{p}")
                     for p in range(self.nprocs)]
        # wire-plane agreement: the published address reveals each
        # peer's plane ("ntv:" = libtpudcn framing).  A mixed job (one
        # host lacking the C++ toolchain, a per-process fallback) must
        # abort HERE with a clear message — native frames against a
        # Python endpoint would otherwise hang the first collective.
        mine = addresses[self.proc].startswith("ntv:")
        mixed = [p for p, a in enumerate(addresses)
                 if a.startswith("ntv:") != mine]
        if mixed:
            from ompi_tpu.core.errors import MPIInternalError

            raise MPIInternalError(
                f"DCN wire-plane mismatch: proc {self.proc} uses the "
                f"{'native' if mine else 'Python'} transport but procs "
                f"{mixed} published the other plane (a host without "
                f"the C++ toolchain?); force one with --mca btl "
                f"tcp|sm|bml on every host"
            )
        self.engine.set_addresses(addresses)
        # failure detector (tpurun --ft / --mca ft_detector_enable 1):
        # heartbeats + gossip; detections fan out to every registered
        # communicator's ULFM state (SURVEY.md §5 failure detection)
        import threading
        import weakref

        self._ft_comms: "weakref.WeakSet" = weakref.WeakSet()
        self._ft_lock = threading.Lock()
        self.detector = None
        from ompi_tpu.ft.detector import FtDetectorComponent, HeartbeatDetector

        ftp = FtDetectorComponent().params(ctx.store)
        if ftp["enable"] and self.nprocs > 1:
            # a reborn proc's peers stay silent toward it until their
            # replace() clears its failed mark — grace the first
            # detection window so the rejoin isn't poisoned by its own
            # detector declaring every survivor dead
            grace = 0.0
            if self.incarnation:
                grace = respawn_timeout(ctx.store)
            self.detector = HeartbeatDetector(
                self.engine, period=ftp["period"], timeout=ftp["timeout"],
                grace=grace,
            )
            self.detector.on_failure(self._fan_out_failure)

    def _make_engine(self, params: dict):
        """Engine selection: the native C++ data plane when the btl
        picked it AND libtpudcn builds on this machine; otherwise the
        Python transports (also the fallback when the toolchain is
        absent — same graceful degradation as a reference build
        without a btl's prerequisites)."""
        params = dict(params)
        if params.get("transport") == "native":
            params.pop("transport")
            try:
                from ompi_tpu.dcn import native as dcn_native

                if dcn_native.available():
                    return dcn_native.NativeDcnEngine(
                        self.proc, self.nprocs, **params)
            except Exception as e:  # noqa: BLE001 — degrade, loudly
                import sys

                print(
                    f"[ompi_tpu] native data plane unavailable "
                    f"({type(e).__name__}: {e}); falling back to the "
                    f"Python bml transport", file=sys.stderr,
                )
            params.pop("ring_bytes", None)
            params["transport"] = "bml"
        params.pop("ring_bytes", None)
        return DcnCollEngine(self.proc, self.nprocs, **params)

    def _fan_out_failure(self, root_proc: int) -> None:
        with self._ft_lock:  # registration races the detector thread
            comms = list(self._ft_comms)
        for comm in comms:
            comm._on_proc_failed(root_proc)

    def register_comm(self, comm) -> None:
        """Track a MultiProcComm for failure fan-out; replay known
        failures so comms created post-failure start consistent."""
        with self._ft_lock:
            self._ft_comms.add(comm)
        if self.detector is not None:
            for p in self.detector.failed():
                comm._on_proc_failed(p)

    def await_respawn(self, root_proc: int, timeout: float) -> tuple[int, str]:
        """Block until a NEW incarnation of ``root_proc`` (> the last
        one we integrated) has re-published its endpoint; returns
        (incarnation, address).  The restart leg's rendezvous: tpurun
        --respawn relaunches the rank, whose boot publishes
        ``inc.<proc>`` and ``dcn.<proc>.i<k>`` (see __init__)."""
        import time

        last = self.incarnations.get(root_proc, 0)
        deadline = time.monotonic() + float(timeout)
        while True:
            try:
                inc = int(self.kvs.get(f"{self.ns}inc.{root_proc}",
                                       wait=False))
            except KeyError:
                inc = 0
            if inc > last:
                break
            if time.monotonic() > deadline:
                from ompi_tpu.core.errors import MPIProcFailedError

                raise MPIProcFailedError(
                    f"replace: no respawned incarnation of proc "
                    f"{root_proc} within ft_respawn_timeout={timeout}s "
                    f"(launched without tpurun --respawn, or the rank "
                    f"exhausted --max-respawns?)")
            time.sleep(0.05)
        address = self.kvs.get(
            f"{self.ns}dcn.{root_proc}.i{inc}",
            timeout=max(1.0, deadline - time.monotonic()))
        self.incarnations[root_proc] = inc
        return inc, address

    def fence(self, name: str) -> None:
        self.kvs.fence(f"{self.ns}{name}", self.proc, self.nprocs)

    def close(self) -> None:
        if self.detector is not None:
            self.detector.close()
        self.engine.close()
        self.kvs.close()
