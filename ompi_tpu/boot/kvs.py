"""Rendezvous key-value store + fence — the PMIx-equivalent.

≈ the PMIx client/server pair (``PMIx_Put``/``PMIx_Commit``/
``PMIx_Fence``/``PMIx_Get``, SURVEY.md §2.7, §3.2): the out-of-band
bootstrap every distributed job needs for rank wire-up.  The launcher
(``tpurun``, ≈ mpirun hosting the PMIx server) runs :class:`KVSServer`;
every worker process connects a :class:`KVSClient` (address from the
environment, like the PMIx unix-socket handshake) and performs the
modex dance: put its DCN endpoint, fence, get peers lazily.

Wire protocol: length-prefixed JSON frames over TCP — tiny control
traffic only (endpoints, fence counters), never bulk data.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Any


def _send_frame(sock: socket.socket, obj: Any) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack("!I", len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("kvs peer closed")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Any:
    (n,) = struct.unpack("!I", _recv_exact(sock, 4))
    return json.loads(_recv_exact(sock, n).decode())


class KVSServer:
    """Single-threaded-per-connection KVS + fence counter server."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._data: dict[str, Any] = {}
        self._fences: dict[str, set[int]] = {}
        self._cond = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.address = "%s:%d" % self._sock.getsockname()
        self._running = True
        self._threads: list[threading.Thread] = []
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                msg = _recv_frame(conn)
                op = msg["op"]
                if op == "put":
                    with self._cond:
                        self._data[msg["key"]] = msg["value"]
                        self._cond.notify_all()
                    _send_frame(conn, {"ok": True})
                elif op == "get":
                    timeout = msg.get("timeout", 30.0)
                    deadline = time.monotonic() + timeout
                    with self._cond:
                        while msg["key"] not in self._data:
                            left = deadline - time.monotonic()
                            if left <= 0 or not msg.get("wait", True):
                                break
                            self._cond.wait(left)
                        val = self._data.get(msg["key"])
                        found = msg["key"] in self._data
                    _send_frame(conn, {"ok": found, "value": val})
                elif op == "get_prefix":
                    # bulk scan (the sharded-modex leg: one group
                    # leader pulls every 'dcn.' endpoint in ONE op
                    # instead of P ranks each issuing P-1 gets)
                    with self._cond:
                        pfx = msg["prefix"]
                        out = {k: v for k, v in self._data.items()
                               if k.startswith(pfx)}
                    _send_frame(conn, {"ok": True, "value": out})
                elif op == "fence":
                    name, rank, size = msg["name"], msg["rank"], msg["size"]
                    deadline = time.monotonic() + msg.get("timeout", 120.0)
                    with self._cond:
                        self._fences.setdefault(name, set()).add(rank)
                        self._cond.notify_all()
                        while len(self._fences[name]) < size:
                            left = deadline - time.monotonic()
                            if left <= 0:
                                _send_frame(conn, {"ok": False, "error": "fence timeout"})
                                break
                            self._cond.wait(left)
                        else:
                            _send_frame(conn, {"ok": True})
                elif op == "shutdown":
                    _send_frame(conn, {"ok": True})
                    return
                else:
                    _send_frame(conn, {"ok": False, "error": f"bad op {op}"})
        except (ConnectionError, OSError):
            return

    def peek(self, key: str, default: Any = None) -> Any:
        """In-process non-blocking read (the launcher/daemon side owns
        the server object, so it need not dial its own socket to poll
        job-completion keys)."""
        with self._cond:
            return self._data.get(key, default)

    def put_local(self, key: str, value: Any) -> None:
        """In-process put (the daemon publishes job directives on the
        same store the workers' KVSClients read)."""
        with self._cond:
            self._data[key] = value
            self._cond.notify_all()

    def seed_fence(self, name: str, ranks) -> None:
        """Pre-populate a fence set (daemon restart recovery): the
        original boot's fences died with the crashed daemon's server,
        but a future respawned rank still replays them — seeding the
        full rank set keeps those replays instant instead of a
        120-second timeout against an empty set."""
        with self._cond:
            self._fences.setdefault(name, set()).update(int(r) for r in ranks)
            self._cond.notify_all()

    def close(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass


class KVSClient:
    """Worker-side handle (≈ the PMIx client)."""

    def __init__(self, address: str):
        self._lock = threading.Lock()
        #: per-op call counters — the boot-scaling signature the np≥16
        #: scale soak asserts on (sharded modex: per-rank 'get' stays
        #: O(1)+lazy instead of P−1)
        self.ops: dict[str, int] = {}
        self._dial(address)

    def _dial(self, address: str) -> None:
        host, port = address.rsplit(":", 1)
        self.address = address
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.connect((host, int(port)))

    def reconnect(self, address: str) -> None:
        """Re-point this client at a NEW server (tpud restart
        re-adoption: the reborn daemon's KVS lives at a fresh port).
        Raises like a normal dial on failure; the old socket is closed
        either way."""
        with self._lock:
            try:
                self._sock.close()
            except OSError:
                pass
            self._dial(address)

    def _call(self, msg: Any) -> Any:
        with self._lock:
            op = msg.get("op", "?")
            self.ops[op] = self.ops.get(op, 0) + 1
            _send_frame(self._sock, msg)
            return _recv_frame(self._sock)

    def put(self, key: str, value: Any) -> None:
        r = self._call({"op": "put", "key": key, "value": value})
        if not r.get("ok"):
            raise ConnectionError(f"kvs put failed: {r}")

    def get(self, key: str, wait: bool = True, timeout: float = 30.0) -> Any:
        r = self._call({"op": "get", "key": key, "wait": wait, "timeout": timeout})
        if not r.get("ok"):
            raise KeyError(key)
        return r["value"]

    def get_prefix(self, prefix: str) -> dict[str, Any]:
        """Bulk non-blocking scan of every key under ``prefix`` (≈ the
        PMIx "instant-on" rack-scale modex pull): one wire round-trip
        however many keys match."""
        r = self._call({"op": "get_prefix", "prefix": prefix})
        if not r.get("ok"):
            raise ConnectionError(f"kvs get_prefix failed: {r}")
        return dict(r["value"] or {})

    def fence(self, name: str, rank: int, size: int, timeout: float = 120.0) -> None:
        """Collective barrier over all ranks (≈ PMIx_Fence)."""
        r = self._call(
            {"op": "fence", "name": name, "rank": rank, "size": size, "timeout": timeout}
        )
        if not r.get("ok"):
            raise TimeoutError(f"fence {name!r} failed: {r.get('error')}")

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
