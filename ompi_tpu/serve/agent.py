"""Per-host launch agent — the daemon's remote arm (≈ prted under
``prte``: the DVM member that owns one host's processes).

``tpurun --daemon`` with a host map spawns ONE agent per remote host
over the plm/rsh leg (the same ``--launch-agent`` template a plain
rsh job uses).  The agent owns everything that requires a shared pid
namespace with the workers — exactly what the daemon physically
cannot do across hosts (``kill 0`` / ``_AdoptedProc`` are local-only,
ROADMAP serving item (d)):

* **spawn/respawn**: the daemon publishes commands on a per-session
  KVS stream (``serve.agent.cmd.<session>.<hid>.<n>``); the agent
  consumes them strictly in order and acks each
  (``serve.agent.ack.<session>.<hid>.<n>`` carries the worker pid) —
  spawn, adopt (agent restart with live workers), kill, stop;
* **pid liveness**: the agent polls its workers and reports their
  state in a periodic heartbeat record (``serve.agent.hb.<hid>``);
  the daemon's monitor reads worker death, respawn progress, and
  agent health from it — per-host agent health is one line on
  ``tools/top.py``;
* **stdio**: worker output pipes into the agent, which forwards it
  (rank-prefixed) up its own rsh pipe to the daemon's iof.

**Daemon crash-safety** (the agent half, mirroring the worker's
:class:`~ompi_tpu.serve.worker.DaemonLink`): the control channel is
the daemon's KVS, so a daemon SIGKILL severs it.  The agent keeps its
workers running (they serve the in-flight job worker-to-worker),
parks on the pidfile for a restarted daemon at a higher generation,
re-dials its KVS, offers ``serve.agent.adopt.<hid>`` (current worker
table included), awaits the ack — which names the NEW command
session — and resumes.  No restarted daemon within the window: the
agent exits; the workers self-terminate through their own re-attach
expiry (no orphans, ever).

An agent that itself dies (host failure takes workers AND agent) is
respawned by the daemon over rsh with the last-known worker table
baked into its environment: the reborn agent probes those pids and
**re-adopts the still-live workers** (agent-only death) or reports
them dead so the daemon drives the normal respawn+repair leg (whole-
host death).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time

from ompi_tpu.boot.kvs import KVSClient
from ompi_tpu.boot.proc import ENV_INCARNATION, ENV_KVS, ENV_NPROCS
from ompi_tpu.faultsim import core as _fsim
from . import state as _state
from .worker import ENV_SERVE_PIDFILE, _PipeSafe, reaim_stdio

#: KVS key prefixes of the agent protocol (daemon mirrors these)
K_AHB = "serve.agent.hb."        # + <hid>               → heartbeat
K_ACMD = "serve.agent.cmd."      # + <session>.<hid>.<n> → command
K_AACK = "serve.agent.ack."      # + <session>.<hid>.<n> → ack
K_AADOPT = "serve.agent.adopt."  # + <hid>               → adoption offer
K_AADOPTED = "serve.agent.adopted."  # + <hid>           → daemon's ack
K_PIDFILE = "serve.pidfile."     # + <generation>  → pidfile-record
#: beacon (keep in sync with serve/daemon.py): the daemon mirrors its
#: pidfile record into the KVS so agents on hosts WITHOUT the daemon's
#: filesystem can copy it to their local pidfile path — the real-remote
#: re-attach channel (workers there poll the local copy as usual)
K_ASESSION = "serve.agent.session."  # + <hid> → the daemon's CURRENT
#: command session for the host — the supersession fence: an agent
#: whose session no longer matches was given up on (wedged past
#: serve_agent_timeout) and replaced; it must exit instead of
#: un-wedging later and executing its old session's spawn commands
#: (a double-spawned rank)

#: agent-side environment (daemon bakes these into the rsh payload —
#: all OMPI_TPU_-prefixed so _remote_cmd carries them)
ENV_AGENT_HOST = "OMPI_TPU_AGENT_HOST"        # host index
ENV_AGENT_RANKS = "OMPI_TPU_AGENT_RANKS"      # comma rank list
ENV_AGENT_SESSION = "OMPI_TPU_AGENT_SESSION"  # command-stream session
ENV_AGENT_ADOPT = "OMPI_TPU_AGENT_ADOPT"      # r:pid:inc,... last known


def _parse_adopt(raw: str) -> dict[int, tuple[int, int]]:
    """``rank:pid:incarnation,...`` → {rank: (pid, incarnation)}."""
    out: dict[int, tuple[int, int]] = {}
    for part in (raw or "").split(","):
        bits = part.split(":")
        if len(bits) == 3:
            try:
                out[int(bits[0])] = (int(bits[1]), int(bits[2]))
            except ValueError:
                continue
    return out


class _Worker:
    """One owned rank: a Popen child, or an adopted bare pid (agent
    restart found it alive)."""

    def __init__(self, rank: int, incarnation: int,
                 proc: subprocess.Popen | None = None, pid: int = 0):
        self.rank = int(rank)
        self.incarnation = int(incarnation)
        self.proc = proc
        self.pid = int(proc.pid if proc is not None else pid)
        self.rc: int | None = None

    def poll(self) -> int | None:
        if self.rc is not None:
            return self.rc
        if self.proc is not None:
            rc = self.proc.poll()
            if rc is not None:
                self.rc = int(rc)
        elif not _state.pid_alive(self.pid):
            # adopted (non-child): the real code reaped to init — a
            # synthetic nonzero is all the respawn machinery needs
            self.rc = 1
        return self.rc

    def signal(self, sig: int) -> None:
        try:
            os.kill(self.pid, sig)
        except OSError:
            pass


class LaunchAgent:
    """The per-host agent process body (``python -m
    ompi_tpu.serve.agent``)."""

    def __init__(self) -> None:
        self.hid = int(os.environ[ENV_AGENT_HOST])
        self.np = int(os.environ[ENV_NPROCS])
        self.ranks = [int(r) for r in
                      os.environ[ENV_AGENT_RANKS].split(",") if r]
        self.session = os.environ.get(ENV_AGENT_SESSION, "g1s0")
        self.pidfile = os.environ.get(ENV_SERVE_PIDFILE, "")
        info = (_state.read_pidfile(self.pidfile)
                if self.pidfile else None)
        self.generation = int((info or {}).get("generation", 0))
        self.kvs_addr = os.environ[ENV_KVS]
        self.kvs = KVSClient(self.kvs_addr)
        self.cursor = 0
        self.cmds_done = 0
        #: executed-but-unacked command results awaiting a KVS re-put
        #: (see _consume/_flush_acks)
        self._ack_backlog: list[tuple[str, str, dict]] = []
        self.workers: dict[int, _Worker] = {}
        self._threads: list[threading.Thread] = []
        self._stop = False
        # knobs (resolved from the inherited OMPI_MCA_* environment —
        # the agent has no --mca line of its own)
        from ompi_tpu.core import mca as _mca

        store = _mca.default_context().store
        self.poll = max(0.02, int(
            store.get("serve_agent_poll_ms", 50) or 50) / 1000.0)
        self.hb_interval = max(0.05, int(
            store.get("serve_agent_hb_ms", 500) or 500) / 1000.0)
        self.window = float(
            store.get("serve_reattach_timeout", 30.0) or 30.0)
        if bool(store.get("faultsim_enable", False)):
            # deterministic agent chaos (agentkill:at=N, site "agent"):
            # one seed replays one agent-death schedule; the proc key
            # offsets by host so two agents under one seed diverge
            _fsim.configure(str(store.get("faultsim_plan", "") or ""),
                            seed=int(store.get("faultsim_seed", 0) or 0),
                            proc=1000 + self.hid)
        # agent restart with a last-known worker table: adopt the
        # still-live pids, report the dead ones in the heartbeat (the
        # daemon drives their respawn through normal commands)
        for r, (pid, inc) in _parse_adopt(
                os.environ.get(ENV_AGENT_ADOPT, "")).items():
            if r not in self.ranks or pid <= 0:
                continue
            w = _Worker(r, inc, pid=pid)
            if not _state.pid_alive(pid):
                w.rc = 1
            else:
                print(f"agent h{self.hid}: re-adopted worker rank {r} "
                      f"(pid {pid})", flush=True)
            self.workers[r] = w

    # -- worker lifecycle ------------------------------------------------

    def _spawn_worker(self, rank: int, incarnation: int,
                      telemetry: str | None = None) -> _Worker:
        from ompi_tpu.boot.tpurun import _forward, worker_env

        # telemetry ingest address from the COMMAND, not the inherited
        # env: after a daemon restart the agent's environment still
        # names the dead predecessor's ingest port, and a worker born
        # pointing there would publish into the void forever
        env = worker_env(rank, self.np, self.kvs_addr,
                         telemetry_addr=telemetry)
        if incarnation:
            env[ENV_INCARNATION] = str(incarnation)
        p = subprocess.Popen(
            [sys.executable, "-m", "ompi_tpu.serve.worker"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

        def _fwd(stream=p.stdout, prefix=str(rank)):
            try:
                _forward(stream, prefix, sys.stdout.buffer)
            except (OSError, ValueError):
                pass  # daemon pipe died: the worker re-aims itself

        t = threading.Thread(target=_fwd, daemon=True)
        t.start()
        self._threads.append(t)
        print(f"agent h{self.hid}: spawned rank {rank} pid {p.pid} "
              f"(incarnation {incarnation})", flush=True)
        return _Worker(rank, incarnation, proc=p)

    def _worker_table(self) -> dict:
        out = {}
        for r, w in self.workers.items():
            rc = w.poll()
            out[str(r)] = {"pid": w.pid, "incarnation": w.incarnation,
                           "alive": rc is None,
                           "rc": rc if rc is not None else 0}
        return out

    # -- control channel -------------------------------------------------

    def _beacon_gen(self) -> int:
        """The generation this agent's command session was minted
        under (``g<gen>s<n>``) — more reliable than the local pidfile
        copy, which may not exist yet on a host that shares no
        filesystem with the daemon."""
        try:
            return int(self.session.lstrip("g").split("s", 1)[0])
        except ValueError:
            return self.generation

    def _mirror_beacon(self) -> None:
        """Real-remote re-attach channel: copy the daemon's pidfile-
        record beacon (``serve.pidfile.<generation>``) to THIS host's
        pidfile path, so the workers here — and this agent itself —
        re-attach through the ordinary local pidfile poll without ever
        reading daemon-local disk.  A reborn agent (respawned over rsh
        by a restarted daemon, new KVS address in its env) mirrors the
        NEW record, which is how parked workers on the host learn the
        restarted daemon's address.  Beacon absent (older daemon):
        no-op — the plain pidfile poll stands.  On a shared
        filesystem the mirror compares equal and never writes."""
        if not self.pidfile:
            return
        gen = max(self._beacon_gen(), self.generation)
        try:
            rec = self.kvs.get(f"{K_PIDFILE}{gen}", wait=False)
        except KeyError:
            return
        if not isinstance(rec, dict):
            return
        if _state.read_pidfile(self.pidfile) != rec:
            try:
                _state.write_pidfile(self.pidfile, dict(rec))
                self.generation = int(rec.get("generation", gen))
                print(f"agent h{self.hid}: mirrored daemon pidfile "
                      f"beacon (generation {self.generation}) to "
                      f"{self.pidfile}", flush=True)
            except OSError:
                pass  # unwritable path: the poll fallback stands

    def _hb(self) -> None:
        # supersession fence (checked at heartbeat cadence): a daemon
        # that rotated this host's session replaced us — a wedged
        # agent that un-wedges here must NOT go on to execute its old
        # session's commands (the replacement already re-issued them)
        try:
            current = self.kvs.get(f"{K_ASESSION}{self.hid}",
                                   wait=False)
        except KeyError:
            current = None
        if current is not None and str(current) != self.session:
            print(f"agent h{self.hid}: superseded (daemon session "
                  f"{current} != mine {self.session}); exiting — "
                  "live workers stay for the replacement's adoption",
                  flush=True)
            raise SystemExit(0)
        self.kvs.put(f"{K_AHB}{self.hid}", {
            "pid": os.getpid(), "host": self.hid,
            "generation": self.generation, "session": self.session,
            "ts_ns": time.time_ns(), "cmds_done": self.cmds_done,
            "workers": self._worker_table()})
        # heartbeat cadence keeps the local pidfile mirror fresh (a
        # just-adopted agent re-mirrors under its new generation)
        self._mirror_beacon()

    def _exec(self, cmd: dict) -> dict:
        if _fsim._enabled:
            for _r in _fsim.actions("agent", kinds={"agentkill"}):
                print(f"agent h{self.hid}: faultsim: injected agent "
                      "kill (agentkill)", flush=True)
                sys.stdout.flush()
                os.kill(os.getpid(), signal.SIGKILL)
        kind = cmd.get("kind")
        if kind == "spawn":
            r, inc = int(cmd["rank"]), int(cmd.get("incarnation", 0))
            w = self.workers.get(r)
            if w is not None and w.incarnation == inc \
                    and w.poll() is None:
                # idempotent: the daemon re-issues unacked spawn
                # commands after an agent reattach/respawn — a worker
                # already running at this incarnation must be ACKED,
                # not double-spawned (the first process would be
                # orphaned outside every workers table)
                return {"ok": True, "rank": r, "pid": w.pid,
                        "incarnation": inc}
            self.workers[r] = self._spawn_worker(
                r, inc, telemetry=cmd.get("telemetry"))
            return {"ok": True, "rank": r, "pid": self.workers[r].pid,
                    "incarnation": inc}
        if kind == "adopt":
            r = int(cmd["rank"])
            pid = int(cmd.get("pid", 0))
            inc = int(cmd.get("incarnation", 0))
            w = _Worker(r, inc, pid=pid)
            if pid <= 0 or not _state.pid_alive(pid):
                w.rc = 1
            self.workers[r] = w
            return {"ok": True, "rank": r, "pid": pid,
                    "alive": w.rc is None}
        if kind == "kill":
            r = int(cmd["rank"])
            w = self.workers.get(r)
            if w is not None:
                w.signal(int(cmd.get("sig", signal.SIGTERM)))
            return {"ok": True, "rank": r}
        if kind == "stop":
            self._stop = True
            return {"ok": True}
        return {"ok": False, "error": f"unknown agent command {kind!r}"}

    def _consume(self) -> bool:
        """One command, if pending (non-blocking).  True = consumed."""
        key = f"{K_ACMD}{self.session}.{self.hid}.{self.cursor}"
        try:
            cmd = self.kvs.get(key, wait=False)
        except KeyError:
            return False
        idx, self.cursor = self.cursor, self.cursor + 1
        try:
            ack = self._exec(dict(cmd))
        except Exception as e:  # noqa: BLE001 — an execution failure
            # (fork EAGAIN/ENOMEM...) must ACK a failure, not bubble
            # into the run loop's KVS-loss handler: the cursor already
            # advanced, and an un-acked spawn would wedge its rank
            # "alive with no process" forever — the failure ack routes
            # it down the daemon's bounded respawn leg instead
            ack = {"ok": False, "rank": cmd.get("rank"),
                   "error": f"{type(e).__name__}: {e}"}
        self.cmds_done += 1
        # ack-after-exec: a KVS loss here must not drop the ack (the
        # command already ran — an unacked executed spawn would be
        # re-issued into the next session; the idempotent-spawn guard
        # covers re-issues to THIS process, the replay covers the
        # transient-put case).  Parked acks flush at the loop top;
        # a session change discards them (the daemon re-issues).
        self._ack_backlog.append(
            (self.session, f"{K_AACK}{self.session}.{self.hid}.{idx}",
             ack))
        self._flush_acks()
        return True

    def _flush_acks(self) -> None:
        while self._ack_backlog:
            session, key, ack = self._ack_backlog[0]
            if session != self.session:
                self._ack_backlog.pop(0)  # dead session: superseded
                continue
            self.kvs.put(key, ack)  # ConnectionError → reattach path
            self._ack_backlog.pop(0)

    # -- crash → re-attach (daemon restart) ------------------------------

    def _reaim_logs(self, info: dict) -> None:
        """Per-agent stdio re-aim (the PR 13 recorded edge): the
        worker's re-attach protocol, aimed at the per-agent log file
        named by the restarted daemon's pidfile record, so post-
        reattach spawn/heartbeat/adoption output is durable."""
        reaim_stdio(str((info or {}).get("logs") or ""),
                    f"agent.h{self.hid}.log", f"agent h{self.hid}")

    def _reattach(self) -> None:
        if not self.pidfile:
            print(f"agent h{self.hid}: daemon gone and no pidfile; "
                  "exiting (workers self-terminate through their own "
                  "re-attach windows)", flush=True)
            raise SystemExit(0)
        deadline = time.monotonic() + self.window
        print(f"agent h{self.hid}: daemon lost; parking up to "
              f"{self.window:.0f}s on {self.pidfile}", flush=True)
        while True:
            info = _state.read_pidfile(self.pidfile)
            alive = bool(info) and _state.pid_alive(
                int(info.get("pid", 0)))
            # skip a restarting daemon's provisional claim record (no
            # KVS yet, predecessor's generation) — same hazard as the
            # worker's park loop: KeyError('kvs') killed the agent
            ready = alive and _state.pidfile_ready(info)
            gen = int((info or {}).get("generation", 0))
            if ready and gen == self.generation:
                try:
                    self.kvs.reconnect(info["kvs"])
                    self.kvs_addr = info["kvs"]
                    print(f"agent h{self.hid}: KVS re-dialed (daemon "
                          "alive)", flush=True)
                    return
                except OSError:
                    pass
            elif ready and gen > self.generation:
                try:
                    self.kvs.reconnect(info["kvs"])
                    self.kvs_addr = info["kvs"]
                    self.kvs.put(f"{K_AADOPT}{self.hid}", {
                        "pid": os.getpid(), "host": self.hid,
                        "generation": gen,
                        "workers": self._worker_table()})
                    t0 = time.monotonic()
                    while time.monotonic() - t0 < 10.0:
                        try:
                            ack = self.kvs.get(
                                f"{K_AADOPTED}{self.hid}", wait=False)
                        except KeyError:
                            ack = None
                        if (ack and int(ack.get("pid", -1))
                                == os.getpid()
                                and int(ack.get("generation", 0))
                                == gen):
                            self.generation = gen
                            self.session = str(
                                ack.get("session", f"g{gen}s0"))
                            self.cursor = 0
                            # the predecessor's rsh pipe died with it:
                            # make post-adoption output durable
                            self._reaim_logs(info)
                            print(f"agent h{self.hid}: re-attached to "
                                  f"daemon generation {gen} (session "
                                  f"{self.session})", flush=True)
                            return
                        time.sleep(0.05)
                except (OSError, ConnectionError):
                    pass
            if time.monotonic() > deadline:
                print(f"agent h{self.hid}: no restarted daemon within "
                      f"{self.window:.0f}s; exiting", flush=True)
                raise SystemExit(0)
            time.sleep(0.25)

    # -- main loop -------------------------------------------------------

    def run(self) -> int:
        print(f"agent h{self.hid}: up (pid {os.getpid()}, ranks "
              f"{self.ranks}, session {self.session})", flush=True)
        last_hb = 0.0
        while True:
            try:
                self._flush_acks()
                progressed = self._consume()
                now = time.monotonic()
                if now - last_hb >= self.hb_interval:
                    self._hb()
                    last_hb = now
            except (ConnectionError, OSError):
                self._reattach()
                last_hb = 0.0
                continue
            if self._stop:
                break
            if not progressed:
                time.sleep(self.poll)
        # stop: SIGTERM the remaining workers, give them a bounded
        # window for their own exit hygiene, then make sure (the
        # no-orphans contract is the agent's on this host)
        live = [w for w in self.workers.values() if w.poll() is None]
        for w in live:
            w.signal(signal.SIGTERM)
        deadline = time.monotonic() + 10.0
        for w in live:
            while w.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if w.poll() is None:
                w.signal(signal.SIGKILL)
        try:
            self._hb()  # final state for the daemon's shutdown sweep
        except (ConnectionError, OSError):
            pass
        print(f"agent h{self.hid}: stopped", flush=True)
        return 0


def main() -> int:
    # the agent's stdout rides the rsh pipe into the daemon — writes
    # must survive a SIGKILLed daemon exactly like a worker's
    sys.stdout = _PipeSafe(sys.stdout)
    sys.stderr = _PipeSafe(sys.stderr)
    return LaunchAgent().run()


if __name__ == "__main__":
    sys.exit(main())
