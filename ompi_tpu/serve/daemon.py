"""``tpud`` — the persistent serving daemon (≈ orted/prted).

One daemon process owns the standing infrastructure a ``tpurun`` job
normally builds and discards per invocation:

* the boot **KVS** (rendezvous server) — resident workers boot against
  it once and then treat it as the job stream: the daemon publishes
  numbered directives (``serve.job.<n>``), workers long-poll them and
  answer with completion records (``serve.done.<n>.<proc>``);
* the **live-telemetry aggregator** — always on; its HTTP endpoint is
  the daemon's ops surface (``/submit``, ``/jobs``, ``/job/<id>``,
  ``/drain``, ``/shutdown``, ``/scale`` mounted next to the PR-5
  ``/metrics``/``/json``/``/history`` scrape endpoints), and its
  queue-depth/health feeds drive admission and scheduling;
* N **resident rank workers** (``ompi_tpu.serve.worker``) whose DCN
  endpoints — both planes — engine threads, and compiled collective
  state stay warm across jobs;
* the **elastic plane, daemon-fired**: a dead worker is respawned
  under a bumped incarnation and restored by a ``repair`` directive
  (survivors run ``replace()``, the reborn rank rejoins — scale-up),
  and ``/scale`` retires ranks (scale-down) or brings retirees back
  through the same respawn+repair leg.

Scheduling is **gang** FIFO with per-tenant round-robin fairness
(:mod:`~ompi_tpu.serve.queue`): a job is published only when its full
rank-set is free, and never while the mesh is unhealthy (dead worker,
repair outstanding) — the telemetry plane's detector feed gating the
job stream.

**Crash safety** (``serve_pidfile`` arms it, :mod:`~ompi_tpu.serve.
state` holds the substrate): the daemon takes a pidfile lock with
stale-lock takeover and journals the job stream (append-only JSONL)
so a daemon SIGKILL loses nothing durable — a restarted daemon
replays the journal (queued jobs restored, in-flight directives
re-published at their original indices; workers dedup by cursor so a
replayed directive executes exactly once) and **re-adopts** the
still-live resident workers through the warm KVS: workers that lost
their daemon park on the pidfile, re-dial the new KVS, re-publish
their modex keys, and offer ``serve.adopt.<r>`` records the daemon
acks — their mesh, DCN endpoints, and warm CIDs never went away.
Only a rank whose process actually died goes down the respawn+repair
leg.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

from ompi_tpu.boot.kvs import KVSServer
from ompi_tpu.boot.proc import ENV_INCARNATION
from ompi_tpu.boot.tpurun import _forward, _truthy, worker_env
from ompi_tpu.core.var import ENV_PREFIXES, SERVING_VARS, full_var_name
from ompi_tpu.faultsim import core as _fsim
from ompi_tpu.metrics.live import TelemetryAggregator
from . import state as _state
from .queue import AdmissionError, JobQueue

#: KVS key prefixes of the serve protocol (workers mirror these)
K_JOB = "serve.job."        # + <n>            → directive JSON
K_DONE = "serve.done."      # + <n>.<proc>     → completion record
K_RESUME = "serve.resume."  # + <proc>.i<inc>  → reborn worker's cursor
K_ADOPT = "serve.adopt."    # + <proc>         → worker re-adoption offer
K_ADOPTED = "serve.adopted."  # + <proc>       → daemon's adoption ack
K_START = "serve.start."    # + <proc>         → fresh worker's cursor

#: env var carrying the pidfile path to resident workers (their
#: re-attach rendezvous after a daemon crash)
ENV_SERVE_PIDFILE = "OMPI_TPU_SERVE_PIDFILE"


def serve_var(mca: dict | None, name: str):
    """Resolve one ``serve_<name>`` knob daemon-side (no MCA context in
    the launcher process, same as tpurun's telemetry gate): ``--mca``
    dict → ``OMPI_MCA_*`` env → the SERVING_VARS default."""
    full = f"serve_{name}"
    if mca and full in mca:
        return mca[full]
    for prefix in ENV_PREFIXES:
        v = os.environ.get(prefix + full)
        if v is not None:
            return v
    for fw, comp, n, default, _typ, _h in SERVING_VARS:
        if full_var_name(fw, comp, n) == full:
            return default
    raise KeyError(full)


class TpuDaemon:
    """The serving daemon.  ``spawn=False`` builds the full control
    plane (KVS, aggregator, queue, ops routes) without resident
    workers — the selftest/unit harness pumps the job stream itself."""

    def __init__(self, np_: int, mca: dict[str, str] | None = None,
                 cpu_devices: int | None = None, max_respawns: int = 2,
                 http_port: int | None = None, spawn: bool = True):
        self.np = int(np_)
        self.mca = dict(mca or {})
        self.cpu_devices = cpu_devices
        self.max_respawns = int(max_respawns)
        self._spawn_workers = spawn
        self.cid_block = int(serve_var(self.mca, "cid_block"))
        self.cid_next = int(serve_var(self.mca, "cid_base"))
        self.job_timeout = float(serve_var(self.mca, "job_timeout"))
        self.reattach_timeout = float(
            serve_var(self.mca, "reattach_timeout"))
        self._lock = threading.RLock()
        # crash-safe control plane (serve_pidfile arms it): stale-lock
        # takeover + journal replay happen BEFORE any socket exists so
        # a refused second daemon leaves no trace
        self.pidfile = str(serve_var(self.mca, "pidfile") or "")
        self.journal_path = str(serve_var(self.mca, "journal") or "")
        if not self.journal_path and self.pidfile:
            self.journal_path = self.pidfile + ".journal"
        self.generation = 1
        self._journal: _state.Journal | None = None
        recovered: dict | None = None
        if self.pidfile:
            stale = _state.acquire_pidfile(self.pidfile)  # may raise
            if stale is not None:
                print(f"[tpud] reaped stale pidfile {self.pidfile} "
                      f"(pid {stale.get('pid')} dead)", flush=True)
            replay = _state.Journal.replay(self.journal_path)
            self.generation = max(
                replay["generation"],
                int((stale or {}).get("generation", 0))) + 1
            if replay["events"] and not replay["clean"]:
                recovered = replay
        # deterministic chaos (daemonkill): the daemon itself runs
        # under the seeded fault plane when the mca/env arm it — rank
        # workers get the same plan via OMPI_MCA_* inheritance
        if _truthy(self._opt("faultsim_enable")):
            _fsim.configure(str(self._opt("faultsim_plan") or ""),
                            seed=int(self._opt("faultsim_seed") or 0),
                            proc=-1)
        self.server = KVSServer()
        self.aggregator = TelemetryAggregator(
            http_port=(int(serve_var(self.mca, "port"))
                       if http_port is None else int(http_port)))
        self.aggregator.extra_state = self._top_state
        self.url = self.aggregator.url
        self.queue = JobQueue(
            self.np, max_pending=int(serve_var(self.mca, "max_pending")))
        self._mount_routes()
        #: next directive index (the job-stream cursor)
        self.cursor = 0
        #: directive index → bookkeeping ({kind, procs, job_id, done})
        self._outstanding: dict[int, dict] = {}
        #: per-proc worker state: process handle + incarnation + status
        #: in {"active", "adopting", "dead", "retired", "exited"}
        self._procs: list[subprocess.Popen | _AdoptedProc | None] = (
            [None] * self.np)
        self._incarnation = [0] * self.np
        self._status = ["active"] * self.np
        self._threads: list[threading.Thread] = []
        #: procs awaiting the repair directive (respawned, not yet
        #: restored into the world by the survivors' replace())
        self._repairing: set[int] = set()
        self._repair_published = False
        #: re-adoption window state (restart recovery)
        self._adopt_deadline = 0.0
        self._adopt_pids: dict[int, int] = {}
        self.shutting_down = False
        self._shutdown_published = False
        self.exit_code = 0
        self.logdir = (self.pidfile + ".logs") if self.pidfile else ""
        if self.pidfile:
            if self.logdir:
                try:
                    os.makedirs(self.logdir, exist_ok=True)
                except OSError:
                    self.logdir = ""
            _state.write_pidfile(self.pidfile, {
                "pid": os.getpid(), "generation": self.generation,
                "np": self.np, "kvs": self.server.address,
                "url": self.url,
                "ingest": self.aggregator.ingest_address,
                "logs": self.logdir,
                "ts_ns": time.time_ns()})
            if recovered is not None:
                # journal compaction (PR 10 deferred edge): takeover
                # rewrites the journal to the live-state fixed point
                # BEFORE appending, so repeated SIGKILL→restart cycles
                # stop growing it without bound
                _state.Journal.compact(self.journal_path, recovered)
            self._journal = _state.Journal(self.journal_path)
        if recovered is not None:
            self._recover(recovered)
        elif spawn:
            for rank in range(self.np):
                self._procs[rank] = self._spawn(rank)

    def _opt(self, name: str, default: str = "") -> str:
        """Resolve a NON-serve var daemon-side (``--mca`` dict → env →
        default) — the faultsim knobs ride the same launcher-process
        resolution serve_var gives the serve_* set."""
        if name in self.mca:
            return str(self.mca[name])
        for prefix in ENV_PREFIXES:
            v = os.environ.get(prefix + name)
            if v is not None:
                return v
        return default

    def _journal_ev(self, ev: str, **fields) -> None:
        if self._journal is not None:
            self._journal.append(ev, **fields)

    # -- restart recovery (journal replay + worker re-adoption) ---------

    def _recover(self, replay: dict) -> None:
        """Rebuild the control plane a SIGKILLed predecessor dropped:
        restore the queue (queued jobs re-admitted, running jobs
        re-entered), the stream cursor and CID high-water mark,
        re-publish every outstanding directive at its ORIGINAL index
        into the fresh KVS (consumers dedup by cursor — a directive a
        worker already executed is skipped, one it never saw runs:
        exactly once either way), seed the boot fences the old server
        took with it, and open the re-adoption window for the still-
        live resident workers."""
        self._journal_ev("takeover", generation=self.generation,
                         recovered_events=replay["events"])
        # running jobs from the journal lack nothing — the published
        # directive carries procs/cid; merge directive fields over the
        # submit record so queue bookkeeping matches pre-crash state
        by_id = {d.get("id"): d for d in replay["outstanding"].values()
                 if d.get("kind", "job") == "job"}
        running = [dict(job, **{k: by_id[job["id"]][k]
                                for k in ("procs", "cid_base", "cid_span")
                                if k in by_id[job["id"]]})
                   for job in replay["running"] if job["id"] in by_id]
        self.queue.restore(queued=replay["queued"], running=running,
                           done=replay["done"])
        self.cursor = int(replay["cursor"])
        if replay["cid_next"] is not None:
            self.cid_next = max(self.cid_next, int(replay["cid_next"]))
        # the WHOLE stream is re-created at its original indices — NOT
        # via _publish (the cursor must not advance; nothing may be
        # re-journaled or re-counted by the fault plane).  Finished
        # directives are re-published too: workers consume strictly in
        # order, so a hole below a finished index would wedge any
        # worker whose cursor is still beneath it — and re-publication
        # cannot double-execute (a finished directive's whole gang
        # reported, so their cursors are past it; everyone else skips
        # non-member directives by construction)
        for idx in sorted(replay["published"]):
            d = replay["published"][idx]
            if idx in replay["outstanding"]:
                self._outstanding[idx] = {
                    "kind": d.get("kind", "job"),
                    "procs": list(d.get("procs") or range(self.np)),
                    "job_id": d.get("id"), "done": {},
                    "ts": time.monotonic(),
                }
            self.server.put_local(f"{K_JOB}{idx}", d)
        # the boot-time fences died with the old KVS; a future
        # respawned rank still replays them idempotently
        self.server.seed_fence("modex", range(self.np))
        self._adopt_pids = {r: int(st.get("pid", 0))
                            for r, st in replay["pids"].items()}
        for r, st in replay["pids"].items():
            if 0 <= int(r) < self.np:
                self._incarnation[int(r)] = int(st.get("incarnation", 0))
        # crash-mid-repair replay (PR 10 deferred edge): a rank the
        # predecessor respawned whose repair never FINISHED re-enters
        # the repairing set — once adoption resolves the mesh view,
        # the repair directive publishes (or a dead reborn goes down
        # the respawn leg, which re-arms it); an outstanding repair
        # directive also needs its reborn-cursor beacons re-seeded
        # (they died with the old KVS)
        for r in (replay.get("repairing") or {}):
            if 0 <= int(r) < self.np:
                self._repairing.add(int(r))
        for idx, d in replay["outstanding"].items():
            if d.get("kind") == "repair":
                self._repair_published = True
                for r in d.get("dead", ()):
                    self.server.put_local(
                        f"{K_RESUME}{int(r)}.i{self._incarnation[int(r)]}",
                        int(idx) + 1)
        self._status = ["adopting"] * self.np
        for r in replay["retired"]:
            # an operator's /scale-down outlives the crash: a retired
            # rank's dead pid is NOT a crashed worker to respawn
            if 0 <= int(r) < self.np:
                self._status[int(r)] = "retired"
                self._adopt_pids.pop(int(r), None)
        if replay["draining"]:
            self.queue.draining = True  # the drain outlives the crash
        self._adopt_deadline = time.monotonic() + self.reattach_timeout
        print(f"[tpud] restart recovery (generation {self.generation}): "
              f"{len(replay['outstanding'])} in-flight directive(s) "
              f"re-published, {len(replay['queued'])} queued job(s) "
              f"restored, awaiting re-adoption of {self.np} worker(s)",
              flush=True)

    def _poll_adoption(self) -> None:
        """One monitor-tick look at the re-adoption window: a live
        worker that found the new pidfile publishes ``serve.adopt.<r>``
        — verify its pid, take it over (no Popen handle: an
        :class:`_AdoptedProc` wraps the pid), and ack so the worker
        resumes its stream.  A rank whose last known pid is dead is
        respawned once every live rank has re-attached (the reborn
        boot needs the survivors' re-published modex keys)."""
        with self._lock:
            pending = [r for r in range(self.np)
                       if self._status[r] == "adopting"]
            if not pending:
                return
            for r in pending:
                offer = self.server.peek(f"{K_ADOPT}{r}")
                if (offer and int(offer.get("generation", 0))
                        == self.generation
                        and _state.pid_alive(int(offer.get("pid", 0)))):
                    pid = int(offer["pid"])
                    self._procs[r] = _AdoptedProc(pid)
                    self._incarnation[r] = int(
                        offer.get("incarnation", 0))
                    self._status[r] = "active"
                    self._adopt_pids.pop(r, None)
                    self.server.put_local(
                        f"{K_ADOPTED}{r}",
                        {"pid": pid, "generation": self.generation})
                    self._journal_ev(
                        "spawn", rank=r, pid=pid, adopted=True,
                        incarnation=self._incarnation[r])
                    print(f"[tpud] re-adopted rank {r} (pid {pid}, "
                          f"cursor {offer.get('cursor')})", flush=True)
            # ranks whose recorded worker died while the daemon was
            # down (or that never re-attach) go down the respawn leg —
            # but only after every live-pid rank resolved, so the
            # reborn boot finds re-published wsize/dcn keys
            live_waiting = [
                r for r in range(self.np)
                if self._status[r] == "adopting"
                and _state.pid_alive(self._adopt_pids.get(r, 0))]
            expired = time.monotonic() > self._adopt_deadline
            if live_waiting and not expired:
                return
            still = [r for r in range(self.np)
                     if self._status[r] == "adopting"]
            if (still and not live_waiting
                    and not any(s == "active" for s in self._status)):
                # the whole mesh died with (or after) the daemon:
                # nothing warm survives to repair against — cold-boot
                # fresh workers; journal-restored queued jobs still
                # run, in-flight ones fail honestly
                print("[tpud] no resident workers survived the "
                      "restart; cold-booting the mesh", flush=True)
                for st in self._outstanding.values():
                    for r in st["procs"]:
                        st["done"].setdefault(r, {
                            "ok": False,
                            "error": "mesh lost across daemon restart"})
                for r in still:
                    self._adopt_pids.pop(r, None)
                    self._incarnation[r] = 0
                    self._status[r] = "active"
                    # fresh incarnation-0 workers must NOT replay the
                    # pre-crash stream (their predecessors' directives
                    # are re-published at indices 0..cursor): the
                    # start beacon skips them past it — journal-
                    # restored QUEUED jobs publish at >= cursor
                    self.server.put_local(f"{K_START}{r}", self.cursor)
                    self._procs[r] = (self._spawn(r)
                                      if self._spawn_workers else None)
                return
            for r in still:
                if _state.pid_alive(self._adopt_pids.get(r, 0)):
                    if not expired:
                        continue
                    # window over with the pid alive: a worker wedged
                    # mid-job attaches when it next polls — keep
                    # waiting (unhealthy, visible on /jobs) rather
                    # than double-spawning the rank
                    print(f"[tpud] rank {r} (pid "
                          f"{self._adopt_pids.get(r)}) alive but not "
                          "re-attached; holding the rank", flush=True)
                    continue
                print(f"[tpud] rank {r} did not re-attach (worker "
                      "dead); respawning", flush=True)
                # the dead rank fails any gang it was part of, exactly
                # like a mid-job death the daemon witnessed
                for st in self._outstanding.values():
                    if r in st["procs"] and r not in st["done"]:
                        st["done"][r] = {
                            "ok": False,
                            "error": "rank died during daemon restart"}
                self._adopt_pids.pop(r, None)
                self._respawn_locked(r)

    # -- worker lifecycle ------------------------------------------------

    def _worker_mca(self) -> dict[str, str]:
        m = dict(self.mca)
        # the serving plane is built ON the observability + elastic
        # planes: frames feed the ops surface, the detector feeds
        # repair — both non-negotiable for a daemon
        m["telemetry_enable"] = "1"
        m["ft_detector_enable"] = "1"
        return m

    def _spawn(self, rank: int) -> subprocess.Popen:
        extra = ({ENV_SERVE_PIDFILE: self.pidfile} if self.pidfile
                 else None)
        env = worker_env(
            rank, self.np, self.server.address, mca=self._worker_mca(),
            cpu_devices=self.cpu_devices, extra_env=extra,
            telemetry_addr=self.aggregator.ingest_address)
        if self._incarnation[rank]:
            env[ENV_INCARNATION] = str(self._incarnation[rank])
        p = subprocess.Popen(
            [sys.executable, "-m", "ompi_tpu.serve.worker"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        t = threading.Thread(
            target=_forward, args=(p.stdout, str(rank), sys.stdout.buffer),
            daemon=True)
        t.start()
        self._threads.append(t)
        self._journal_ev("spawn", rank=rank, pid=p.pid,
                         incarnation=self._incarnation[rank])
        return p

    # -- ops surface (mounted on the aggregator's HTTP endpoint) --------

    def _mount_routes(self) -> None:
        agg = self.aggregator
        agg.add_route("POST", "/submit", self._r_submit)
        agg.add_route("GET", "/jobs", self._r_jobs)
        agg.add_route("GET", "/job", self._r_job)
        agg.add_route("POST", "/drain", self._r_drain)
        agg.add_route("POST", "/shutdown", self._r_shutdown)
        agg.add_route("POST", "/scale", self._r_scale)

    @staticmethod
    def _json(status: int, obj) -> tuple[int, str, bytes]:
        return status, "application/json", json.dumps(obj).encode()

    def _r_submit(self, path, body):
        try:
            req = json.loads(body.decode() or "{}")
        except ValueError:
            return self._json(400, {"error": "bad JSON body"})
        if not req.get("script"):
            return self._json(400, {"error": "missing 'script'"})
        tenant = req.get("tenant") or str(serve_var(self.mca, "tenant"))
        try:
            job = self.queue.submit(
                req["script"], args=req.get("args") or (),
                tenant=tenant, nprocs=req.get("nprocs"),
                env=req.get("env"))
        except AdmissionError as e:
            return self._json(e.status, {"error": str(e)})
        self._journal_ev("submit", job=job)
        return self._json(200, job)

    def _r_jobs(self, path, body):
        st = self.queue.state()
        with self._lock:
            st["procs"] = {
                str(r): {"status": self._status[r],
                         "incarnation": self._incarnation[r],
                         "pid": self._proc_pid(r),
                         **({"log": os.path.join(
                             self.logdir, f"worker.{r}.log")}
                            if self.logdir
                            and isinstance(self._procs[r], _AdoptedProc)
                            else {})}
                for r in range(self.np)}
            st["healthy"] = self._healthy_locked()
            st["cursor"] = self.cursor
            st["generation"] = self.generation
        st["telemetry"] = self.aggregator.jobs_state()
        st["url"] = self.url
        return self._json(200, st)

    def _proc_pid(self, r: int) -> int | None:
        p = self._procs[r]
        pid = getattr(p, "pid", None)
        return (int(pid) if pid is not None
                else self._adopt_pids.get(r))

    def _top_state(self) -> dict:
        """The aggregator /json extension (tools/top.py's daemon line):
        liveness identity, journal depth, and the re-adoption picture —
        an operator watching top sees a restarted daemon re-adopt."""
        qs = self.queue.state()
        with self._lock:
            return {"daemon": {
                "pid": os.getpid(),
                "generation": self.generation,
                "crash_safe": bool(self.pidfile),
                "queued": len(qs["queued"]),
                "outstanding": len(self._outstanding),
                "journal_depth": len(qs["queued"]) + len(self._outstanding),
                "adopting": [r for r in range(self.np)
                             if self._status[r] == "adopting"],
                "procs": {str(r): self._status[r]
                          for r in range(self.np)},
                "draining": self.queue.draining,
            }}

    def _r_job(self, path, body):
        job_id = path.rsplit("/", 1)[-1]
        job = self.queue.get(job_id)
        if job is None:
            return self._json(404, {"error": f"no such job {job_id!r}"})
        return self._json(200, job)

    def _r_drain(self, path, body):
        self.queue.draining = True
        self._journal_ev("drain")  # a restart must stay draining
        return self._json(200, {"draining": True})

    def _r_shutdown(self, path, body):
        self.queue.draining = True
        self._journal_ev("drain")
        self.shutting_down = True
        return self._json(200, {"shutting_down": True})

    def _r_scale(self, path, body):
        try:
            want = int(json.loads(body.decode() or "{}")["nprocs"])
        except (ValueError, KeyError):
            return self._json(400, {"error": "body must be "
                                             '{"nprocs": <int>}'})
        if not 0 < want <= self.np:
            return self._json(400, {"error": f"nprocs must be in "
                                             f"[1, {self.np}]"})
        with self._lock:
            active = [r for r in range(self.np)
                      if self._status[r] == "active"]
            if want < len(active):
                retire = active[want:]
                self._publish({"kind": "retire", "procs": active,
                               "retire": retire})
                for r in retire:
                    self._status[r] = "retiring"
                return self._json(200, {"retiring": retire})
            grow = [r for r in range(self.np)
                    if self._status[r] in ("retired", "dead")][
                        :want - len(active)]
            for r in grow:
                self._respawn_locked(r)
            return self._json(
                200, {"restoring": grow} if grow else {"unchanged": True})

    # -- directive stream ------------------------------------------------

    def _publish(self, directive: dict) -> int:
        """Append one directive to the job stream; workers consume
        indices in order, so publication order IS execution order.
        Journaled BEFORE it becomes visible — a crash between the two
        re-publishes it on recovery; consumers dedup by cursor."""
        if _fsim._enabled:
            # chaos (daemonkill:at=N): the Nth publish attempt kills
            # the daemon dead, BEFORE the directive is journaled or
            # visible — the deterministic SIGKILL the restart-hygiene
            # soak replays from one seed.  Repair publishes are their
            # own site (daemon_repair) so a plan can land the kill
            # precisely inside the repair window
            site = ("daemon_repair" if directive.get("kind") == "repair"
                    else "daemon")
            for _r in _fsim.actions(site, kinds={"daemonkill"}):
                print("[tpud] faultsim: injected daemon kill "
                      "(daemonkill)", flush=True)
                sys.stdout.flush()
                os.kill(os.getpid(), signal.SIGKILL)
        with self._lock:
            idx = self.cursor
            self.cursor += 1
            d = dict(directive)
            d["idx"] = idx
            self._outstanding[idx] = {
                "kind": d.get("kind", "job"),
                "procs": list(d.get("procs") or range(self.np)),
                "job_id": d.get("id"),
                "done": {},
                "ts": time.monotonic(),
            }
            self._journal_ev("publish", d=d)
            self.server.put_local(f"{K_JOB}{idx}", d)
            return idx

    def _publish_job(self, job: dict) -> None:
        base = self.cid_next
        self.cid_next += self.cid_block
        job["cid_base"] = base
        job["cid_span"] = self.cid_block
        # job-scoped telemetry: frames from these procs now label this
        # job and /metrics reads relative to this instant's baselines
        self.aggregator.begin_job(job["id"], procs=job["procs"])
        self._publish({"kind": "job", **{
            k: job[k] for k in ("id", "tenant", "script", "args", "env",
                                "procs", "cid_base", "cid_span")}})

    # -- failure / elastic plane ----------------------------------------

    def _respawn_locked(self, rank: int) -> None:
        """Scale-up leg (shared by death recovery and /scale restore):
        relaunch the rank under a bumped incarnation and queue the
        repair that will ``replace()`` it back into the warm world."""
        self._incarnation[rank] += 1
        self._status[rank] = "respawning"
        self._repairing.add(rank)
        self._repair_published = False
        # journal the repair INTENT before anything is visible: a
        # daemon SIGKILLed between this respawn and the replace()
        # completion finishes the repair after restart instead of
        # stranding the reborn worker (cleared by the repair finish)
        self._journal_ev("repair_pending", rank=rank,
                         incarnation=self._incarnation[rank])
        self._procs[rank] = (self._spawn(rank) if self._spawn_workers
                             else None)

    def _handle_death(self, rank: int, rc: int) -> None:
        with self._lock:
            if self._status[rank] == "retiring":
                self._status[rank] = "retired"
                self._journal_ev("retire", ranks=[rank])
                return
            if self.shutting_down and self._shutdown_published:
                self._status[rank] = "exited"
                return
            # a died worker fails its directive's gang: synthesize its
            # completion record so survivors' reports can close it out
            for st in self._outstanding.values():
                if rank in st["procs"] and rank not in st["done"]:
                    st["done"][rank] = {"ok": False,
                                        "error": f"rank died (rc={rc})"}
            if self._incarnation[rank] >= self.max_respawns:
                print(f"[tpud] rank {rank} died (rc={rc}); respawn "
                      f"budget exhausted — marking it dead", flush=True)
                self._status[rank] = "dead"
                return
            print(f"[tpud] rank {rank} died (rc={rc}); respawning "
                  f"(incarnation {self._incarnation[rank] + 1})",
                  flush=True)
            self._respawn_locked(rank)

    def _maybe_publish_repair(self) -> None:
        """Publish ONE repair directive once every rank-set is free:
        survivors run ``replace()`` (awaiting the reborn incarnations),
        the reborn workers rejoin through the replace beacon and then
        resume the stream AFTER this directive (their cursor is the
        ``serve.resume`` key written here)."""
        with self._lock:
            if (not self._repairing or self._repair_published
                    or any(s == "adopting" for s in self._status)
                    or any(st["kind"] != "repair"
                           for st in self._outstanding.values())):
                return
            if any(self._status[r] == "respawning" and
                   (self._procs[r] is None or
                    self._procs[r].poll() is not None)
                   for r in self._repairing):
                return  # a respawn died before repair; death path re-arms
            survivors = [r for r in range(self.np)
                         if self._status[r] == "active"]
            if not survivors:
                return
            idx = self._publish({
                "kind": "repair", "procs": survivors,
                "dead": sorted(self._repairing)})
            for r in sorted(self._repairing):
                self.server.put_local(
                    f"{K_RESUME}{r}.i{self._incarnation[r]}", idx + 1)
            self._repair_published = True

    # -- monitor loop ----------------------------------------------------

    def _healthy_locked(self) -> bool:
        return not self._repairing and all(
            s in ("active", "retired", "dead", "exited")
            for s in self._status)

    def _poll_workers(self) -> None:
        for r in range(self.np):
            p = self._procs[r]
            if p is None or self._status[r] in ("retired", "dead",
                                                "exited"):
                continue
            rc = p.poll()
            if rc is not None:
                self._handle_death(r, rc or 0)

    def _collect_done(self) -> None:
        done_idx = []
        with self._lock:
            for idx, st in self._outstanding.items():
                for r in st["procs"]:
                    if r in st["done"]:
                        continue
                    rec = self.server.peek(f"{K_DONE}{idx}.{r}")
                    if rec is not None:
                        st["done"][r] = rec
                if len(st["done"]) >= len(st["procs"]):
                    done_idx.append(idx)
                elif (st["kind"] == "job" and self.job_timeout > 0
                      and time.monotonic() - st["ts"] > self.job_timeout):
                    # job overran its budget: reclaim the rank-set by
                    # killing its members — the death path respawns and
                    # repairs them (the elastic plane as the enforcer)
                    print(f"[tpud] job {st['job_id']} exceeded "
                          f"serve_job_timeout={self.job_timeout}s; "
                          f"killing its ranks", flush=True)
                    st["ts"] = float("inf")
                    for r in st["procs"]:
                        q = self._procs[r]
                        if q is not None and q.poll() is None:
                            q.terminate()
        for idx in done_idx:
            self._finish_directive(idx)

    def _finish_directive(self, idx: int) -> None:
        with self._lock:
            st = self._outstanding.pop(idx)
        if st["kind"] == "job":
            bad = [f"rank {r}: {rec.get('error', '?')}"
                   for r, rec in sorted(st["done"].items())
                   if not rec.get("ok")]
            job = self.queue.finish(st["job_id"], ok=not bad,
                                    error="; ".join(bad),
                                    ranks=st["done"])
            self._journal_ev("finish", idx=idx, kind="job", job=job)
            if job is not None:
                print(f"[tpud] job {job['id']} ({job['tenant']}) "
                      f"{job['state']}", flush=True)
        elif st["kind"] == "repair":
            with self._lock:
                for r in self._repairing:
                    if self._status[r] == "respawning":
                        self._status[r] = "active"
                self._repairing.clear()
                self._repair_published = False
            self._journal_ev("finish", idx=idx, kind="repair")
            print("[tpud] repair complete: mesh restored", flush=True)
        elif st["kind"] == "retire":
            with self._lock:
                done = [r for r in range(self.np)
                        if self._status[r] == "retiring"]
                for r in done:
                    self._status[r] = "retired"
            if done:
                self._journal_ev("retire", ranks=done)
            self._journal_ev("finish", idx=idx, kind="retire")

    def _busy_procs(self) -> set[int]:
        with self._lock:
            return {r for st in self._outstanding.values()
                    for r in st["procs"]}

    def _booted(self) -> bool:
        """Mesh boot gate: a rank worker's ``wsize.<r>`` modex publish
        is its I-am-up beacon — scheduling (and therefore the
        daemonkill directive counter) must not run ahead of workers
        that are still importing.  Without this, a daemon crash in the
        boot window strands directives no worker ever saw AND kills
        the workers at their first KVS dial (found by the
        --daemon-restart soak's own race)."""
        if not self._spawn_workers:
            return True  # workerless harness pumps the stream itself
        return all(self.server.peek(f"wsize.{r}") is not None
                   for r in range(self.np)
                   if self._status[r] == "active")

    def _schedule(self) -> None:
        with self._lock:
            if not self._healthy_locked() or self._shutdown_published:
                return
            active = {r for r in range(self.np)
                      if self._status[r] == "active"}
        if not self._booted():
            return
        free = active - self._busy_procs()
        while True:
            job = self.queue.next_runnable(free)
            if job is None:
                return
            if job["nprocs"] > len(active):
                self.queue.finish(
                    job["id"], ok=False,
                    error=f"needs {job['nprocs']} procs; only "
                          f"{len(active)} active")
                continue
            self._publish_job(job)
            free -= set(job["procs"])

    def _maybe_shutdown(self) -> bool:
        with self._lock:
            if not self.shutting_down or self._shutdown_published:
                return self._shutdown_published
            if self._outstanding or not self.queue.idle():
                return False
            active = [r for r in range(self.np)
                      if self._status[r] == "active"]
            self._publish({"kind": "shutdown", "procs": active})
            self._shutdown_published = True
            return True

    def step(self) -> None:
        """One monitor tick (public so tests can drive the loop
        deterministically)."""
        self._poll_adoption()
        self._poll_workers()
        self._collect_done()
        self._maybe_publish_repair()
        self._schedule()
        self._maybe_shutdown()

    def run(self) -> int:
        """Blocking monitor loop until shutdown completes."""
        print(f"[tpud] ops: {self.url}/jobs (submit: python "
              f"tools/tpud_ctl.py --url {self.url} submit <script>; "
              f"scrape: {self.url}/metrics)", flush=True)
        def _sigterm(*_):
            # same contract as POST /shutdown: stop admitting AND stop
            # serving — shutting_down alone would keep accepting jobs
            # and never drain under continued submit traffic
            self.queue.draining = True
            self.shutting_down = True

        try:
            signal.signal(signal.SIGTERM, _sigterm)
        except ValueError:
            pass  # non-main thread (tests): SIGTERM stays default
        try:
            while True:
                self.step()
                if self._shutdown_published:
                    live = [p for p in self._procs
                            if p is not None and p.poll() is None]
                    if not live:
                        break
                time.sleep(0.05)
        except KeyboardInterrupt:
            self.shutting_down = True
            self.exit_code = 130
        finally:
            self.close()
        return self.exit_code

    def close(self) -> None:
        self.queue.fail_queued("daemon shut down")
        deadline = time.monotonic() + 10
        for p in self._procs:
            while (p is not None and p.poll() is None
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            if p is not None and p.poll() is None:
                p.kill()
        for t in self._threads:
            t.join(timeout=5)
        self.aggregator.close()
        self.server.close()
        # clean release: the journal is REMOVED (nothing durable
        # remains to recover, and an append-only file reused across
        # many daemon lifetimes would grow without bound) and the
        # pidfile lifts — the next daemon starts fresh instead of
        # "recovering" a shutdown it misreads as a crash.  The
        # shutdown event is still written first: if the unlink loses a
        # race (or the operator copies the journal mid-shutdown), the
        # tail says clean.
        if self._journal is not None:
            self._journal_ev("shutdown", generation=self.generation)
            self._journal.close()
            self._journal = None
            try:
                os.unlink(self.journal_path)
            except OSError:
                pass
        if self.pidfile:
            _state.remove_pidfile(self.pidfile)


class _AdoptedProc:
    """A re-adopted resident worker: not our child, so no Popen — a
    pid wrapper with the Popen surface the monitor loop touches.
    ``poll()`` can only report liveness (the real exit code reaps to
    init), so death reads as a synthetic rc 1 — enough for the
    respawn machinery, which only branches on nonzero."""

    def __init__(self, pid: int):
        self.pid = int(pid)
        self.returncode: int | None = None

    def poll(self) -> int | None:
        if self.returncode is None and not _state.pid_alive(self.pid):
            self.returncode = 1
        return self.returncode

    def _signal(self, sig: int) -> None:
        try:
            os.kill(self.pid, sig)
        except OSError:
            pass

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def wait(self, timeout: float | None = None) -> int:
        deadline = time.monotonic() + (timeout or 0)
        while self.poll() is None:
            if timeout is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("adopted", timeout)
            time.sleep(0.05)
        return self.returncode  # type: ignore[return-value]


def run_daemon(np_: int, mca: dict[str, str] | None = None,
               cpu_devices: int | None = None, max_respawns: int = 2,
               http_port: int | None = None) -> int:
    """The ``tpurun --daemon`` / ``tools/tpud.py`` entry."""
    try:
        d = TpuDaemon(np_, mca=mca, cpu_devices=cpu_devices,
                      max_respawns=max_respawns, http_port=http_port)
    except _state.DaemonAlreadyRunning as e:
        # idempotent start: a second `tpurun --daemon` against a live
        # pidfile is a clean one-liner, not a traceback
        print(f"tpud: {e}", flush=True)
        return 1
    return d.run()
