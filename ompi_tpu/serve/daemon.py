"""``tpud`` — the persistent serving daemon (≈ orted/prted).

One daemon process owns the standing infrastructure a ``tpurun`` job
normally builds and discards per invocation:

* the boot **KVS** (rendezvous server) — resident workers boot against
  it once and then treat it as the job stream: the daemon publishes
  numbered directives (``serve.job.<n>``), workers long-poll them and
  answer with completion records (``serve.done.<n>.<proc>``);
* the **live-telemetry aggregator** — always on; its HTTP endpoint is
  the daemon's ops surface (``/submit``, ``/jobs``, ``/job/<id>``,
  ``/drain``, ``/shutdown``, ``/scale`` mounted next to the PR-5
  ``/metrics``/``/json``/``/history`` scrape endpoints), and its
  queue-depth/health feeds drive admission and scheduling;
* N **resident rank workers** (``ompi_tpu.serve.worker``) whose DCN
  endpoints — both planes — engine threads, and compiled collective
  state stay warm across jobs;
* the **elastic plane, daemon-fired**: a dead worker is respawned
  under a bumped incarnation and restored by a ``repair`` directive
  (survivors run ``replace()``, the reborn rank rejoins — scale-up),
  and ``/scale`` retires ranks (scale-down) or brings retirees back
  through the same respawn+repair leg.

Scheduling is **gang** FIFO with per-tenant round-robin fairness
(:mod:`~ompi_tpu.serve.queue`): a job is published only when its full
rank-set is free, and never while the mesh is unhealthy (dead worker,
repair outstanding) — the telemetry plane's detector feed gating the
job stream.

**Crash safety** (``serve_pidfile`` arms it, :mod:`~ompi_tpu.serve.
state` holds the substrate): the daemon takes a pidfile lock with
stale-lock takeover and journals the job stream (append-only JSONL)
so a daemon SIGKILL loses nothing durable — a restarted daemon
replays the journal (queued jobs restored, in-flight directives
re-published at their original indices; workers dedup by cursor so a
replayed directive executes exactly once) and **re-adopts** the
still-live resident workers through the warm KVS: workers that lost
their daemon park on the pidfile, re-dial the new KVS, re-publish
their modex keys, and offer ``serve.adopt.<r>`` records the daemon
acks — their mesh, DCN endpoints, and warm CIDs never went away.
Only a rank whose process actually died goes down the respawn+repair
leg.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

from ompi_tpu.boot.kvs import KVSServer
from ompi_tpu.boot.proc import ENV_HOST_IDS, ENV_INCARNATION, ENV_PROC
from ompi_tpu.boot.tpurun import (_final_cmd, _forward, _is_local_host,
                                  _truthy, worker_env)
from ompi_tpu.core.var import ENV_PREFIXES, SERVING_VARS, full_var_name
from ompi_tpu.faultsim import core as _fsim
from ompi_tpu.metrics.live import TelemetryAggregator
from . import agent as _agent
from . import state as _state
from .queue import AdmissionController, AdmissionError, JobQueue

#: KVS key prefixes of the serve protocol (workers mirror these)
K_JOB = "serve.job."        # + <n>            → directive JSON
K_DONE = "serve.done."      # + <n>.<proc>     → completion record
K_RESUME = "serve.resume."  # + <proc>.i<inc>  → reborn worker's cursor
K_ADOPT = "serve.adopt."    # + <proc>         → worker re-adoption offer
K_ADOPTED = "serve.adopted."  # + <proc>       → daemon's adoption ack
K_START = "serve.start."    # + <proc>         → fresh worker's cursor
K_PIDFILE = "serve.pidfile."  # + <generation>  → pidfile-record beacon
#                              (agents mirror it to hosts without the
#                               daemon's filesystem — see serve/agent.py)

#: env var carrying the pidfile path to resident workers (their
#: re-attach rendezvous after a daemon crash)
ENV_SERVE_PIDFILE = "OMPI_TPU_SERVE_PIDFILE"


def serve_var(mca: dict | None, name: str):
    """Resolve one ``serve_<name>`` knob daemon-side (no MCA context in
    the launcher process, same as tpurun's telemetry gate): ``--mca``
    dict → ``OMPI_MCA_*`` env → the SERVING_VARS default."""
    full = f"serve_{name}"
    if mca and full in mca:
        return mca[full]
    for prefix in ENV_PREFIXES:
        v = os.environ.get(prefix + full)
        if v is not None:
            return v
    for fw, comp, n, default, _typ, _h in SERVING_VARS:
        if full_var_name(fw, comp, n) == full:
            return default
    raise KeyError(full)


class TpuDaemon:
    """The serving daemon.  ``spawn=False`` builds the full control
    plane (KVS, aggregator, queue, ops routes) without resident
    workers — the selftest/unit harness pumps the job stream itself."""

    def __init__(self, np_: int, mca: dict[str, str] | None = None,
                 cpu_devices: int | None = None, max_respawns: int = 2,
                 http_port: int | None = None, spawn: bool = True,
                 hosts: list[tuple[str, int]] | None = None,
                 map_by: str = "slot",
                 launch_agent: str = "ssh {host} {cmd}",
                 kvs_host: str | None = None,
                 oversubscribe: bool = False):
        self.np = int(np_)
        self.mca = dict(mca or {})
        self.cpu_devices = cpu_devices
        self.max_respawns = int(max_respawns)
        self._spawn_workers = spawn
        self.launch_agent = launch_agent
        # multi-host DVM (the prte shape): map ranks onto the host
        # allocation; each NON-local host gets one launch agent over
        # the rsh leg that owns its ranks' spawn/respawn/pid-liveness
        # — the daemon's `kill 0`-style probes cannot cross hosts
        self._rank_hid: list[int | None] = [None] * self.np
        self._host_names: dict[int, str] = {}
        self._host_ids_env = ""
        self._agents: dict[int, dict] = {}
        if hosts:
            from ompi_tpu.boot.rmaps import map_ranks

            rank_host = map_ranks(hosts, self.np, policy=map_by,
                                  oversubscribe=oversubscribe)
            order: dict[str, int] = {}
            for hname in rank_host:
                order.setdefault(hname, len(order))
            self._host_ids_env = ",".join(
                str(order[hname]) for hname in rank_host)
            for r, hname in enumerate(rank_host):
                hid = order[hname]
                self._host_names[hid] = hname
                if not _is_local_host(hname):
                    self._rank_hid[r] = hid
            for hid, hname in sorted(self._host_names.items()):
                ranks = [r for r in range(self.np)
                         if self._rank_hid[r] == hid]
                if ranks:
                    self._agents[hid] = {
                        "name": hname, "ranks": ranks, "proc": None,
                        "session": "", "cursor": 0, "pending": {},
                        "hb": None, "spawns": 0, "status": "down",
                        "worker_pids": {}}
        self.cid_block = int(serve_var(self.mca, "cid_block"))
        self.cid_next = int(serve_var(self.mca, "cid_base"))
        self.job_timeout = float(serve_var(self.mca, "job_timeout"))
        #: softer bound than job_timeout: expiry revokes the job's comm
        #: (typed failure, gang woken) instead of killing its ranks
        self.job_deadline = float(serve_var(self.mca, "job_deadline_s"))
        self.reattach_timeout = float(
            serve_var(self.mca, "reattach_timeout"))
        self._lock = threading.RLock()
        # crash-safe control plane (serve_pidfile arms it): stale-lock
        # takeover + journal replay happen BEFORE any socket exists so
        # a refused second daemon leaves no trace
        self.pidfile = str(serve_var(self.mca, "pidfile") or "")
        self.journal_path = str(serve_var(self.mca, "journal") or "")
        if not self.journal_path and self.pidfile:
            self.journal_path = self.pidfile + ".journal"
        self.generation = 1
        self._journal: _state.Journal | None = None
        recovered: dict | None = None
        if self.pidfile:
            stale = _state.acquire_pidfile(self.pidfile)  # may raise
            if stale is not None:
                print(f"[tpud] reaped stale pidfile {self.pidfile} "
                      f"(pid {stale.get('pid')} dead)", flush=True)
            replay = _state.Journal.replay(self.journal_path)
            self.generation = max(
                replay["generation"],
                int((stale or {}).get("generation", 0))) + 1
            if replay["events"] and not replay["clean"]:
                recovered = replay
        # deterministic chaos (daemonkill): the daemon itself runs
        # under the seeded fault plane when the mca/env arm it — rank
        # workers get the same plan via OMPI_MCA_* inheritance
        if _truthy(self._opt("faultsim_enable")):
            _fsim.configure(str(self._opt("faultsim_plan") or ""),
                            seed=int(self._opt("faultsim_seed") or 0),
                            proc=-1)
        self.server = KVSServer(host=kvs_host or "127.0.0.1")
        self.aggregator = TelemetryAggregator(
            http_port=(int(serve_var(self.mca, "port"))
                       if http_port is None else int(http_port)))
        self.aggregator.extra_state = self._top_state
        self.url = self.aggregator.url
        self.queue = JobQueue(
            self.np, max_pending=int(serve_var(self.mca, "max_pending")),
            max_concurrent=int(serve_var(self.mca, "max_concurrent")),
            retry_budget=int(serve_var(self.mca, "retry_budget")),
            admission=AdmissionController(
                stall_ns=int(serve_var(self.mca, "admission_stall_ns")),
                policy=str(serve_var(self.mca, "shed_policy"))))
        #: frame timestamps the admission controller already folded —
        #: its streak must advance at telemetry cadence, not at the
        #: much faster monitor-tick cadence (see _admission_update)
        self._adm_seen: dict[int, int] = {}
        # the daemon-owned serving counters (jobs_shed, …) ride the
        # normal native-counter discipline: the in-process pvar surface
        # via a provider anchored on the queue's lifetime, and /metrics
        # via the aggregator's host-process extension (proc="daemon")
        from ompi_tpu.metrics import core as _mcore

        _mcore.register_provider(
            self.queue, lambda q=self.queue: dict(q.counters))
        self.aggregator.extra_counters = self._daemon_counters
        # hang diagnosis in the DAEMON process (the pre-revoke report
        # on the deadline path + the /metrics hang_* families): same
        # launcher-process knob resolution the faultsim gate uses
        from ompi_tpu.trace import waitgraph as _waitgraph

        hd = self._opt("hang_diag_enable")
        _waitgraph.sync_from_store(
            {"hang_diag_enable": True if hd == "" else _truthy(hd)})
        self._hang_timeout_s = max(0.0, float(
            self._opt("hang_snapshot_timeout_ms") or 2000) / 1000.0)
        self._mount_routes()
        #: next directive index (the job-stream cursor)
        self.cursor = 0
        #: directive index → bookkeeping ({kind, procs, job_id, done})
        self._outstanding: dict[int, dict] = {}
        #: per-proc worker state: process handle + incarnation + status
        #: in {"active", "adopting", "dead", "retired", "exited"}
        self._procs: list[subprocess.Popen | _AdoptedProc | None] = (
            [None] * self.np)
        self._incarnation = [0] * self.np
        self._status = ["active"] * self.np
        self._threads: list[threading.Thread] = []
        #: procs awaiting the repair directive (respawned, not yet
        #: restored into the world by the survivors' replace())
        self._repairing: set[int] = set()
        self._repair_published = False
        #: re-adoption window state (restart recovery)
        self._adopt_deadline = 0.0
        self._adopt_pids: dict[int, int] = {}
        self.shutting_down = False
        self._shutdown_published = False
        self.exit_code = 0
        self.logdir = (self.pidfile + ".logs") if self.pidfile else ""
        if self.pidfile:
            if self.logdir:
                try:
                    os.makedirs(self.logdir, exist_ok=True)
                except OSError:
                    self.logdir = ""
            record = {
                "pid": os.getpid(), "generation": self.generation,
                "np": self.np, "kvs": self.server.address,
                "url": self.url,
                "ingest": self.aggregator.ingest_address,
                "logs": self.logdir,
                "ts_ns": time.time_ns()}
            _state.write_pidfile(self.pidfile, record)
            # real-remote re-attach channel: mirror the pidfile record
            # as a KVS beacon — launch agents copy it to THEIR host's
            # pidfile path, so workers on hosts that share no
            # filesystem with the daemon still find a restarted daemon
            # through the ordinary pidfile poll
            self.server.put_local(f"{K_PIDFILE}{self.generation}",
                                  record)
            if recovered is not None:
                # journal compaction (PR 10 deferred edge): takeover
                # rewrites the journal to the live-state fixed point
                # BEFORE appending, so repeated SIGKILL→restart cycles
                # stop growing it without bound
                _state.Journal.compact(self.journal_path, recovered)
            # rotation bounds (the crash-free twin of takeover
            # compaction): a month-resident daemon's journal compacts
            # in place once it crosses the size/age knobs
            self._journal = _state.Journal(
                self.journal_path,
                max_bytes=int(self._agent_var(
                    "journal_max_kb", 0)) * 1024,
                max_age_s=float(self._agent_var(
                    "journal_max_age_s", 0.0)))
        if recovered is not None:
            self._recover(recovered)
        elif spawn:
            for hid in sorted(self._agents):
                self._boot_agent(hid)
            for rank in range(self.np):
                self._procs[rank] = self._spawn(rank)

    def _opt(self, name: str, default: str = "") -> str:
        """Resolve a NON-serve var daemon-side (``--mca`` dict → env →
        default) — the faultsim knobs ride the same launcher-process
        resolution serve_var gives the serve_* set."""
        if name in self.mca:
            return str(self.mca[name])
        for prefix in ENV_PREFIXES:
            v = os.environ.get(prefix + name)
            if v is not None:
                return v
        return default

    def _journal_ev(self, ev: str, **fields) -> None:
        if self._journal is not None:
            self._journal.append(ev, **fields)

    # -- restart recovery (journal replay + worker re-adoption) ---------

    def _recover(self, replay: dict) -> None:
        """Rebuild the control plane a SIGKILLed predecessor dropped:
        restore the queue (queued jobs re-admitted, running jobs
        re-entered), the stream cursor and CID high-water mark,
        re-publish every outstanding directive at its ORIGINAL index
        into the fresh KVS (consumers dedup by cursor — a directive a
        worker already executed is skipped, one it never saw runs:
        exactly once either way), seed the boot fences the old server
        took with it, and open the re-adoption window for the still-
        live resident workers."""
        self._journal_ev("takeover", generation=self.generation,
                         recovered_events=replay["events"])
        # running jobs from the journal lack nothing — the published
        # directive carries procs/cid; merge directive fields over the
        # submit record so queue bookkeeping matches pre-crash state
        by_id = {d.get("id"): d for d in replay["outstanding"].values()
                 if d.get("kind", "job") == "job"}
        running = [dict(job, **{k: by_id[job["id"]][k]
                                for k in ("procs", "cid_base", "cid_span")
                                if k in by_id[job["id"]]})
                   for job in replay["running"] if job["id"] in by_id]
        self.queue.restore(queued=replay["queued"], running=running,
                           done=replay["done"])
        self.cursor = int(replay["cursor"])
        if replay["cid_next"] is not None:
            self.cid_next = max(self.cid_next, int(replay["cid_next"]))
        # the WHOLE stream is re-created at its original indices — NOT
        # via _publish (the cursor must not advance; nothing may be
        # re-journaled or re-counted by the fault plane).  Finished
        # directives are re-published too: workers consume strictly in
        # order, so a hole below a finished index would wedge any
        # worker whose cursor is still beneath it — and re-publication
        # cannot double-execute (a finished directive's whole gang
        # reported, so their cursors are past it; everyone else skips
        # non-member directives by construction)
        for idx in sorted(replay["published"]):
            d = replay["published"][idx]
            if idx in replay["outstanding"]:
                self._outstanding[idx] = {
                    "kind": d.get("kind", "job"),
                    "procs": list(d.get("procs") or range(self.np)),
                    "job_id": d.get("id"), "done": {},
                    "ts": time.monotonic(),
                }
            self.server.put_local(f"{K_JOB}{idx}", d)
        # the boot-time fences died with the old KVS; a future
        # respawned rank still replays them idempotently
        self.server.seed_fence("modex", range(self.np))
        self._adopt_pids = {r: int(st.get("pid", 0))
                            for r, st in replay["pids"].items()}
        for r, st in replay["pids"].items():
            if 0 <= int(r) < self.np:
                self._incarnation[int(r)] = int(st.get("incarnation", 0))
        # multi-host: the journal's host placement tells the restarted
        # daemon which agents to await — each parks on the pidfile
        # like a worker and offers serve.agent.adopt.<hid>; one that
        # never re-attaches (it died with the daemon) is respawned
        # over rsh with the journaled worker-pid table, so ITS reborn
        # agent re-adopts the still-live workers
        for hid, ag in self._agents.items():
            ag["status"] = "adopting"
            ag["hb_mono"] = time.monotonic()
            for r, st in replay["pids"].items():
                if (0 <= int(r) < self.np
                        and self._rank_hid[int(r)] == hid
                        and int(st.get("pid", 0))):
                    ag["worker_pids"][int(r)] = (
                        int(st["pid"]), int(st.get("incarnation", 0)))
        # crash-mid-repair replay (PR 10 deferred edge): a rank the
        # predecessor respawned whose repair never FINISHED re-enters
        # the repairing set — once adoption resolves the mesh view,
        # the repair directive publishes (or a dead reborn goes down
        # the respawn leg, which re-arms it); an outstanding repair
        # directive also needs its reborn-cursor beacons re-seeded
        # (they died with the old KVS)
        for r in (replay.get("repairing") or {}):
            if 0 <= int(r) < self.np:
                self._repairing.add(int(r))
        for idx, d in replay["outstanding"].items():
            if d.get("kind") == "repair":
                self._repair_published = True
                for r in d.get("dead", ()):
                    self.server.put_local(
                        f"{K_RESUME}{int(r)}.i{self._incarnation[int(r)]}",
                        int(idx) + 1)
        self._status = ["adopting"] * self.np
        for r in replay["retired"]:
            # an operator's /scale-down outlives the crash: a retired
            # rank's dead pid is NOT a crashed worker to respawn
            if 0 <= int(r) < self.np:
                self._status[int(r)] = "retired"
                self._adopt_pids.pop(int(r), None)
        if replay["draining"]:
            self.queue.draining = True  # the drain outlives the crash
        self._adopt_deadline = time.monotonic() + self.reattach_timeout
        print(f"[tpud] restart recovery (generation {self.generation}): "
              f"{len(replay['outstanding'])} in-flight directive(s) "
              f"re-published, {len(replay['queued'])} queued job(s) "
              f"restored, awaiting re-adoption of {self.np} worker(s)",
              flush=True)

    def _poll_adoption(self) -> None:
        """One monitor-tick look at the re-adoption window: a live
        worker that found the new pidfile publishes ``serve.adopt.<r>``
        — verify its pid, take it over (no Popen handle: an
        :class:`_AdoptedProc` wraps the pid), and ack so the worker
        resumes its stream.  A rank whose last known pid is dead is
        respawned once every live rank has re-attached (the reborn
        boot needs the survivors' re-published modex keys)."""
        with self._lock:
            pending = [r for r in range(self.np)
                       if self._status[r] == "adopting"]
            if not pending:
                return
            for r in pending:
                offer = self.server.peek(f"{K_ADOPT}{r}")
                # a remote rank's offer IS its proof of life (the
                # local pid probe cannot cross hosts; the worker just
                # published under our generation)
                pid_ok = (self._rank_hid[r] is not None
                          or _state.pid_alive(int(offer.get("pid", 0)))
                          ) if offer else False
                if (offer and int(offer.get("generation", 0))
                        == self.generation and pid_ok):
                    pid = int(offer["pid"])
                    self._incarnation[r] = int(
                        offer.get("incarnation", 0))
                    if self._rank_hid[r] is not None:
                        rp = _RemoteProc(self, r, self._rank_hid[r],
                                         self._incarnation[r])
                        rp.pid = pid
                        self._procs[r] = rp
                    else:
                        self._procs[r] = _AdoptedProc(pid)
                    self._status[r] = "active"
                    self._adopt_pids.pop(r, None)
                    self.server.put_local(
                        f"{K_ADOPTED}{r}",
                        {"pid": pid, "generation": self.generation})
                    self._journal_ev(
                        "spawn", rank=r, pid=pid, adopted=True,
                        incarnation=self._incarnation[r],
                        **({"host": self._rank_hid[r]}
                           if self._rank_hid[r] is not None else {}))
                    print(f"[tpud] re-adopted rank {r} (pid {pid}, "
                          f"cursor {offer.get('cursor')})", flush=True)
            # ranks whose recorded worker died while the daemon was
            # down (or that never re-attach) go down the respawn leg —
            # but only after every live-pid rank resolved, so the
            # reborn boot finds re-published wsize/dcn keys
            live_waiting = [
                r for r in range(self.np)
                if self._status[r] == "adopting"
                and self._rank_alive(r, self._adopt_pids.get(r, 0))]
            expired = time.monotonic() > self._adopt_deadline
            if live_waiting and not expired:
                return
            still = [r for r in range(self.np)
                     if self._status[r] == "adopting"]
            if (still and not live_waiting
                    and not any(s == "active" for s in self._status)):
                # the whole mesh died with (or after) the daemon:
                # nothing warm survives to repair against — cold-boot
                # fresh workers; journal-restored queued jobs still
                # run, in-flight ones fail honestly
                print("[tpud] no resident workers survived the "
                      "restart; cold-booting the mesh", flush=True)
                for st in self._outstanding.values():
                    for r in st["procs"]:
                        st["done"].setdefault(r, {
                            "ok": False,
                            "error": "mesh lost across daemon restart"})
                # multi-host: a cold boot needs live agents with real
                # command sessions BEFORE any remote spawn publishes —
                # an agent still marked adopting never offered itself
                # (it died with the mesh), so relaunch it now
                for hid, ag in self._agents.items():
                    if ag["status"] != "active":
                        self._boot_agent(hid)
                for r in still:
                    self._adopt_pids.pop(r, None)
                    self._incarnation[r] = 0
                    self._status[r] = "active"
                    # fresh incarnation-0 workers must NOT replay the
                    # pre-crash stream (their predecessors' directives
                    # are re-published at indices 0..cursor): the
                    # start beacon skips them past it — journal-
                    # restored QUEUED jobs publish at >= cursor
                    self.server.put_local(f"{K_START}{r}", self.cursor)
                    self._procs[r] = (self._spawn(r)
                                      if self._spawn_workers else None)
                return
            for r in still:
                if self._rank_alive(r, self._adopt_pids.get(r, 0)):
                    if not expired:
                        continue
                    # window over with the pid alive: a worker wedged
                    # mid-job attaches when it next polls — keep
                    # waiting (unhealthy, visible on /jobs) rather
                    # than double-spawning the rank
                    print(f"[tpud] rank {r} (pid "
                          f"{self._adopt_pids.get(r)}) alive but not "
                          "re-attached; holding the rank", flush=True)
                    continue
                hid = self._rank_hid[r]
                if (hid is not None
                        and self._agents[hid]["status"] != "active"):
                    # a remote rank cannot respawn without its agent:
                    # publishing the command now would land in a dead
                    # or not-yet-acked session and be lost when the
                    # agent resolves — hold the rank; the agent's own
                    # adoption/respawn (_poll_agents) unblocks it
                    continue
                print(f"[tpud] rank {r} did not re-attach (worker "
                      "dead); respawning", flush=True)
                # the dead rank fails any gang it was part of, exactly
                # like a mid-job death the daemon witnessed
                for st in self._outstanding.values():
                    if r in st["procs"] and r not in st["done"]:
                        st["done"][r] = {
                            "ok": False,
                            "error": "rank died during daemon restart"}
                self._adopt_pids.pop(r, None)
                self._respawn_locked(r)

    # -- worker lifecycle ------------------------------------------------

    def _worker_mca(self) -> dict[str, str]:
        m = dict(self.mca)
        # the serving plane is built ON the observability + elastic
        # planes: frames feed the ops surface, the detector feeds
        # repair — both non-negotiable for a daemon
        m["telemetry_enable"] = "1"
        m["ft_detector_enable"] = "1"
        return m

    def _spawn(self, rank: int):
        hid = self._rank_hid[rank]
        if hid is not None:
            # remote rank: the owning host's launch agent executes the
            # spawn (the daemon shares no pid namespace with it); the
            # journal records placement now and the real pid when the
            # agent's ack arrives
            inc = self._incarnation[rank]
            self._agent_cmd(hid, {
                "kind": "spawn", "rank": rank, "incarnation": inc,
                # the CURRENT ingest address rides the command: the
                # agent's inherited env may still name a dead
                # predecessor's aggregator after a daemon restart
                "telemetry": self.aggregator.ingest_address})
            self._journal_ev("spawn", rank=rank, pid=0,
                             incarnation=inc, host=hid)
            return _RemoteProc(self, rank, hid, inc)
        extra = dict({ENV_SERVE_PIDFILE: self.pidfile}
                     if self.pidfile else {})
        if self._host_ids_env:
            extra[ENV_HOST_IDS] = self._host_ids_env
        env = worker_env(
            rank, self.np, self.server.address, mca=self._worker_mca(),
            cpu_devices=self.cpu_devices, extra_env=extra or None,
            telemetry_addr=self.aggregator.ingest_address)
        if self._incarnation[rank]:
            env[ENV_INCARNATION] = str(self._incarnation[rank])
        p = subprocess.Popen(
            [sys.executable, "-m", "ompi_tpu.serve.worker"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        t = threading.Thread(
            target=_forward, args=(p.stdout, str(rank), sys.stdout.buffer),
            daemon=True)
        t.start()
        self._threads.append(t)
        self._journal_ev("spawn", rank=rank, pid=p.pid,
                         incarnation=self._incarnation[rank])
        return p

    # -- per-host launch agents (the multi-host DVM leg) ----------------

    def _agent_var(self, name: str, default: float) -> float:
        try:
            return float(serve_var(self.mca, name))
        except (KeyError, ValueError):
            return float(default)

    def _boot_agent(self, hid: int,
                    adopt: dict[int, tuple[int, int]] | None = None
                    ) -> None:
        """(Re)launch one host's agent over the rsh leg.  ``adopt``
        hands the reborn agent the last-known worker table (rank →
        (pid, incarnation)) so an agent-only death re-adopts the
        still-live workers instead of double-spawning the host."""
        ag = self._agents[hid]
        ag["session"] = f"g{self.generation}s{ag['spawns']}"
        ag["spawns"] += 1
        ag["cursor"] = 0
        # old-session indices are dead with the session: the respawn
        # caller re-issues what it captured, and a stale entry left
        # here would be re-issued AGAIN on every later respawn
        # (double-spawning a rank that is already alive)
        ag["pending"] = {}
        ag["hb"] = None
        ag["hb_mono"] = time.monotonic()
        ag["status"] = "active"
        extra = dict({ENV_SERVE_PIDFILE: self.pidfile}
                     if self.pidfile else {})
        extra[_agent.ENV_AGENT_HOST] = str(hid)
        extra[_agent.ENV_AGENT_RANKS] = ",".join(
            str(r) for r in ag["ranks"])
        extra[_agent.ENV_AGENT_SESSION] = ag["session"]
        if adopt:
            extra[_agent.ENV_AGENT_ADOPT] = ",".join(
                f"{r}:{pid}:{inc}" for r, (pid, inc) in sorted(
                    adopt.items()))
        if self._host_ids_env:
            extra[ENV_HOST_IDS] = self._host_ids_env
        env = worker_env(
            0, self.np, self.server.address, mca=self._worker_mca(),
            cpu_devices=self.cpu_devices, extra_env=extra,
            telemetry_addr=self.aggregator.ingest_address)
        env.pop(ENV_PROC, None)  # the agent is not a rank
        cmd = [sys.executable, "-m", "ompi_tpu.serve.agent"]
        p = subprocess.Popen(
            _final_cmd(self.launch_agent, cmd, env, ag["name"]),
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        t = threading.Thread(
            target=_forward,
            args=(p.stdout, f"h{hid}", sys.stdout.buffer), daemon=True)
        t.start()
        self._threads.append(t)
        ag["proc"] = p
        # supersession fence: the CURRENT session, visible to a
        # predecessor agent that wedged past serve_agent_timeout and
        # later un-wedges — it reads the mismatch at heartbeat cadence
        # and exits instead of executing its stale session's commands
        self.server.put_local(f"{_agent.K_ASESSION}{hid}",
                              ag["session"])
        self._journal_ev("agent", host=hid, session=ag["session"],
                         rsh_pid=p.pid)
        print(f"[tpud] launch agent h{hid} ({ag['name']}) spawned "
              f"(session {ag['session']}, ranks {ag['ranks']})",
              flush=True)

    def _agent_cmd(self, hid: int, cmd: dict) -> int:
        """Publish one command on the agent's current session stream;
        spawn commands are tracked until their ack (the real worker
        pid) arrives — an agent respawn re-issues unacked ones into
        the fresh session.  Under ``self._lock`` (re-entrant): HTTP
        handlers (/scale) and the monitor thread both publish, and an
        unlocked read-increment of the cursor could hand two commands
        the same stream index (the later put overwrites the earlier —
        a silently lost spawn/kill)."""
        with self._lock:
            ag = self._agents[hid]
            idx = ag["cursor"]
            ag["cursor"] += 1
            d = dict(cmd)
            self.server.put_local(
                f"{_agent.K_ACMD}{ag['session']}.{hid}.{idx}", d)
            if d.get("kind") in ("spawn", "adopt"):
                ag["pending"][idx] = d
            return idx

    def _agent_worker_state(self, hid: int, rank: int) -> dict | None:
        ag = self._agents.get(hid)
        hb = (ag or {}).get("hb") or {}
        return (hb.get("workers") or {}).get(str(rank))

    def _agent_kill(self, hid: int, rank: int, sig: int) -> None:
        try:
            self._agent_cmd(hid, {"kind": "kill", "rank": rank,
                                  "sig": int(sig)})
        except KeyError:
            pass

    def _rank_alive(self, rank: int, pid: int) -> bool:
        """Liveness probe that respects host placement: local ranks
        use the pid; remote ranks route through the owning agent's
        heartbeat table (``kill 0`` cannot cross hosts).  An agent
        that has not reported yet falls back to the pid probe — exact
        on the emulated-host harness (shared pid namespace), best-
        effort on real remote hosts until the heartbeat lands."""
        hid = self._rank_hid[rank]
        if hid is not None:
            st = self._agent_worker_state(hid, rank)
            if st is not None:
                return bool(st.get("alive"))
        return _state.pid_alive(pid)

    def _poll_agents(self) -> None:
        """One monitor-tick look at every launch agent: fold in fresh
        heartbeats, collect spawn acks (journal the real pid),
        re-adopt agents offering themselves to a restarted daemon, and
        respawn agents whose launch process died or whose heartbeats
        went silent — the reborn agent re-adopts still-live workers
        from the last-known pid table.  Runs under ``self._lock``
        (re-entrant) like every other mutator of the per-agent
        session/cursor/pending state — an HTTP-thread /scale racing a
        session rotation must not split a command across sessions."""
        if not self._agents:
            return
        now = time.monotonic()
        timeout = self._agent_var("agent_timeout", 10.0)
        hb_only = bool(self._agent_var("agent_hb_only", 0.0))
        with self._lock:
            self._poll_agents_locked(now, timeout, hb_only)

    def _poll_agents_locked(self, now: float, timeout: float,
                            hb_only: bool = False) -> None:
        for hid, ag in self._agents.items():
            hb = self.server.peek(f"{_agent.K_AHB}{hid}")
            if hb and hb.get("session") == ag["session"]:
                if hb is not ag["hb"]:
                    prev = ag["hb"] or {}
                    if hb.get("ts_ns") != prev.get("ts_ns"):
                        ag["hb_mono"] = now
                    ag["hb"] = hb
                for r, st in (hb.get("workers") or {}).items():
                    if int(st.get("pid", 0)):
                        ag["worker_pids"][int(r)] = (
                            int(st["pid"]), int(st.get("incarnation", 0)))
            # adoption offer from an agent that outlived a daemon crash
            offer = self.server.peek(f"{_agent.K_AADOPT}{hid}")
            if (ag["status"] == "adopting" and offer
                    and int(offer.get("generation", 0))
                    == self.generation):
                ag["session"] = f"g{self.generation}s0"
                # the adoption claims the s0 session name — a later
                # agent RESPAWN must take s1+, not collide with the
                # adopted stream's consumed indices
                ag["spawns"] = max(ag["spawns"], 1)
                ag["cursor"] = 0
                ag["pending"] = {}
                ag["status"] = "active"
                ag["proc"] = None  # not our child: liveness via hb
                ag["hb"] = {"pid": offer.get("pid"),
                            "session": ag["session"],
                            "workers": offer.get("workers") or {}}
                ag["hb_mono"] = now
                for r, st in (offer.get("workers") or {}).items():
                    if int(st.get("pid", 0)):
                        ag["worker_pids"][int(r)] = (
                            int(st["pid"]), int(st.get("incarnation", 0)))
                self.server.put_local(f"{_agent.K_ASESSION}{hid}",
                                      ag["session"])
                self.server.put_local(f"{_agent.K_AADOPTED}{hid}", {
                    "pid": offer.get("pid"),
                    "generation": self.generation,
                    "session": ag["session"]})
                self._journal_ev("agent", host=hid,
                                 session=ag["session"], adopted=True)
                print(f"[tpud] re-adopted agent h{hid} (pid "
                      f"{offer.get('pid')})", flush=True)
            # spawn/adopt acks → the real worker pid, journaled; a
            # FAILED spawn (fork error on the remote host) routes the
            # rank down the normal death leg so the bounded respawn
            # budget retries it instead of wedging it "alive" forever
            for idx in sorted(list(ag["pending"])):
                ack = self.server.peek(
                    f"{_agent.K_AACK}{ag['session']}.{hid}.{idx}")
                if ack is None:
                    continue
                d = ag["pending"].pop(idx)
                r = int(d.get("rank", -1))
                pid = int(ack.get("pid", 0))
                if r >= 0 and not ack.get("ok", True):
                    print(f"[tpud] agent h{hid} could not spawn rank "
                          f"{r}: {ack.get('error', '?')}", flush=True)
                    self._handle_death(r, 1)
                    continue
                if r >= 0 and pid:
                    ag["worker_pids"][r] = (
                        pid, int(d.get("incarnation", 0)))
                    self._journal_ev(
                        "spawn", rank=r, pid=pid, host=hid,
                        incarnation=int(d.get("incarnation", 0)))
            # a restart window that expires with no adoption offer:
            # the agent died WITH the daemon (host failure) — respawn
            # it; the reborn agent re-adopts any still-live workers
            # from the journaled pid table and reports the dead ones
            if ag["status"] == "adopting":
                if (now > self._adopt_deadline
                        and not self.shutting_down):
                    print(f"[tpud] agent h{hid} did not re-attach; "
                          "respawning it", flush=True)
                    self._boot_agent(hid,
                                     adopt=dict(ag["worker_pids"]))
                continue
            # agent death: launch process gone, or heartbeats silent
            if ag["status"] != "active":
                continue
            rsh_dead = (ag["proc"] is not None
                        and ag["proc"].poll() is not None)
            # heartbeat silence since boot/adoption/last hb — a fresh
            # agent that wedges BEFORE its first heartbeat (KVS
            # unreachable, hung boot) with the rsh transport still
            # connected must be declared dead too, not held forever
            silent = now - ag.get("hb_mono", now) > timeout
            # hb-only mode (serve_agent_hb_only): a backgrounding
            # agent template's rsh wrapper daemonizes and exits
            # immediately, so its launch process dying is normal —
            # liveness is judged by heartbeat staleness alone
            dead = silent if hb_only else (rsh_dead or silent)
            if dead and not self.shutting_down:
                if ag["spawns"] > self.max_respawns + 1:
                    print(f"[tpud] agent h{hid} died; respawn budget "
                          "exhausted — host marked down", flush=True)
                    ag["status"] = "down"
                    continue
                print(f"[tpud] agent h{hid} "
                      f"{'exited' if rsh_dead and not hb_only else 'silent'}; "
                      "respawning it (live workers will be "
                      "re-adopted)", flush=True)
                pending = [ag["pending"][i]
                           for i in sorted(ag["pending"])]
                adopt = {r: pi for r, pi in ag["worker_pids"].items()}
                self._boot_agent(hid, adopt=adopt)
                for d in pending:  # unacked work survives the respawn
                    self._agent_cmd(hid, d)

    # -- ops surface (mounted on the aggregator's HTTP endpoint) --------

    def _mount_routes(self) -> None:
        agg = self.aggregator
        agg.add_route("POST", "/submit", self._r_submit)
        agg.add_route("GET", "/jobs", self._r_jobs)
        agg.add_route("GET", "/job", self._r_job)
        agg.add_route("POST", "/drain", self._r_drain)
        agg.add_route("POST", "/shutdown", self._r_shutdown)
        agg.add_route("POST", "/scale", self._r_scale)

    @staticmethod
    def _json(status: int, obj) -> tuple[int, str, bytes]:
        return status, "application/json", json.dumps(obj).encode()

    def _r_submit(self, path, body):
        try:
            req = json.loads(body.decode() or "{}")
        except ValueError:
            return self._json(400, {"error": "bad JSON body"})
        if not req.get("script"):
            return self._json(400, {"error": "missing 'script'"})
        tenant = req.get("tenant") or str(serve_var(self.mca, "tenant"))
        try:
            job = self.queue.submit(
                req["script"], args=req.get("args") or (),
                tenant=tenant, nprocs=req.get("nprocs"),
                env=req.get("env"))
        except AdmissionError as e:
            body: dict = {"error": str(e)}
            if e.retry_after is not None:
                # load-shed rejection: the Retry-After rides both the
                # JSON body and a real HTTP header (RFC-compliant
                # clients back off without parsing the body)
                body["retry_after"] = e.retry_after
                return (*self._json(e.status, body),
                        {"Retry-After": str(int(e.retry_after))})
            return self._json(e.status, body)
        self._journal_ev("submit", job=job)
        return self._json(200, job)

    def _r_jobs(self, path, body):
        st = self.queue.state()
        with self._lock:
            st["procs"] = {
                str(r): {"status": self._status[r],
                         "incarnation": self._incarnation[r],
                         "pid": self._proc_pid(r),
                         **({"log": os.path.join(
                             self.logdir, f"worker.{r}.log")}
                            if self.logdir
                            and isinstance(self._procs[r], _AdoptedProc)
                            else {})}
                for r in range(self.np)}
            st["healthy"] = self._healthy_locked()
            st["cursor"] = self.cursor
            st["generation"] = self.generation
        st["telemetry"] = self.aggregator.jobs_state()
        st["url"] = self.url
        return self._json(200, st)

    def _proc_pid(self, r: int) -> int | None:
        p = self._procs[r]
        pid = getattr(p, "pid", None)
        return (int(pid) if pid is not None
                else self._adopt_pids.get(r))

    def _daemon_counters(self) -> dict:
        """The aggregator's /metrics host-process extension
        (``proc="daemon"`` samples): the queue's serving counters plus
        the daemon-owned hang-diagnosis totals — the deadline path's
        reports are captured HERE, not in any rank."""
        c = dict(self.queue.counters)
        from ompi_tpu.trace import waitgraph as _waitgraph

        if _waitgraph._enabled:
            c.update(_waitgraph.counters_snapshot())
        return c

    def _capture_hang_report(self, job_id: str, procs) -> dict | None:
        """Pre-revoke hang report: assemble the gang's cross-rank
        wait-for graph from the newest telemetry frames while everyone
        is still parked.  Bounded by ``hang_snapshot_timeout_ms``: the
        capture waits that long for at least one blocked-state
        snapshot from the gang (frames arrive at telemetry cadence),
        then reports from whatever it holds — diagnosis must never
        stall the revoke beyond its budget."""
        from ompi_tpu.trace import waitgraph as _waitgraph

        if not _waitgraph._enabled:
            return None
        gang = {int(p) for p in procs}
        deadline = time.monotonic() + self._hang_timeout_s
        while True:
            frames = self.aggregator.latest_frames()
            snaps = {p: f["waits"] for p, f in frames.items()
                     if p in gang and f.get("waits")}
            if snaps or time.monotonic() >= deadline:
                break
            time.sleep(0.05)
        failed: set[int] = set()
        for p, f in frames.items():
            if p in gang:
                failed.update(int(x) for x in (f.get("failed") or ()))
        try:
            return _waitgraph.report(snaps, failed=sorted(failed),
                                     reason=f"deadline:{job_id}")
        except Exception:  # noqa: BLE001 — diagnosis never blocks revoke
            return None

    def _top_state(self) -> dict:
        """The aggregator /json extension (tools/top.py's daemon line):
        liveness identity, journal depth, and the re-adoption picture —
        an operator watching top sees a restarted daemon re-adopt."""
        qs = self.queue.state()
        now = time.monotonic()
        with self._lock:
            agents = {}
            for hid, ag in self._agents.items():
                workers = ((ag.get("hb") or {}).get("workers") or {})
                agents[str(hid)] = {
                    "host": ag["name"],
                    "status": ag["status"],
                    "session": ag["session"],
                    "ranks": list(ag["ranks"]),
                    "pid": int((ag.get("hb") or {}).get("pid", 0)),
                    "hb_age_ms": round(
                        (now - ag.get("hb_mono", now)) * 1e3, 1),
                    "alive_workers": sum(
                        1 for st in workers.values()
                        if st.get("alive")),
                    "spawns": ag["spawns"],
                }
            return {"daemon": {
                "pid": os.getpid(),
                "generation": self.generation,
                "crash_safe": bool(self.pidfile),
                "queued": len(qs["queued"]),
                "outstanding": len(self._outstanding),
                "journal_depth": len(qs["queued"]) + len(self._outstanding),
                "adopting": [r for r in range(self.np)
                             if self._status[r] == "adopting"],
                "procs": {str(r): self._status[r]
                          for r in range(self.np)},
                "draining": self.queue.draining,
                "jobs": {"running": len(qs["running"]),
                         "counters": dict(qs["counters"]),
                         "admission": qs["admission"]},
                **({"agents": agents} if agents else {}),
            }}

    def _r_job(self, path, body):
        job_id = path.rsplit("/", 1)[-1]
        job = self.queue.get(job_id)
        if job is None:
            return self._json(404, {"error": f"no such job {job_id!r}"})
        return self._json(200, job)

    def _r_drain(self, path, body):
        self.queue.draining = True
        self._journal_ev("drain")  # a restart must stay draining
        return self._json(200, {"draining": True})

    def _r_shutdown(self, path, body):
        self.queue.draining = True
        self._journal_ev("drain")
        self.shutting_down = True
        return self._json(200, {"shutting_down": True})

    def _r_scale(self, path, body):
        try:
            want = int(json.loads(body.decode() or "{}")["nprocs"])
        except (ValueError, KeyError):
            return self._json(400, {"error": "body must be "
                                             '{"nprocs": <int>}'})
        if not 0 < want <= self.np:
            return self._json(400, {"error": f"nprocs must be in "
                                             f"[1, {self.np}]"})
        with self._lock:
            active = [r for r in range(self.np)
                      if self._status[r] == "active"]
            if want < len(active):
                retire = active[want:]
                self._publish({"kind": "retire", "procs": active,
                               "retire": retire})
                for r in retire:
                    self._status[r] = "retiring"
                return self._json(200, {"retiring": retire})
            grow = [r for r in range(self.np)
                    if self._status[r] in ("retired", "dead")][
                        :want - len(active)]
            for r in grow:
                self._respawn_locked(r)
            return self._json(
                200, {"restoring": grow} if grow else {"unchanged": True})

    # -- directive stream ------------------------------------------------

    def _publish(self, directive: dict) -> int:
        """Append one directive to the job stream; workers consume
        indices in order, so publication order IS execution order.
        Journaled BEFORE it becomes visible — a crash between the two
        re-publishes it on recovery; consumers dedup by cursor."""
        if _fsim._enabled:
            # chaos (daemonkill:at=N): the Nth publish attempt kills
            # the daemon dead, BEFORE the directive is journaled or
            # visible — the deterministic SIGKILL the restart-hygiene
            # soak replays from one seed.  Repair publishes are their
            # own site (daemon_repair) so a plan can land the kill
            # precisely inside the repair window
            site = ("daemon_repair" if directive.get("kind") == "repair"
                    else "daemon")
            for _r in _fsim.actions(site, kinds={"daemonkill"}):
                print("[tpud] faultsim: injected daemon kill "
                      "(daemonkill)", flush=True)
                sys.stdout.flush()
                os.kill(os.getpid(), signal.SIGKILL)
        with self._lock:
            idx = self.cursor
            self.cursor += 1
            d = dict(directive)
            d["idx"] = idx
            self._outstanding[idx] = {
                "kind": d.get("kind", "job"),
                "procs": list(d.get("procs") or range(self.np)),
                "job_id": d.get("id"),
                "done": {},
                "ts": time.monotonic(),
            }
            self._journal_ev("publish", d=d)
            self.server.put_local(f"{K_JOB}{idx}", d)
            return idx

    def _publish_job(self, job: dict) -> None:
        base = self.cid_next
        self.cid_next += self.cid_block
        job["cid_base"] = base
        job["cid_span"] = self.cid_block
        # job-scoped telemetry: frames from these procs now label this
        # job and /metrics reads relative to this instant's baselines
        self.aggregator.begin_job(job["id"], procs=job["procs"])
        self._publish({"kind": "job", **{
            k: job[k] for k in ("id", "tenant", "script", "args", "env",
                                "procs", "cid_base", "cid_span")}})

    # -- failure / elastic plane ----------------------------------------

    def _respawn_locked(self, rank: int) -> None:
        """Scale-up leg (shared by death recovery and /scale restore):
        relaunch the rank under a bumped incarnation and queue the
        repair that will ``replace()`` it back into the warm world."""
        self._incarnation[rank] += 1
        self._status[rank] = "respawning"
        self._repairing.add(rank)
        self._repair_published = False
        # journal the repair INTENT before anything is visible: a
        # daemon SIGKILLed between this respawn and the replace()
        # completion finishes the repair after restart instead of
        # stranding the reborn worker (cleared by the repair finish)
        self._journal_ev("repair_pending", rank=rank,
                         incarnation=self._incarnation[rank])
        self._procs[rank] = (self._spawn(rank) if self._spawn_workers
                             else None)

    def _handle_death(self, rank: int, rc: int) -> None:
        with self._lock:
            if self._status[rank] == "retiring":
                self._status[rank] = "retired"
                self._journal_ev("retire", ranks=[rank])
                return
            if self.shutting_down and self._shutdown_published:
                self._status[rank] = "exited"
                return
            # a died worker fails its directive's gang: synthesize its
            # completion record so survivors' reports can close it out
            for st in self._outstanding.values():
                if rank in st["procs"] and rank not in st["done"]:
                    st["done"][rank] = {"ok": False,
                                        "error": f"rank died (rc={rc})"}
            if self._incarnation[rank] >= self.max_respawns:
                print(f"[tpud] rank {rank} died (rc={rc}); respawn "
                      f"budget exhausted — marking it dead", flush=True)
                self._status[rank] = "dead"
                return
            print(f"[tpud] rank {rank} died (rc={rc}); respawning "
                  f"(incarnation {self._incarnation[rank] + 1})",
                  flush=True)
            self._respawn_locked(rank)

    def _maybe_publish_repair(self) -> None:
        """Publish ONE repair directive once every rank-set is free:
        survivors run ``replace()`` (awaiting the reborn incarnations),
        the reborn workers rejoin through the replace beacon and then
        resume the stream AFTER this directive (their cursor is the
        ``serve.resume`` key written here)."""
        with self._lock:
            # bystander-quiet gate: only a directive whose gang
            # INTERSECTS the dead set blocks the repair (its members
            # are failing on the dead rank right now and must close
            # out first) — a concurrently running disjoint gang keeps
            # its job while the survivors heal the base world under it
            if (not self._repairing or self._repair_published
                    or any(s == "adopting" for s in self._status)
                    or any(st["kind"] != "repair"
                           and set(st["procs"]) & self._repairing
                           for st in self._outstanding.values())):
                return
            if any(self._status[r] == "respawning" and
                   (self._procs[r] is None or
                    self._procs[r].poll() is not None)
                   for r in self._repairing):
                return  # a respawn died before repair; death path re-arms
            survivors = [r for r in range(self.np)
                         if self._status[r] == "active"]
            if not survivors:
                return
            idx = self._publish({
                "kind": "repair", "procs": survivors,
                "dead": sorted(self._repairing)})
            for r in sorted(self._repairing):
                self.server.put_local(
                    f"{K_RESUME}{r}.i{self._incarnation[r]}", idx + 1)
            self._repair_published = True

    # -- monitor loop ----------------------------------------------------

    def _admission_update(self) -> None:
        """Fold one tick of the daemon's OWN telemetry feeds into the
        admission controller: per-proc cumulative stall sums
        (ring + CTS + device-DMA wait, straight off the newest frames),
        detector health, and the /critical dominant cause for the 429
        message.  Ticks that saw no fresh frame are skipped while the
        mesh is healthy — the controller's streak must advance at
        telemetry cadence, not at the much faster monitor cadence, or
        the zero-delta gap between frames would reset it every time."""
        ctrl = self.queue.admission
        if ctrl is None or not ctrl.enabled():
            return
        latest = self.aggregator.latest_frames()
        fresh = False
        stalls: dict[int, int] = {}
        for p, frame in latest.items():
            ts = int(frame.get("ts_ns", 0))
            if ts != self._adm_seen.get(p):
                fresh = True
                self._adm_seen[p] = ts
            nat = frame.get("native") or {}
            stalls[p] = (int(nat.get("ring_stall_ns", 0))
                         + int(nat.get("cts_wait_ns", 0))
                         + int(nat.get("device_dma_wait_ns", 0)))
        with self._lock:
            healthy = self._healthy_locked()
        if not fresh and healthy and not ctrl.unhealthy:
            return
        cause = ""
        try:
            dom = self.aggregator.critical_state().get("dominant")
            cause = str((dom.get("cause") if isinstance(dom, dict)
                         else dom) or "")
        except Exception:  # noqa: BLE001 — admission over blame detail
            pass
        ctrl.update(stalls, healthy=healthy, cause=cause)

    def _healthy_locked(self) -> bool:
        return not self._repairing and all(
            s in ("active", "retired", "dead", "exited")
            for s in self._status)

    def _poll_workers(self) -> None:
        for r in range(self.np):
            p = self._procs[r]
            if p is None or self._status[r] in ("retired", "dead",
                                                "exited"):
                continue
            rc = p.poll()
            if rc is not None:
                self._handle_death(r, rc or 0)

    def _collect_done(self) -> None:
        done_idx = []
        revoke: list[tuple[str, list[int]]] = []
        with self._lock:
            for idx, st in self._outstanding.items():
                for r in st["procs"]:
                    if r in st["done"]:
                        continue
                    rec = self.server.peek(f"{K_DONE}{idx}.{r}")
                    if rec is not None:
                        st["done"][r] = rec
                if len(st["done"]) >= len(st["procs"]):
                    done_idx.append(idx)
                    continue
                if st["kind"] != "job":
                    continue
                elapsed = time.monotonic() - st["ts"]
                if (self.job_deadline > 0 and not st.get("revoked")
                        and elapsed > self.job_deadline):
                    # ULFM-grade deadline escalation: revoke exactly
                    # this job's comm — its gang wakes out of any
                    # parked collective with MPIRevokedError and
                    # reports a typed failure; the ranks stay ALIVE
                    # and concurrent disjoint gangs never notice
                    # (serve_job_timeout below stays the harder,
                    # rank-killing bound)
                    print(f"[tpud] job {st['job_id']} exceeded "
                          f"serve_job_deadline_s={self.job_deadline:g}"
                          "s; revoking its comm", flush=True)
                    st["revoked"] = True
                    st["deadline_hit"] = True
                    self.queue.counters["jobs_deadline_expired"] += 1
                    revoke.append((st["job_id"], list(st["procs"])))
                if (self.job_timeout > 0
                        and elapsed > self.job_timeout):
                    # job overran its budget: reclaim the rank-set by
                    # killing its members — the death path respawns and
                    # repairs them (the elastic plane as the enforcer)
                    print(f"[tpud] job {st['job_id']} exceeded "
                          f"serve_job_timeout={self.job_timeout}s; "
                          f"killing its ranks", flush=True)
                    st["ts"] = float("inf")
                    for r in st["procs"]:
                        q = self._procs[r]
                        if q is not None and q.poll() is None:
                            q.terminate()
        for job_id, procs in revoke:
            # capture the hang report BEFORE the revoke wakes the gang:
            # revoked waits unregister themselves, so the blocked-state
            # evidence evaporates the moment the directive lands
            hang = self._capture_hang_report(job_id, procs)
            if hang is not None:
                with self._lock:
                    for st in self._outstanding.values():
                        if (st["kind"] == "job"
                                and st.get("job_id") == job_id):
                            st["hang"] = hang
            self._publish({"kind": "revoke", "procs": procs,
                           "id": job_id})
        for idx in done_idx:
            self._finish_directive(idx)

    def _finish_directive(self, idx: int) -> None:
        with self._lock:
            st = self._outstanding.pop(idx)
        if st["kind"] == "job":
            bad = [f"rank {r}: {rec.get('error', '?')}"
                   for r, rec in sorted(st["done"].items())
                   if not rec.get("ok")]
            error = "; ".join(bad)
            died = any("rank died" in rec.get("error", "")
                       or "mesh lost" in rec.get("error", "")
                       for rec in st["done"].values()
                       if not rec.get("ok"))
            if bad and st.get("deadline_hit"):
                # typed failure the client reads off /job/<id>; a
                # deadline kill is policy, never retried
                error = ("DeadlineExpired: exceeded "
                         f"serve_job_deadline_s={self.job_deadline:g}s"
                         f"; {error}")
            elif bad and died:
                # mesh repair killed the job, not the job itself:
                # serve_retry_budget buys it automatic re-enqueues —
                # the close-the-attempt + re-queue pair is ONE journal
                # line, so a daemon crash on either side of it replays
                # to exactly one more attempt (exactly-once)
                job = self.queue.retry(st["job_id"])
                if job is not None:
                    self._journal_ev("retry", idx=idx, job=job)
                    print(f"[tpud] job {job['id']} killed by mesh "
                          f"repair; re-queued (retry {job['retries']}"
                          f"/{self.queue.retry_budget})", flush=True)
                    return
                if self.queue.retry_budget > 0:
                    error = ("RetryBudgetExhausted: serve_retry_budget"
                             f"={self.queue.retry_budget} consumed; "
                             f"{error}")
            job = self.queue.finish(st["job_id"], ok=not bad,
                                    error=error,
                                    ranks=st["done"],
                                    hang=st.get("hang"))
            self._journal_ev("finish", idx=idx, kind="job", job=job)
            if job is not None:
                print(f"[tpud] job {job['id']} ({job['tenant']}) "
                      f"{job['state']}", flush=True)
        elif st["kind"] == "repair":
            with self._lock:
                for r in self._repairing:
                    if self._status[r] == "respawning":
                        self._status[r] = "active"
                self._repairing.clear()
                self._repair_published = False
            self._journal_ev("finish", idx=idx, kind="repair")
            print("[tpud] repair complete: mesh restored", flush=True)
        elif st["kind"] == "revoke":
            # the revocation itself: members acked poisoning the comm;
            # the JOB directive still closes separately (its gang's
            # typed failure reports drive the branch above)
            self._journal_ev("finish", idx=idx, kind="revoke")
        elif st["kind"] == "retire":
            with self._lock:
                done = [r for r in range(self.np)
                        if self._status[r] == "retiring"]
                for r in done:
                    self._status[r] = "retired"
            if done:
                self._journal_ev("retire", ranks=done)
            self._journal_ev("finish", idx=idx, kind="retire")

    def _busy_procs(self) -> set[int]:
        with self._lock:
            return {r for st in self._outstanding.values()
                    for r in st["procs"]}

    def _booted(self) -> bool:
        """Mesh boot gate: a rank worker's ``wsize.<r>`` modex publish
        is its I-am-up beacon — scheduling (and therefore the
        daemonkill directive counter) must not run ahead of workers
        that are still importing.  Without this, a daemon crash in the
        boot window strands directives no worker ever saw AND kills
        the workers at their first KVS dial (found by the
        --daemon-restart soak's own race)."""
        if not self._spawn_workers:
            return True  # workerless harness pumps the stream itself
        return all(self.server.peek(f"wsize.{r}") is not None
                   for r in range(self.np)
                   if self._status[r] == "active")

    def _schedule(self) -> None:
        with self._lock:
            if not self._healthy_locked() or self._shutdown_published:
                return
            active = {r for r in range(self.np)
                      if self._status[r] == "active"}
        if not self._booted():
            return
        free = active - self._busy_procs()
        while True:
            job = self.queue.next_runnable(free)
            if job is None:
                return
            if job["nprocs"] > len(active):
                self.queue.finish(
                    job["id"], ok=False,
                    error=f"needs {job['nprocs']} procs; only "
                          f"{len(active)} active")
                continue
            self._publish_job(job)
            free -= set(job["procs"])

    def _maybe_shutdown(self) -> bool:
        with self._lock:
            if not self.shutting_down or self._shutdown_published:
                return self._shutdown_published
            if self._outstanding or not self.queue.idle():
                return False
            active = [r for r in range(self.np)
                      if self._status[r] == "active"]
            self._publish({"kind": "shutdown", "procs": active})
            self._shutdown_published = True
            return True

    def step(self) -> None:
        """One monitor tick (public so tests can drive the loop
        deterministically)."""
        self._poll_agents()
        self._poll_adoption()
        self._poll_workers()
        self._collect_done()
        self._maybe_publish_repair()
        self._admission_update()
        self._schedule()
        self._maybe_shutdown()

    def run(self) -> int:
        """Blocking monitor loop until shutdown completes."""
        print(f"[tpud] ops: {self.url}/jobs (submit: python "
              f"tools/tpud_ctl.py --url {self.url} submit <script>; "
              f"scrape: {self.url}/metrics)", flush=True)
        def _sigterm(*_):
            # same contract as POST /shutdown: stop admitting AND stop
            # serving — shutting_down alone would keep accepting jobs
            # and never drain under continued submit traffic
            self.queue.draining = True
            self.shutting_down = True

        try:
            signal.signal(signal.SIGTERM, _sigterm)
        except ValueError:
            pass  # non-main thread (tests): SIGTERM stays default
        try:
            while True:
                self.step()
                if self._shutdown_published:
                    live = [p for p in self._procs
                            if p is not None and p.poll() is None]
                    if not live:
                        break
                time.sleep(0.05)
        except KeyboardInterrupt:
            self.shutting_down = True
            self.exit_code = 130
        finally:
            self.close()
        return self.exit_code

    def close(self) -> None:
        self.queue.fail_queued("daemon shut down")
        deadline = time.monotonic() + 10
        for p in self._procs:
            while (p is not None and p.poll() is None
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            if p is not None and p.poll() is None:
                p.kill()
        # stop the launch agents (their workers are already down):
        # each acks the stop, sweeps any leftover worker on its host,
        # and exits — taking the rsh leg down with it
        for hid, ag in self._agents.items():
            if ag["status"] in ("down",):
                continue
            try:
                self._agent_cmd(hid, {"kind": "stop"})
            except Exception:  # noqa: BLE001 — exiting anyway
                pass
        adeadline = time.monotonic() + 10
        for hid, ag in self._agents.items():
            p = ag.get("proc")
            while (p is not None and p.poll() is None
                   and time.monotonic() < adeadline):
                time.sleep(0.05)
            if p is not None and p.poll() is None:
                p.kill()
            if p is None:
                # adopted agent (not our child): best-effort local
                # signal sweep — exact on the emulated-host harness
                pid = int((ag.get("hb") or {}).get("pid", 0))
                while (pid and _state.pid_alive(pid)
                       and time.monotonic() < adeadline):
                    time.sleep(0.05)
                if pid and _state.pid_alive(pid):
                    try:
                        os.kill(pid, signal.SIGKILL)
                    except OSError:
                        pass
        for t in self._threads:
            t.join(timeout=5)
        self.aggregator.close()
        self.server.close()
        # clean release: the journal is REMOVED (nothing durable
        # remains to recover, and an append-only file reused across
        # many daemon lifetimes would grow without bound) and the
        # pidfile lifts — the next daemon starts fresh instead of
        # "recovering" a shutdown it misreads as a crash.  The
        # shutdown event is still written first: if the unlink loses a
        # race (or the operator copies the journal mid-shutdown), the
        # tail says clean.
        if self._journal is not None:
            self._journal_ev("shutdown", generation=self.generation)
            self._journal.close()
            self._journal = None
            try:
                os.unlink(self.journal_path)
            except OSError:
                pass
        if self.pidfile:
            _state.remove_pidfile(self.pidfile)


class _RemoteProc:
    """A rank owned by a per-host launch agent: the Popen surface the
    monitor loop touches, with liveness routed through the owning
    agent's heartbeat table — the daemon shares no pid namespace with
    the worker, so ``poll()`` reads the agent's report instead of a
    local wait/kill-0, and ``terminate``/``kill`` publish agent
    commands.  A table entry for a PRIOR incarnation is ignored
    (stale: the respawn command is still in flight)."""

    def __init__(self, daemon: "TpuDaemon", rank: int, hid: int,
                 incarnation: int):
        self._d = daemon
        self.rank = int(rank)
        self.hid = int(hid)
        self.incarnation = int(incarnation)
        self.pid: int | None = None
        self.returncode: int | None = None

    def poll(self) -> int | None:
        if self.returncode is not None:
            return self.returncode
        st = self._d._agent_worker_state(self.hid, self.rank)
        if st is None:
            return None  # agent has not reported this rank yet
        if int(st.get("incarnation", -1)) != self.incarnation:
            return None  # stale table: the spawn is still in flight
        if int(st.get("pid", 0)):
            self.pid = int(st["pid"])
        if not st.get("alive", True):
            self.returncode = int(st.get("rc", 1))
        return self.returncode

    def terminate(self) -> None:
        self._d._agent_kill(self.hid, self.rank, signal.SIGTERM)

    def kill(self) -> None:
        self._d._agent_kill(self.hid, self.rank, signal.SIGKILL)

    def wait(self, timeout: float | None = None) -> int:
        deadline = time.monotonic() + (timeout or 0)
        while self.poll() is None:
            if timeout is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("remote", timeout)
            time.sleep(0.05)
        return self.returncode  # type: ignore[return-value]


class _AdoptedProc:
    """A re-adopted resident worker: not our child, so no Popen — a
    pid wrapper with the Popen surface the monitor loop touches.
    ``poll()`` can only report liveness (the real exit code reaps to
    init), so death reads as a synthetic rc 1 — enough for the
    respawn machinery, which only branches on nonzero."""

    def __init__(self, pid: int):
        self.pid = int(pid)
        self.returncode: int | None = None

    def poll(self) -> int | None:
        if self.returncode is None and not _state.pid_alive(self.pid):
            self.returncode = 1
        return self.returncode

    def _signal(self, sig: int) -> None:
        try:
            os.kill(self.pid, sig)
        except OSError:
            pass

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def wait(self, timeout: float | None = None) -> int:
        deadline = time.monotonic() + (timeout or 0)
        while self.poll() is None:
            if timeout is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("adopted", timeout)
            time.sleep(0.05)
        return self.returncode  # type: ignore[return-value]


def run_daemon(np_: int, mca: dict[str, str] | None = None,
               cpu_devices: int | None = None, max_respawns: int = 2,
               http_port: int | None = None,
               hosts: list[tuple[str, int]] | None = None,
               map_by: str = "slot",
               launch_agent: str = "ssh {host} {cmd}",
               kvs_host: str | None = None,
               oversubscribe: bool = False) -> int:
    """The ``tpurun --daemon`` / ``tools/tpud.py`` entry."""
    try:
        d = TpuDaemon(np_, mca=mca, cpu_devices=cpu_devices,
                      max_respawns=max_respawns, http_port=http_port,
                      hosts=hosts, map_by=map_by,
                      launch_agent=launch_agent, kvs_host=kvs_host,
                      oversubscribe=oversubscribe)
    except _state.DaemonAlreadyRunning as e:
        # idempotent start: a second `tpurun --daemon` against a live
        # pidfile is a clean one-liner, not a traceback
        print(f"tpud: {e}", flush=True)
        return 1
    return d.run()
