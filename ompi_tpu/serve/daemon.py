"""``tpud`` — the persistent serving daemon (≈ orted/prted).

One daemon process owns the standing infrastructure a ``tpurun`` job
normally builds and discards per invocation:

* the boot **KVS** (rendezvous server) — resident workers boot against
  it once and then treat it as the job stream: the daemon publishes
  numbered directives (``serve.job.<n>``), workers long-poll them and
  answer with completion records (``serve.done.<n>.<proc>``);
* the **live-telemetry aggregator** — always on; its HTTP endpoint is
  the daemon's ops surface (``/submit``, ``/jobs``, ``/job/<id>``,
  ``/drain``, ``/shutdown``, ``/scale`` mounted next to the PR-5
  ``/metrics``/``/json``/``/history`` scrape endpoints), and its
  queue-depth/health feeds drive admission and scheduling;
* N **resident rank workers** (``ompi_tpu.serve.worker``) whose DCN
  endpoints — both planes — engine threads, and compiled collective
  state stay warm across jobs;
* the **elastic plane, daemon-fired**: a dead worker is respawned
  under a bumped incarnation and restored by a ``repair`` directive
  (survivors run ``replace()``, the reborn rank rejoins — scale-up),
  and ``/scale`` retires ranks (scale-down) or brings retirees back
  through the same respawn+repair leg.

Scheduling is **gang** FIFO with per-tenant round-robin fairness
(:mod:`~ompi_tpu.serve.queue`): a job is published only when its full
rank-set is free, and never while the mesh is unhealthy (dead worker,
repair outstanding) — the telemetry plane's detector feed gating the
job stream.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

from ompi_tpu.boot.kvs import KVSServer
from ompi_tpu.boot.proc import ENV_INCARNATION
from ompi_tpu.boot.tpurun import _forward, worker_env
from ompi_tpu.core.var import ENV_PREFIXES, SERVING_VARS, full_var_name
from ompi_tpu.metrics.live import TelemetryAggregator
from .queue import AdmissionError, JobQueue

#: KVS key prefixes of the serve protocol (workers mirror these)
K_JOB = "serve.job."        # + <n>            → directive JSON
K_DONE = "serve.done."      # + <n>.<proc>     → completion record
K_RESUME = "serve.resume."  # + <proc>.i<inc>  → reborn worker's cursor


def serve_var(mca: dict | None, name: str):
    """Resolve one ``serve_<name>`` knob daemon-side (no MCA context in
    the launcher process, same as tpurun's telemetry gate): ``--mca``
    dict → ``OMPI_MCA_*`` env → the SERVING_VARS default."""
    full = f"serve_{name}"
    if mca and full in mca:
        return mca[full]
    for prefix in ENV_PREFIXES:
        v = os.environ.get(prefix + full)
        if v is not None:
            return v
    for fw, comp, n, default, _typ, _h in SERVING_VARS:
        if full_var_name(fw, comp, n) == full:
            return default
    raise KeyError(full)


class TpuDaemon:
    """The serving daemon.  ``spawn=False`` builds the full control
    plane (KVS, aggregator, queue, ops routes) without resident
    workers — the selftest/unit harness pumps the job stream itself."""

    def __init__(self, np_: int, mca: dict[str, str] | None = None,
                 cpu_devices: int | None = None, max_respawns: int = 2,
                 http_port: int | None = None, spawn: bool = True):
        self.np = int(np_)
        self.mca = dict(mca or {})
        self.cpu_devices = cpu_devices
        self.max_respawns = int(max_respawns)
        self._spawn_workers = spawn
        self.cid_block = int(serve_var(self.mca, "cid_block"))
        self.cid_next = int(serve_var(self.mca, "cid_base"))
        self.job_timeout = float(serve_var(self.mca, "job_timeout"))
        self._lock = threading.RLock()
        self.server = KVSServer()
        self.aggregator = TelemetryAggregator(
            http_port=(int(serve_var(self.mca, "port"))
                       if http_port is None else int(http_port)))
        self.url = self.aggregator.url
        self.queue = JobQueue(
            self.np, max_pending=int(serve_var(self.mca, "max_pending")))
        self._mount_routes()
        #: next directive index (the job-stream cursor)
        self.cursor = 0
        #: directive index → bookkeeping ({kind, procs, job_id, done})
        self._outstanding: dict[int, dict] = {}
        #: per-proc worker state: process handle + incarnation + status
        #: in {"active", "dead", "retired", "exited"}
        self._procs: list[subprocess.Popen | None] = [None] * self.np
        self._incarnation = [0] * self.np
        self._status = ["active"] * self.np
        self._threads: list[threading.Thread] = []
        #: procs awaiting the repair directive (respawned, not yet
        #: restored into the world by the survivors' replace())
        self._repairing: set[int] = set()
        self._repair_published = False
        self.shutting_down = False
        self._shutdown_published = False
        self.exit_code = 0
        if spawn:
            for rank in range(self.np):
                self._procs[rank] = self._spawn(rank)

    # -- worker lifecycle ------------------------------------------------

    def _worker_mca(self) -> dict[str, str]:
        m = dict(self.mca)
        # the serving plane is built ON the observability + elastic
        # planes: frames feed the ops surface, the detector feeds
        # repair — both non-negotiable for a daemon
        m["telemetry_enable"] = "1"
        m["ft_detector_enable"] = "1"
        return m

    def _spawn(self, rank: int) -> subprocess.Popen:
        env = worker_env(
            rank, self.np, self.server.address, mca=self._worker_mca(),
            cpu_devices=self.cpu_devices,
            telemetry_addr=self.aggregator.ingest_address)
        if self._incarnation[rank]:
            env[ENV_INCARNATION] = str(self._incarnation[rank])
        p = subprocess.Popen(
            [sys.executable, "-m", "ompi_tpu.serve.worker"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        t = threading.Thread(
            target=_forward, args=(p.stdout, str(rank), sys.stdout.buffer),
            daemon=True)
        t.start()
        self._threads.append(t)
        return p

    # -- ops surface (mounted on the aggregator's HTTP endpoint) --------

    def _mount_routes(self) -> None:
        agg = self.aggregator
        agg.add_route("POST", "/submit", self._r_submit)
        agg.add_route("GET", "/jobs", self._r_jobs)
        agg.add_route("GET", "/job", self._r_job)
        agg.add_route("POST", "/drain", self._r_drain)
        agg.add_route("POST", "/shutdown", self._r_shutdown)
        agg.add_route("POST", "/scale", self._r_scale)

    @staticmethod
    def _json(status: int, obj) -> tuple[int, str, bytes]:
        return status, "application/json", json.dumps(obj).encode()

    def _r_submit(self, path, body):
        try:
            req = json.loads(body.decode() or "{}")
        except ValueError:
            return self._json(400, {"error": "bad JSON body"})
        if not req.get("script"):
            return self._json(400, {"error": "missing 'script'"})
        tenant = req.get("tenant") or str(serve_var(self.mca, "tenant"))
        try:
            job = self.queue.submit(
                req["script"], args=req.get("args") or (),
                tenant=tenant, nprocs=req.get("nprocs"),
                env=req.get("env"))
        except AdmissionError as e:
            return self._json(e.status, {"error": str(e)})
        return self._json(200, job)

    def _r_jobs(self, path, body):
        st = self.queue.state()
        with self._lock:
            st["procs"] = {
                str(r): {"status": self._status[r],
                         "incarnation": self._incarnation[r]}
                for r in range(self.np)}
            st["healthy"] = self._healthy_locked()
            st["cursor"] = self.cursor
        st["telemetry"] = self.aggregator.jobs_state()
        st["url"] = self.url
        return self._json(200, st)

    def _r_job(self, path, body):
        job_id = path.rsplit("/", 1)[-1]
        job = self.queue.get(job_id)
        if job is None:
            return self._json(404, {"error": f"no such job {job_id!r}"})
        return self._json(200, job)

    def _r_drain(self, path, body):
        self.queue.draining = True
        return self._json(200, {"draining": True})

    def _r_shutdown(self, path, body):
        self.queue.draining = True
        self.shutting_down = True
        return self._json(200, {"shutting_down": True})

    def _r_scale(self, path, body):
        try:
            want = int(json.loads(body.decode() or "{}")["nprocs"])
        except (ValueError, KeyError):
            return self._json(400, {"error": "body must be "
                                             '{"nprocs": <int>}'})
        if not 0 < want <= self.np:
            return self._json(400, {"error": f"nprocs must be in "
                                             f"[1, {self.np}]"})
        with self._lock:
            active = [r for r in range(self.np)
                      if self._status[r] == "active"]
            if want < len(active):
                retire = active[want:]
                self._publish({"kind": "retire", "procs": active,
                               "retire": retire})
                for r in retire:
                    self._status[r] = "retiring"
                return self._json(200, {"retiring": retire})
            grow = [r for r in range(self.np)
                    if self._status[r] in ("retired", "dead")][
                        :want - len(active)]
            for r in grow:
                self._respawn_locked(r)
            return self._json(
                200, {"restoring": grow} if grow else {"unchanged": True})

    # -- directive stream ------------------------------------------------

    def _publish(self, directive: dict) -> int:
        """Append one directive to the job stream; workers consume
        indices in order, so publication order IS execution order."""
        with self._lock:
            idx = self.cursor
            self.cursor += 1
            d = dict(directive)
            d["idx"] = idx
            self._outstanding[idx] = {
                "kind": d.get("kind", "job"),
                "procs": list(d.get("procs") or range(self.np)),
                "job_id": d.get("id"),
                "done": {},
                "ts": time.monotonic(),
            }
            self.server.put_local(f"{K_JOB}{idx}", d)
            return idx

    def _publish_job(self, job: dict) -> None:
        base = self.cid_next
        self.cid_next += self.cid_block
        job["cid_base"] = base
        job["cid_span"] = self.cid_block
        # job-scoped telemetry: frames from these procs now label this
        # job and /metrics reads relative to this instant's baselines
        self.aggregator.begin_job(job["id"], procs=job["procs"])
        self._publish({"kind": "job", **{
            k: job[k] for k in ("id", "tenant", "script", "args", "env",
                                "procs", "cid_base", "cid_span")}})

    # -- failure / elastic plane ----------------------------------------

    def _respawn_locked(self, rank: int) -> None:
        """Scale-up leg (shared by death recovery and /scale restore):
        relaunch the rank under a bumped incarnation and queue the
        repair that will ``replace()`` it back into the warm world."""
        self._incarnation[rank] += 1
        self._status[rank] = "respawning"
        self._repairing.add(rank)
        self._repair_published = False
        self._procs[rank] = self._spawn(rank)

    def _handle_death(self, rank: int, rc: int) -> None:
        with self._lock:
            if self._status[rank] == "retiring":
                self._status[rank] = "retired"
                return
            if self.shutting_down and self._shutdown_published:
                self._status[rank] = "exited"
                return
            # a died worker fails its directive's gang: synthesize its
            # completion record so survivors' reports can close it out
            for st in self._outstanding.values():
                if rank in st["procs"] and rank not in st["done"]:
                    st["done"][rank] = {"ok": False,
                                        "error": f"rank died (rc={rc})"}
            if self._incarnation[rank] >= self.max_respawns:
                print(f"[tpud] rank {rank} died (rc={rc}); respawn "
                      f"budget exhausted — marking it dead", flush=True)
                self._status[rank] = "dead"
                return
            print(f"[tpud] rank {rank} died (rc={rc}); respawning "
                  f"(incarnation {self._incarnation[rank] + 1})",
                  flush=True)
            self._respawn_locked(rank)

    def _maybe_publish_repair(self) -> None:
        """Publish ONE repair directive once every rank-set is free:
        survivors run ``replace()`` (awaiting the reborn incarnations),
        the reborn workers rejoin through the replace beacon and then
        resume the stream AFTER this directive (their cursor is the
        ``serve.resume`` key written here)."""
        with self._lock:
            if (not self._repairing or self._repair_published
                    or any(st["kind"] != "repair"
                           for st in self._outstanding.values())):
                return
            if any(self._status[r] == "respawning" and
                   (self._procs[r] is None or
                    self._procs[r].poll() is not None)
                   for r in self._repairing):
                return  # a respawn died before repair; death path re-arms
            survivors = [r for r in range(self.np)
                         if self._status[r] == "active"]
            if not survivors:
                return
            idx = self._publish({
                "kind": "repair", "procs": survivors,
                "dead": sorted(self._repairing)})
            for r in sorted(self._repairing):
                self.server.put_local(
                    f"{K_RESUME}{r}.i{self._incarnation[r]}", idx + 1)
            self._repair_published = True

    # -- monitor loop ----------------------------------------------------

    def _healthy_locked(self) -> bool:
        return not self._repairing and all(
            s in ("active", "retired", "dead", "exited")
            for s in self._status)

    def _poll_workers(self) -> None:
        for r in range(self.np):
            p = self._procs[r]
            if p is None or self._status[r] in ("retired", "dead",
                                                "exited"):
                continue
            rc = p.poll()
            if rc is not None:
                self._handle_death(r, rc or 0)

    def _collect_done(self) -> None:
        done_idx = []
        with self._lock:
            for idx, st in self._outstanding.items():
                for r in st["procs"]:
                    if r in st["done"]:
                        continue
                    rec = self.server.peek(f"{K_DONE}{idx}.{r}")
                    if rec is not None:
                        st["done"][r] = rec
                if len(st["done"]) >= len(st["procs"]):
                    done_idx.append(idx)
                elif (st["kind"] == "job" and self.job_timeout > 0
                      and time.monotonic() - st["ts"] > self.job_timeout):
                    # job overran its budget: reclaim the rank-set by
                    # killing its members — the death path respawns and
                    # repairs them (the elastic plane as the enforcer)
                    print(f"[tpud] job {st['job_id']} exceeded "
                          f"serve_job_timeout={self.job_timeout}s; "
                          f"killing its ranks", flush=True)
                    st["ts"] = float("inf")
                    for r in st["procs"]:
                        q = self._procs[r]
                        if q is not None and q.poll() is None:
                            q.terminate()
        for idx in done_idx:
            self._finish_directive(idx)

    def _finish_directive(self, idx: int) -> None:
        with self._lock:
            st = self._outstanding.pop(idx)
        if st["kind"] == "job":
            bad = [f"rank {r}: {rec.get('error', '?')}"
                   for r, rec in sorted(st["done"].items())
                   if not rec.get("ok")]
            job = self.queue.finish(st["job_id"], ok=not bad,
                                    error="; ".join(bad),
                                    ranks=st["done"])
            if job is not None:
                print(f"[tpud] job {job['id']} ({job['tenant']}) "
                      f"{job['state']}", flush=True)
        elif st["kind"] == "repair":
            with self._lock:
                for r in self._repairing:
                    if self._status[r] == "respawning":
                        self._status[r] = "active"
                self._repairing.clear()
                self._repair_published = False
            print("[tpud] repair complete: mesh restored", flush=True)
        elif st["kind"] == "retire":
            with self._lock:
                for r in range(self.np):
                    if self._status[r] == "retiring":
                        self._status[r] = "retired"

    def _busy_procs(self) -> set[int]:
        with self._lock:
            return {r for st in self._outstanding.values()
                    for r in st["procs"]}

    def _schedule(self) -> None:
        with self._lock:
            if not self._healthy_locked() or self._shutdown_published:
                return
            active = {r for r in range(self.np)
                      if self._status[r] == "active"}
        free = active - self._busy_procs()
        while True:
            job = self.queue.next_runnable(free)
            if job is None:
                return
            if job["nprocs"] > len(active):
                self.queue.finish(
                    job["id"], ok=False,
                    error=f"needs {job['nprocs']} procs; only "
                          f"{len(active)} active")
                continue
            self._publish_job(job)
            free -= set(job["procs"])

    def _maybe_shutdown(self) -> bool:
        with self._lock:
            if not self.shutting_down or self._shutdown_published:
                return self._shutdown_published
            if self._outstanding or not self.queue.idle():
                return False
            active = [r for r in range(self.np)
                      if self._status[r] == "active"]
            self._publish({"kind": "shutdown", "procs": active})
            self._shutdown_published = True
            return True

    def step(self) -> None:
        """One monitor tick (public so tests can drive the loop
        deterministically)."""
        self._poll_workers()
        self._collect_done()
        self._maybe_publish_repair()
        self._schedule()
        self._maybe_shutdown()

    def run(self) -> int:
        """Blocking monitor loop until shutdown completes."""
        print(f"[tpud] ops: {self.url}/jobs (submit: python "
              f"tools/tpud_ctl.py --url {self.url} submit <script>; "
              f"scrape: {self.url}/metrics)", flush=True)
        def _sigterm(*_):
            # same contract as POST /shutdown: stop admitting AND stop
            # serving — shutting_down alone would keep accepting jobs
            # and never drain under continued submit traffic
            self.queue.draining = True
            self.shutting_down = True

        try:
            signal.signal(signal.SIGTERM, _sigterm)
        except ValueError:
            pass  # non-main thread (tests): SIGTERM stays default
        try:
            while True:
                self.step()
                if self._shutdown_published:
                    live = [p for p in self._procs
                            if p is not None and p.poll() is None]
                    if not live:
                        break
                time.sleep(0.05)
        except KeyboardInterrupt:
            self.shutting_down = True
            self.exit_code = 130
        finally:
            self.close()
        return self.exit_code

    def close(self) -> None:
        self.queue.fail_queued("daemon shut down")
        deadline = time.monotonic() + 10
        for p in self._procs:
            while (p is not None and p.poll() is None
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            if p is not None and p.poll() is None:
                p.kill()
        for t in self._threads:
            t.join(timeout=5)
        self.aggregator.close()
        self.server.close()


def run_daemon(np_: int, mca: dict[str, str] | None = None,
               cpu_devices: int | None = None, max_respawns: int = 2,
               http_port: int | None = None) -> int:
    """The ``tpurun --daemon`` / ``tools/tpud.py`` entry."""
    return TpuDaemon(np_, mca=mca, cpu_devices=cpu_devices,
                     max_respawns=max_respawns,
                     http_port=http_port).run()
