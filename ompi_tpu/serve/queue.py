"""Job queue + gang scheduler + per-tenant admission (daemon side).

≈ the reference's plm job-state machinery collapsed to the piece a
single-host serving daemon needs: a FIFO of submitted jobs, scheduled
onto the resident rank-set **gang-style** — a job launches only when
every proc it needs is free — with round-robin fairness across tenants
(one tenant's burst cannot starve another's queue) and an admission
quota per tenant (``serve_max_pending``).

Scheduling is **any-fit**, not head-of-line: within a tenant's FIFO
the first job whose full rank-set fits the currently free procs
launches, so a wide job parked at the head cannot starve narrow jobs
behind it while disjoint ranks sit idle (``serve_max_concurrent``
bounds how many gangs overlap; 0 = any fit).

The :class:`AdmissionController` adds the telemetry-driven half: the
daemon folds its own aggregator feeds (summed ring/cts/DMA stall
deltas, detector health, the /critical dominant cause) into it once
per monitor tick.  One over-threshold tick holds dispatch (jobs queue
instead of landing on a stalled mesh); ``SUSTAIN`` consecutive ticks
under ``serve_shed_policy=shed`` flips to load shedding — submits
from tenants that already have work are rejected 429 with a
Retry-After hint — and one clean tick restores admission.

Pure bookkeeping: no sockets, no threads — the daemon drives it from
its monitor loop, and tests drive it directly.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any


def _id_num(job_id: str) -> int:
    """Numeric part of a ``j<N>`` id (0 for foreign ids)."""
    try:
        return int(str(job_id).lstrip("j"))
    except ValueError:
        return 0


class AdmissionError(Exception):
    """Submit rejected by admission control (HTTP 429/503 at the ops
    surface); ``.status`` carries the HTTP code and ``.retry_after``
    the Retry-After hint in seconds (None when the rejection is a
    hard quota/drain, not a transient overload shed)."""

    def __init__(self, msg: str, status: int = 429,
                 retry_after: float | None = None):
        super().__init__(msg)
        self.status = status
        self.retry_after = retry_after


class AdmissionController:
    """Telemetry-driven admission state machine (one per daemon).

    ``update()`` folds one monitor tick: per-proc CUMULATIVE stall
    sums (ring_stall_ns + cts_wait_ns + device_dma_wait_ns from the
    aggregator's latest frames — deltas against the previous tick are
    the overload signal, so a busy past never sheds forever) plus
    detector health and the dominant /critical cause.  States:

    * ``ok``       — dispatch and admit normally;
    * ``stalled``  — the last tick crossed ``serve_admission_stall_ns``
      (or the mesh is unhealthy): hold dispatch, keep admitting;
    * ``shedding`` — ``SUSTAIN`` consecutive stalled ticks under
      ``serve_shed_policy=shed``: tenants that already have work
      queued or running get 429 + Retry-After; an idle tenant still
      gets one job in (overload must not lock a tenant out).

    One clean tick resets the streak — a healed mesh restores
    admission immediately (the np=2 acceptance asserts the full
    ok → shedding → ok round trip in event space).
    """

    #: consecutive over-threshold ticks before queue-hold escalates
    #: to shedding (and the Retry-After hint, in poll-tick seconds)
    SUSTAIN = 3

    def __init__(self, stall_ns: int = 0, policy: str = "shed",
                 sustain: int = SUSTAIN):
        self.stall_ns = int(stall_ns)
        self.policy = str(policy or "shed")
        self.sustain = max(1, int(sustain))
        self._streak = 0
        #: proc → last cumulative stall sum (delta base)
        self._last: dict[int, int] = {}
        self.last_delta_ns = 0
        self.cause = ""
        self.unhealthy = False

    def enabled(self) -> bool:
        return self.stall_ns > 0

    def update(self, stalls_by_proc: dict | None, healthy: bool = True,
               cause: str = "") -> None:
        """Fold one monitor tick (no-op while disabled)."""
        if not self.enabled():
            return
        delta = 0
        for p, v in (stalls_by_proc or {}).items():
            p, v = int(p), int(v)
            delta += max(0, v - self._last.get(p, v))
            self._last[p] = v
        self.last_delta_ns = delta
        self.unhealthy = not healthy
        over = delta > self.stall_ns or not healthy
        self._streak = self._streak + 1 if over else 0
        self.cause = str(cause or "") if over else ""

    def overloaded(self) -> bool:
        """Hold dispatch? (any over-threshold tick, until one clean)"""
        return self.enabled() and self._streak >= 1

    def shedding(self) -> bool:
        return (self.enabled() and self.policy == "shed"
                and self._streak >= self.sustain)

    def retry_after_s(self) -> int:
        """Retry-After hint: the shortest interval after which the
        streak could have cleared (one sustain window of ticks)."""
        return max(1, int(self.sustain))

    def state(self) -> dict:
        return {
            "state": ("shedding" if self.shedding()
                      else "stalled" if self.overloaded() else "ok"),
            "enabled": self.enabled(),
            "stall_ns": self.stall_ns,
            "policy": self.policy,
            "streak": self._streak,
            "last_delta_ns": self.last_delta_ns,
            "unhealthy": self.unhealthy,
            "cause": self.cause,
        }


class JobQueue:
    """Multi-tenant FIFO with gang scheduling over ``nprocs`` slots."""

    def __init__(self, nprocs: int, max_pending: int = 8,
                 max_concurrent: int = 0, retry_budget: int = 0,
                 admission: AdmissionController | None = None):
        self.nprocs = int(nprocs)
        self.max_pending = int(max_pending)
        #: gang-concurrency cap (serve_max_concurrent; 0 = unlimited)
        self.max_concurrent = int(max_concurrent)
        #: automatic re-enqueues per repair-killed job (serve_retry_budget)
        self.retry_budget = int(retry_budget)
        #: telemetry-driven admission (None/disabled = PR-10 behavior)
        self.admission = admission
        #: serving-plane NATIVE_COUNTERS slice (daemon provider feed);
        #: jobs_concurrent_hwm is monotone here, max-merged downstream
        self.counters: dict[str, int] = {
            "jobs_concurrent_hwm": 0, "jobs_shed": 0,
            "jobs_deadline_expired": 0, "jobs_retried": 0}
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        #: submitted, not yet launched (submission order — FIFO spine)
        self._queue: list[dict] = []
        #: job id → record, running jobs
        self._running: dict[str, dict] = {}
        #: job id → record, completed jobs (done/failed), insertion order
        self._done: dict[str, dict] = {}
        #: tenant → monotonic pick counter (round-robin fairness state)
        self._served: dict[str, int] = {}
        self._pick = 0
        self.draining = False

    # -- admission -------------------------------------------------------

    def submit(self, script: str, args=(), tenant: str = "default",
               nprocs: int | None = None, env: dict | None = None) -> dict:
        """Admission control + enqueue.  Raises :class:`AdmissionError`
        when the daemon is draining (503) or the tenant already has
        ``max_pending`` jobs queued or running (429) — the queue-depth
        feed the ops surface reports per tenant."""
        with self._lock:
            if self.draining:
                raise AdmissionError("daemon is draining: no new jobs",
                                     status=503)
            tenant = str(tenant or "default")
            ctrl = self.admission
            if (ctrl is not None and ctrl.shedding()
                    and self._tenant_depth(tenant) >= 1):
                # sustained overload: shed tenants that already have
                # work in the system; a tenant with nothing queued or
                # running still gets one job admitted (fairness floor)
                self.counters["jobs_shed"] += 1
                ra = ctrl.retry_after_s()
                raise AdmissionError(
                    "mesh overloaded (admission shedding"
                    + (f", cause {ctrl.cause}" if ctrl.cause else "")
                    + f"): retry after {ra}s", status=429,
                    retry_after=ra)
            if self.max_pending > 0:
                depth = self._tenant_depth(tenant)
                if depth >= self.max_pending:
                    raise AdmissionError(
                        f"tenant {tenant!r} at serve_max_pending="
                        f"{self.max_pending} (depth {depth}); retry "
                        "after the queue drains", status=429)
            want = int(nprocs or self.nprocs)
            if not 0 < want <= self.nprocs:
                raise AdmissionError(
                    f"job wants {want} procs; the mesh has "
                    f"{self.nprocs}", status=400)
            job = {
                "id": f"j{next(self._ids)}",
                "tenant": tenant,
                "script": str(script),
                "args": [str(a) for a in (args or ())],
                "env": {str(k): str(v) for k, v in (env or {}).items()},
                "nprocs": want,
                "state": "queued",
                "submit_ns": time.time_ns(),
            }
            self._queue.append(job)
            return dict(job)

    def _tenant_depth(self, tenant: str) -> int:
        return (sum(1 for j in self._queue if j["tenant"] == tenant)
                + sum(1 for j in self._running.values()
                      if j["tenant"] == tenant))

    # -- gang scheduling -------------------------------------------------

    def next_runnable(self, free_procs) -> dict | None:
        """Pick the next job whose full rank-set fits in ``free_procs``
        and assign it the lowest free procs.  Order: round-robin across
        tenants (the tenant picked least recently goes first), FIFO
        within a tenant — but **any-fit**, not head-of-line: within a
        tenant's FIFO the first job that FITS the free set launches,
        so a wide job parked at the head cannot idle disjoint ranks a
        narrow job behind it could use.  Returns None while the
        admission controller holds dispatch (over-threshold stall
        tick) or ``serve_max_concurrent`` gangs already run."""
        free = sorted(int(p) for p in free_procs)
        with self._lock:
            if self.admission is not None and self.admission.overloaded():
                return None  # queue instead of dispatch onto a stall
            if (self.max_concurrent > 0
                    and len(self._running) >= self.max_concurrent):
                return None
            by_tenant: dict[str, list[dict]] = {}
            for j in self._queue:
                by_tenant.setdefault(j["tenant"], []).append(j)
            if not by_tenant:
                return None
            for tenant in sorted(
                    by_tenant, key=lambda t: (self._served.get(t, -1), t)):
                for job in by_tenant[tenant]:  # FIFO scan, first FIT
                    if job["nprocs"] > len(free):
                        continue
                    self._queue.remove(job)
                    self._pick += 1
                    self._served[tenant] = self._pick
                    job["procs"] = free[:job["nprocs"]]
                    job["state"] = "running"
                    job["start_ns"] = time.time_ns()
                    self._running[job["id"]] = job
                    self.counters["jobs_concurrent_hwm"] = max(
                        self.counters["jobs_concurrent_hwm"],
                        len(self._running))
                    return dict(job)
            return None

    # -- completion ------------------------------------------------------

    def finish(self, job_id: str, ok: bool, error: str = "",
               ranks: dict | None = None,
               hang: dict | None = None) -> dict | None:
        with self._lock:
            job = self._running.pop(job_id, None)
            if job is None:
                return None
            job["state"] = "done" if ok else "failed"
            if error:
                job["error"] = error[:2000]
            if ranks:
                # per-rank completion records (timings + transport dial
                # counters): the warm-reuse proof the ops surface and
                # the acceptance test read
                job["ranks"] = {str(r): rec for r, rec in ranks.items()}
            if hang is not None:
                # the pre-revoke hang report (deadline path): who was
                # blocked on whom when the deadline fired — served off
                # /job/<id> next to the DeadlineExpired error
                job["hang"] = hang
            job["end_ns"] = time.time_ns()
            self._done[job_id] = job
            return dict(job)

    def retry(self, job_id: str) -> dict | None:
        """Re-enqueue a RUNNING job killed by mesh repair, consuming
        one unit of ``serve_retry_budget``.  Returns the re-queued
        record, or None when the budget is exhausted (the job stays
        running; the caller finishes it failed with the typed
        RetryBudgetExhausted error).  The daemon journals the returned
        record as one atomic ``retry`` event — close-the-attempt +
        re-queue in a single fsync'd line, the exactly-once hinge."""
        with self._lock:
            job = self._running.get(job_id)
            if job is None:
                return None
            n = int(job.get("retries", 0))
            if self.retry_budget <= 0 or n >= self.retry_budget:
                return None
            del self._running[job_id]
            job["retries"] = n + 1
            job["state"] = "queued"
            job.pop("procs", None)
            job.pop("start_ns", None)
            job.pop("ranks", None)
            job.pop("error", None)
            job.pop("hang", None)
            self._queue.append(job)
            self.counters["jobs_retried"] += 1
            return dict(job)

    # -- restart recovery (journal replay) -------------------------------

    def restore(self, queued=(), running=(), done=()) -> None:
        """Reload journal-replayed state into a FRESH queue (daemon
        restart): queued jobs go back to the FIFO in submission order,
        running jobs re-enter the running set (their re-published
        directives are already outstanding), done jobs keep the ops
        history.  The id counter resumes past every restored id so a
        post-restart submit can never collide."""
        with self._lock:
            top = 0
            for job in sorted(queued, key=lambda j: j.get("submit_ns", 0)):
                self._queue.append(dict(job, state="queued"))
                top = max(top, _id_num(job["id"]))
            for job in running:
                self._running[job["id"]] = dict(job, state="running")
                top = max(top, _id_num(job["id"]))
            for job in done:
                self._done[job["id"]] = dict(job)
                top = max(top, _id_num(job["id"]))
            if top:
                self._ids = itertools.count(top + 1)

    def fail_queued(self, reason: str) -> None:
        """Flush the queue as failed (daemon shutdown with jobs
        pending)."""
        with self._lock:
            for job in self._queue:
                job["state"] = "failed"
                job["error"] = reason
                job["end_ns"] = time.time_ns()
                self._done[job["id"]] = job
            self._queue.clear()

    # -- introspection ---------------------------------------------------

    def get(self, job_id: str) -> dict | None:
        with self._lock:
            for pool in (self._running, self._done):
                if job_id in pool:
                    return dict(pool[job_id])
            for j in self._queue:
                if j["id"] == job_id:
                    return dict(j)
            return None

    def running(self) -> list[dict]:
        with self._lock:
            return [dict(j) for j in self._running.values()]

    def idle(self) -> bool:
        with self._lock:
            return not self._queue and not self._running

    def state(self) -> dict[str, Any]:
        """The ops-surface /jobs payload: queue depths per tenant (the
        admission feed), queued/running/done records."""
        with self._lock:
            tenants = sorted(
                {j["tenant"] for j in self._queue}
                | {j["tenant"] for j in self._running.values()})
            return {
                "draining": self.draining,
                "queued": [dict(j) for j in self._queue],
                "running": [dict(j) for j in self._running.values()],
                "done": {k: dict(v) for k, v in self._done.items()},
                "tenant_depth": {t: self._tenant_depth(t)
                                 for t in tenants},
                "max_pending": self.max_pending,
                "max_concurrent": self.max_concurrent,
                "retry_budget": self.retry_budget,
                "counters": dict(self.counters),
                "admission": (self.admission.state()
                              if self.admission is not None
                              else {"state": "ok", "enabled": False}),
            }
