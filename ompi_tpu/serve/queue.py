"""Job queue + gang scheduler + per-tenant admission (daemon side).

≈ the reference's plm job-state machinery collapsed to the piece a
single-host serving daemon needs: a FIFO of submitted jobs, scheduled
onto the resident rank-set **gang-style** — a job launches only when
every proc it needs is free — with round-robin fairness across tenants
(one tenant's burst cannot starve another's queue) and an admission
quota per tenant (``serve_max_pending``).

Pure bookkeeping: no sockets, no threads — the daemon drives it from
its monitor loop, and tests drive it directly.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any


def _id_num(job_id: str) -> int:
    """Numeric part of a ``j<N>`` id (0 for foreign ids)."""
    try:
        return int(str(job_id).lstrip("j"))
    except ValueError:
        return 0


class AdmissionError(Exception):
    """Submit rejected by admission control (HTTP 429/503 at the ops
    surface); ``.status`` carries the HTTP code."""

    def __init__(self, msg: str, status: int = 429):
        super().__init__(msg)
        self.status = status


class JobQueue:
    """Multi-tenant FIFO with gang scheduling over ``nprocs`` slots."""

    def __init__(self, nprocs: int, max_pending: int = 8):
        self.nprocs = int(nprocs)
        self.max_pending = int(max_pending)
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        #: submitted, not yet launched (submission order — FIFO spine)
        self._queue: list[dict] = []
        #: job id → record, running jobs
        self._running: dict[str, dict] = {}
        #: job id → record, completed jobs (done/failed), insertion order
        self._done: dict[str, dict] = {}
        #: tenant → monotonic pick counter (round-robin fairness state)
        self._served: dict[str, int] = {}
        self._pick = 0
        self.draining = False

    # -- admission -------------------------------------------------------

    def submit(self, script: str, args=(), tenant: str = "default",
               nprocs: int | None = None, env: dict | None = None) -> dict:
        """Admission control + enqueue.  Raises :class:`AdmissionError`
        when the daemon is draining (503) or the tenant already has
        ``max_pending`` jobs queued or running (429) — the queue-depth
        feed the ops surface reports per tenant."""
        with self._lock:
            if self.draining:
                raise AdmissionError("daemon is draining: no new jobs",
                                     status=503)
            tenant = str(tenant or "default")
            if self.max_pending > 0:
                depth = self._tenant_depth(tenant)
                if depth >= self.max_pending:
                    raise AdmissionError(
                        f"tenant {tenant!r} at serve_max_pending="
                        f"{self.max_pending} (depth {depth}); retry "
                        "after the queue drains", status=429)
            want = int(nprocs or self.nprocs)
            if not 0 < want <= self.nprocs:
                raise AdmissionError(
                    f"job wants {want} procs; the mesh has "
                    f"{self.nprocs}", status=400)
            job = {
                "id": f"j{next(self._ids)}",
                "tenant": tenant,
                "script": str(script),
                "args": [str(a) for a in (args or ())],
                "env": {str(k): str(v) for k, v in (env or {}).items()},
                "nprocs": want,
                "state": "queued",
                "submit_ns": time.time_ns(),
            }
            self._queue.append(job)
            return dict(job)

    def _tenant_depth(self, tenant: str) -> int:
        return (sum(1 for j in self._queue if j["tenant"] == tenant)
                + sum(1 for j in self._running.values()
                      if j["tenant"] == tenant))

    # -- gang scheduling -------------------------------------------------

    def next_runnable(self, free_procs) -> dict | None:
        """Pick the next job whose full rank-set fits in ``free_procs``
        and assign it the lowest free procs.  Order: round-robin across
        tenants (the tenant picked least recently goes first), FIFO
        within a tenant — so ``submit`` order holds per tenant while a
        burst from one tenant cannot monopolize the mesh."""
        free = sorted(int(p) for p in free_procs)
        with self._lock:
            tenants: dict[str, dict] = {}
            for j in self._queue:  # FIFO: first hit per tenant wins
                tenants.setdefault(j["tenant"], j)
            if not tenants:
                return None
            for tenant in sorted(
                    tenants, key=lambda t: (self._served.get(t, -1), t)):
                job = tenants[tenant]
                if job["nprocs"] <= len(free):
                    self._queue.remove(job)
                    self._pick += 1
                    self._served[tenant] = self._pick
                    job["procs"] = free[:job["nprocs"]]
                    job["state"] = "running"
                    job["start_ns"] = time.time_ns()
                    self._running[job["id"]] = job
                    return dict(job)
            return None

    # -- completion ------------------------------------------------------

    def finish(self, job_id: str, ok: bool, error: str = "",
               ranks: dict | None = None) -> dict | None:
        with self._lock:
            job = self._running.pop(job_id, None)
            if job is None:
                return None
            job["state"] = "done" if ok else "failed"
            if error:
                job["error"] = error[:2000]
            if ranks:
                # per-rank completion records (timings + transport dial
                # counters): the warm-reuse proof the ops surface and
                # the acceptance test read
                job["ranks"] = {str(r): rec for r, rec in ranks.items()}
            job["end_ns"] = time.time_ns()
            self._done[job_id] = job
            return dict(job)

    # -- restart recovery (journal replay) -------------------------------

    def restore(self, queued=(), running=(), done=()) -> None:
        """Reload journal-replayed state into a FRESH queue (daemon
        restart): queued jobs go back to the FIFO in submission order,
        running jobs re-enter the running set (their re-published
        directives are already outstanding), done jobs keep the ops
        history.  The id counter resumes past every restored id so a
        post-restart submit can never collide."""
        with self._lock:
            top = 0
            for job in sorted(queued, key=lambda j: j.get("submit_ns", 0)):
                self._queue.append(dict(job, state="queued"))
                top = max(top, _id_num(job["id"]))
            for job in running:
                self._running[job["id"]] = dict(job, state="running")
                top = max(top, _id_num(job["id"]))
            for job in done:
                self._done[job["id"]] = dict(job)
                top = max(top, _id_num(job["id"]))
            if top:
                self._ids = itertools.count(top + 1)

    def fail_queued(self, reason: str) -> None:
        """Flush the queue as failed (daemon shutdown with jobs
        pending)."""
        with self._lock:
            for job in self._queue:
                job["state"] = "failed"
                job["error"] = reason
                job["end_ns"] = time.time_ns()
                self._done[job["id"]] = job
            self._queue.clear()

    # -- introspection ---------------------------------------------------

    def get(self, job_id: str) -> dict | None:
        with self._lock:
            for pool in (self._running, self._done):
                if job_id in pool:
                    return dict(pool[job_id])
            for j in self._queue:
                if j["id"] == job_id:
                    return dict(j)
            return None

    def running(self) -> list[dict]:
        with self._lock:
            return [dict(j) for j in self._running.values()]

    def idle(self) -> bool:
        with self._lock:
            return not self._queue and not self._running

    def state(self) -> dict[str, Any]:
        """The ops-surface /jobs payload: queue depths per tenant (the
        admission feed), queued/running/done records."""
        with self._lock:
            tenants = sorted(
                {j["tenant"] for j in self._queue}
                | {j["tenant"] for j in self._running.values()})
            return {
                "draining": self.draining,
                "queued": [dict(j) for j in self._queue],
                "running": [dict(j) for j in self._running.values()],
                "done": {k: dict(v) for k, v in self._done.items()},
                "tenant_depth": {t: self._tenant_depth(t)
                                 for t in tenants},
                "max_pending": self.max_pending,
            }
