"""Attach-to-daemon client — the HTTP half of ``tpud_submit``.

Talks to a running :class:`~ompi_tpu.serve.daemon.TpuDaemon`'s ops
endpoint (the live-telemetry aggregator's HTTP surface with the serve
routes mounted).  Stdlib-only; ``tools/tpud_ctl.py`` and
``ompi_tpu.api.tpud_submit`` are thin wrappers over these calls.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any


class ServeError(Exception):
    """Ops-endpoint error; ``.status`` carries the HTTP code (429 =
    admission quota/shed, 503 = draining) and ``.retry_after`` the
    server's Retry-After hint in seconds (None when it sent none —
    shed rejections under overload always carry one)."""

    def __init__(self, msg: str, status: int = 0,
                 retry_after: float | None = None):
        super().__init__(msg)
        self.status = status
        self.retry_after = retry_after


def _call(url: str, path: str, payload: Any | None = None,
          timeout: float = 10.0) -> Any:
    req = urllib.request.Request(
        url.rstrip("/") + path,
        data=(None if payload is None
              else json.dumps(payload).encode()),
        method="GET" if payload is None else "POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        body = e.read().decode(errors="replace")
        retry_after: float | None = None
        try:
            parsed = json.loads(body)
            msg = parsed.get("error", body)
            if parsed.get("retry_after") is not None:
                retry_after = float(parsed["retry_after"])
        except ValueError:
            msg = body
        if retry_after is None:
            hdr = e.headers.get("Retry-After") if e.headers else None
            if hdr is not None:
                try:
                    retry_after = float(hdr)
                except ValueError:
                    pass
        raise ServeError(f"{path}: {msg}", status=e.code,
                         retry_after=retry_after) from None
    except OSError as e:
        raise ServeError(f"{path}: daemon unreachable ({e})") from None


def submit(url: str, script: str, args=(), tenant: str | None = None,
           nprocs: int | None = None, env: dict | None = None) -> dict:
    """Submit a worker script to the warm mesh; returns the job record
    (``id``, ``state``, tenant).  Raises :class:`ServeError` on
    admission rejection (429 quota / 503 draining)."""
    payload: dict[str, Any] = {"script": str(script),
                               "args": [str(a) for a in (args or ())]}
    if tenant is not None:
        payload["tenant"] = str(tenant)
    if nprocs is not None:
        payload["nprocs"] = int(nprocs)
    if env:
        payload["env"] = {str(k): str(v) for k, v in env.items()}
    return _call(url, "/submit", payload)


def status(url: str, job_id: str | None = None) -> dict:
    """Full ops state (``/jobs``: queue, running, done, tenant depths)
    or one job's record (``/job/<id>``)."""
    if job_id is None:
        return _call(url, "/jobs")
    return _call(url, f"/job/{job_id}")


def wait(url: str, job_id: str, timeout: float = 600.0,
         poll: float = 0.2) -> dict:
    """Poll until the job completes; returns its final record."""
    deadline = time.monotonic() + float(timeout)
    while True:
        job = status(url, job_id)
        if job.get("state") in ("done", "failed"):
            return job
        if time.monotonic() > deadline:
            raise ServeError(
                f"job {job_id} still {job.get('state')!r} after "
                f"{timeout}s")
        time.sleep(poll)


def drain(url: str) -> dict:
    """Stop admitting new jobs; queued/running jobs finish."""
    return _call(url, "/drain", {})


def shutdown(url: str) -> dict:
    """Drain, then stop the daemon once the queue empties (resident
    workers finalize and exit)."""
    return _call(url, "/shutdown", {})


def scale(url: str, nprocs: int) -> dict:
    """Resize the active rank-set: below the current size retires the
    highest ranks (shrink-style scale-down); back up to the boot size
    respawns them through the elastic restore leg (replace-style
    scale-up)."""
    return _call(url, "/scale", {"nprocs": int(nprocs)})
