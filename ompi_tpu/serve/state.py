"""Durable daemon state — pidfile lock + job-stream journal.

The tpud control plane's crash-safety substrate (ROADMAP tpud
follow-up (d)): everything the daemon process holds only in memory —
its identity, the job queue, the directive stream cursor, the worker
pids — dies with a SIGKILL, and PR 6's daemon orphaned every resident
worker when that happened.  Two small on-disk artifacts fix it:

* the **pidfile** (``serve_pidfile``) is a JSON record of the live
  daemon: pid, generation, and the three addresses a worker or
  operator needs to find it (KVS, ops HTTP URL, telemetry ingest).
  Acquisition implements *stale-lock takeover*: a pidfile whose pid is
  dead is reaped and its generation continued; a pidfile whose pid is
  alive refuses the second daemon.  Resident workers that lose their
  daemon poll this file for a higher generation — the re-adoption
  rendezvous;
* the **journal** (``serve_journal``, append-only JSONL next to the
  pidfile) records the job stream: submissions, published directives,
  directive completions, worker spawns/adoptions, clean shutdowns.
  :func:`Journal.replay` folds it back into the state a restarted
  daemon needs — queued jobs to re-admit, in-flight directives to
  re-publish at their ORIGINAL indices (workers dedup by cursor, so a
  replayed directive executes exactly once), the stream cursor, the
  CID-block high-water mark, and the last known pid per rank (the
  liveness test that decides re-adopt vs respawn).

Both are plain files, written atomically (tmp + rename) or
appended+flushed per event; no daemon state outlives a clean
shutdown (the pidfile is removed and a ``shutdown`` event resets the
journal's replay state).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any


def pid_alive(pid: int) -> bool:
    """Best-effort liveness: signal 0 probes existence (EPERM counts
    as alive — some other user's process holds the pid).  A ZOMBIE is
    dead: a SIGKILLed worker whose reaper is slow still answers
    kill-0, and a launch agent adopting it as 'alive' would hold a
    corpse's rank forever (found by the whole-host-kill soak)."""
    if pid <= 0:
        return False
    try:
        os.kill(int(pid), 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    try:
        with open(f"/proc/{int(pid)}/stat") as f:
            # field 3 (after the parenthesized comm, which may itself
            # contain spaces) is the state letter
            state = f.read().rsplit(")", 1)[-1].split()
        if state and state[0] == "Z":
            return False
    except (OSError, IndexError, ValueError):
        pass  # no procfs: keep the kill-0 answer
    return True


def read_pidfile(path: str) -> dict | None:
    """Parse the pidfile; None when absent or corrupt (a torn write is
    treated exactly like a stale lock — reaped on acquire)."""
    try:
        with open(path) as f:
            info = json.loads(f.read() or "{}")
    except (OSError, ValueError):
        return None
    return info if isinstance(info, dict) and "pid" in info else None


def pidfile_ready(info: dict | None) -> bool:
    """True for a FULL pidfile record a peer may re-attach through.
    :func:`acquire_pidfile` publishes a provisional ``O_EXCL`` claim
    (``{pid, claiming, <reaped generation>}``) before the daemon's
    sockets exist; a parked worker/agent polling inside that window
    must keep waiting for the full-record overwrite — the claim has
    no KVS address to dial, and its generation is the DEAD
    predecessor's, so a same-generation worker would mistake the
    restarting daemon for its old one."""
    return (bool(info) and not info.get("claiming")
            and bool(info.get("kvs")))


def write_pidfile(path: str, info: dict) -> None:
    """Atomic publish (tmp + rename): a reader never sees a torn
    record, and the rename is the commit point workers poll for."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(info, sort_keys=True))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class DaemonAlreadyRunning(RuntimeError):
    """A live daemon owns the pidfile; ``.info`` is its record."""

    def __init__(self, info: dict):
        super().__init__(
            f"tpud already running (pid {info.get('pid')}, ops "
            f"{info.get('url', '?')}) — pidfile {info.get('path', '')!r}")
        self.info = info


def acquire_pidfile(path: str) -> dict | None:
    """Take the pidfile lock.  Returns the STALE record we reaped
    (the restart-recovery cue, generation included) or None for a
    fresh start; raises :class:`DaemonAlreadyRunning` when the
    recorded pid is alive — including the loser of a concurrent
    takeover race: after reaping a stale record, the lock is CLAIMED
    with an ``O_CREAT|O_EXCL`` create (a provisional record carrying
    our live pid), so two simultaneously restarted daemons cannot
    both believe they own it.  The caller overwrites the claim with
    its full record once its sockets exist (addresses are part of the
    record)."""
    info = read_pidfile(path)
    if info is not None and pid_alive(int(info.get("pid", 0))):
        raise DaemonAlreadyRunning(dict(info, path=path))
    if info is not None:
        try:
            os.unlink(path)
        except OSError:
            pass
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
    except FileExistsError:
        # a racing daemon claimed between our unlink and create
        raise DaemonAlreadyRunning(
            dict(read_pidfile(path) or {"pid": -1}, path=path))
    with os.fdopen(fd, "w") as f:
        f.write(json.dumps({"pid": os.getpid(), "claiming": True,
                            "generation": int((info or {})
                                              .get("generation", 0))}))
        f.flush()
        os.fsync(f.fileno())
    return info


def remove_pidfile(path: str) -> None:
    """Release on clean shutdown — only if we still own it (a newer
    generation may have taken over a lock we wrongly held)."""
    info = read_pidfile(path)
    if info is not None and int(info.get("pid", -1)) != os.getpid():
        return
    try:
        os.unlink(path)
    except OSError:
        pass


class Journal:
    """Append-only JSONL event log of the job stream.

    Events (one JSON object per line, ``ev`` discriminates):

    ``submit``    a job admitted to the queue (full record)
    ``publish``   a directive appended to the stream (full directive,
                  ``idx`` inside)
    ``finish``    a directive completed (``idx``; job directives also
                  carry the final job record)
    ``retry``     a repair-killed job re-enqueued under its retry
                  budget: ONE atomic record closes the failed
                  attempt's directive (``idx``) AND re-queues the job
                  (``job``, ``retries`` bumped) — a daemon crash on
                  either side of this line replays to exactly one
                  re-run (before: attempt still outstanding, closed
                  again after restart by the workers' cached
                  completion records, retry decision re-made once;
                  after: job queued once, attempt closed)
    ``spawn``     a worker process launched or re-adopted
                  (``rank``/``pid``/``incarnation``/``adopted``;
                  ``host`` names the owning launch agent's host index
                  on the multi-host DVM leg — the placement a
                  restarted daemon routes liveness/respawn through) —
                  also un-retires the rank (a /scale restore)
    ``agent``     a per-host launch agent spawned or re-adopted
                  (``host``/``session``; informational — agent
                  liveness is heartbeat-driven, not replayed)
    ``repair_pending``  a rank was respawned and its repair directive
                  is NOT yet finished (``rank``/``incarnation``) — a
                  daemon SIGKILLed between the respawn and the
                  replace() completion finishes the repair after
                  restart instead of stranding the reborn worker;
                  cleared by the repair directive's ``finish``
    ``retire``    ranks scaled down (``ranks``) — a restart must not
                  resurrect an operator's scale-down
    ``drain``     admission stopped — a restart must stay draining
    ``takeover``  a restarted daemon recovered this journal
    ``compact``   the rewrite marker a takeover leaves after
                  :meth:`compact` (carries the cursor/cid/generation
                  floors the dropped events once established)
    ``shutdown``  clean daemon shutdown — replay state resets here

    Repeated SIGKILL→restart cycles must not grow the journal without
    bound: every takeover first **compacts** it — the file is
    rewritten with only live state (queued/running jobs, the last
    spawn per rank, retire/drain marks, pending repairs, done-job
    history), and every FINISHED published directive collapses to a
    constant-size ``noop`` index stub.  The stubs keep the stream's
    index space contiguous — workers consume indices strictly in
    order, so a hole below a still-lagging worker's cursor would
    wedge it; a ``noop`` is consumed and ignored.

    A *long-lived* daemon that never crashes never takes over, so the
    takeover-time compaction alone still grows the file without
    bound.  **Rotation** closes that edge: with ``max_bytes``/
    ``max_age_s`` armed (``serve_journal_max_kb`` /
    ``serve_journal_max_age_s``), :meth:`append` checks the bounds
    after writing and, when crossed, rewrites the journal in place as
    one compacted snapshot (the same ``compact`` fixed point — a
    ``compact`` marker line plus live state) and starts a fresh tail.
    Replay is unchanged: it already reads snapshot + tail, because a
    rotated journal is byte-for-byte what a takeover compaction
    leaves.  Rotation is atomic (tmp+rename) and crash-safe — a
    SIGKILL mid-rotation replays either the old file or the complete
    snapshot, never a half of each.
    """

    def __init__(self, path: str, max_bytes: int = 0,
                 max_age_s: float = 0.0):
        self.path = path
        self.max_bytes = int(max_bytes)
        self.max_age_s = float(max_age_s)
        #: rotation counter (tests / ops introspection)
        self.rotations = 0
        self._birth = time.monotonic()
        # a SIGKILLed writer can leave a torn final line; terminate it
        # before appending, or the first post-takeover event glues to
        # the torn tail and BOTH lines are lost to replay
        try:
            with open(path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                torn = f.read(1) != b"\n"
        except (OSError, ValueError):
            torn = False
        self._f = open(path, "a")
        if torn:
            self._f.write("\n")
            self._f.flush()

    def append(self, ev: str, **fields: Any) -> None:
        rec = {"ev": ev, "ts_ns": time.time_ns(), **fields}
        self._f.write(json.dumps(rec, sort_keys=True) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())
        if self._should_rotate():
            self.rotate()

    def _should_rotate(self) -> bool:
        if self.max_bytes > 0:
            try:
                if self._f.tell() > self.max_bytes:
                    return True
            except (OSError, ValueError):
                return False
        if self.max_age_s > 0:
            return time.monotonic() - self._birth > self.max_age_s
        return False

    def rotate(self) -> None:
        """Compact-in-place: fold the current file through
        :meth:`replay`, rewrite it as the :meth:`compact` snapshot
        (atomic tmp+rename), and reopen a fresh append tail.  The
        size/age clocks reset; the snapshot IS a valid journal, so a
        crash at any point replays cleanly."""
        try:
            self._f.close()
        except OSError:
            pass
        Journal.compact(self.path, Journal.replay(self.path))
        self._f = open(self.path, "a")
        self._birth = time.monotonic()
        self.rotations += 1

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass

    @staticmethod
    def compact(path: str, replay: dict) -> None:
        """Rewrite the journal with only live state (takeover-time
        dedup): one event per queued job, done-job record, last spawn
        per rank, pending repair, retire/drain mark — and one
        constant-size ``noop`` stub per FINISHED published directive
        (index-space continuity, see the class docstring).  Repeated
        crash→restart cycles re-derive this fixed point instead of
        appending to an ever-growing history.  Atomic (tmp+rename):
        a crash mid-compaction replays the old file."""
        tmp = f"{path}.compact.{os.getpid()}"
        with open(tmp, "w") as f:
            def w(ev: str, **fields: Any) -> None:
                f.write(json.dumps({"ev": ev, "ts_ns": time.time_ns(),
                                    **fields}, sort_keys=True) + "\n")

            w("compact", cursor=int(replay["cursor"]),
              cid_next=replay["cid_next"],
              generation=int(replay["generation"]))
            for job in replay["queued"]:
                w("submit", job=job)
            for job in replay["done"]:
                w("finish", idx=-1, kind="job", job=job)
            for idx in sorted(replay["published"]):
                d = replay["published"][idx]
                if idx in replay["outstanding"]:
                    w("publish", d=d)
                else:
                    w("publish", d={"kind": "noop", "idx": int(idx)})
            for r in sorted(replay["pids"]):
                st = replay["pids"][r]
                w("spawn", rank=int(r), pid=int(st.get("pid", 0)),
                  incarnation=int(st.get("incarnation", 0)),
                  **({"host": int(st["host"])}
                     if st.get("host") is not None else {}))
            for r in sorted(replay.get("repairing", {})):
                w("repair_pending", rank=int(r),
                  incarnation=int(replay["repairing"][r]))
            if replay["retired"]:
                w("retire", ranks=[int(r) for r in replay["retired"]])
            if replay["draining"]:
                w("drain")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    @staticmethod
    def replay(path: str) -> dict:
        """Fold the journal into restart state (empty state when the
        file is absent, unparseable lines skipped — a torn final line
        from the crash instant must not poison recovery):

        ``queued``       job records admitted but never published
        ``running``      job records whose directive is outstanding
        ``done``         finished job records (ops-surface history)
        ``published``    idx → directive, EVERY publish (finished
                         included — the restart must re-create the
                         whole stream: workers consume strictly in
                         order, so a hole below a finished index
                         would wedge any worker still beneath it)
        ``outstanding``  idx → directive, published but not finished
        ``cursor``       next directive index
        ``cid_next``     first CID block not yet handed out
        ``pids``         rank → {pid, incarnation} (last spawn/adopt)
        ``retired``      ranks scaled down and not since restored
        ``draining``     True when admission was stopped pre-crash
        ``generation``   takeover count recorded so far
        ``clean``        True when the tail is a clean shutdown
        """
        jobs: dict[str, dict] = {}
        published: dict[int, dict] = {}
        finished: dict[int, dict] = {}
        #: job ids whose LATEST record came from a ``retry`` event —
        #: their queued state must win over the published-and-finished
        #: done classification below
        retried_ids: set[str] = set()
        pids: dict[int, dict] = {}
        repairing: dict[int, int] = {}
        retired: set[int] = set()
        draining = False
        generation = 0
        cursor_floor = 0
        cid_floor: int | None = None
        clean = True

        def _reset() -> None:
            nonlocal draining, cursor_floor, cid_floor
            jobs.clear()
            published.clear()
            finished.clear()
            retried_ids.clear()
            pids.clear()
            repairing.clear()
            retired.clear()
            draining = False
            cursor_floor = 0
            cid_floor = None

        try:
            f = open(path)
        except OSError:
            return {"queued": [], "running": [], "done": [],
                    "published": {}, "outstanding": {}, "cursor": 0,
                    "cid_next": None, "pids": {}, "repairing": {},
                    "retired": [], "draining": False, "generation": 0,
                    "clean": True, "events": 0}
        events = 0
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail line from the crash instant
                events += 1
                ev = rec.get("ev")
                if ev == "submit":
                    job = rec.get("job") or {}
                    if job.get("id"):
                        jobs[job["id"]] = job
                    clean = False
                elif ev == "publish":
                    d = rec.get("d") or {}
                    if "idx" in d:
                        published[int(d["idx"])] = d
                    clean = False
                elif ev == "finish":
                    idx = int(rec.get("idx", -1))
                    finished[idx] = rec
                    if rec.get("kind") == "repair":
                        repairing.clear()
                    job = rec.get("job")
                    if job and job.get("id"):
                        jobs[job["id"]] = job
                elif ev == "retry":
                    # one atomic record = close the failed attempt's
                    # directive AND re-queue the job (retries bumped):
                    # either the line exists (attempt closed, job
                    # queued once) or it doesn't (attempt still
                    # outstanding — re-published on restart, workers'
                    # cached completion records close it again and the
                    # retry decision re-runs once).  Exactly-once
                    # either way.
                    idx = int(rec.get("idx", -1))
                    finished[idx] = rec
                    job = rec.get("job")
                    if job and job.get("id"):
                        jobs[job["id"]] = job
                        retried_ids.add(job["id"])
                    clean = False
                elif ev == "repair_pending":
                    repairing[int(rec.get("rank", -1))] = int(
                        rec.get("incarnation", 0))
                    clean = False
                elif ev == "compact":
                    cursor_floor = max(cursor_floor,
                                       int(rec.get("cursor", 0)))
                    if rec.get("cid_next") is not None:
                        cid_floor = int(rec["cid_next"])
                    generation = max(generation,
                                     int(rec.get("generation", 0)))
                elif ev == "spawn":
                    rank = int(rec.get("rank", -1))
                    pids[rank] = {
                        "pid": int(rec.get("pid", 0)),
                        "incarnation": int(rec.get("incarnation", 0))}
                    if rec.get("host") is not None:
                        # multi-host placement: the owning launch
                        # agent's host index — a restarted daemon
                        # routes this rank's liveness/respawn through
                        # that agent instead of a local pid probe
                        pids[rank]["host"] = int(rec["host"])
                    retired.discard(rank)  # /scale restore
                    clean = False
                elif ev == "retire":
                    for r in rec.get("ranks", ()):
                        retired.add(int(r))
                        repairing.pop(int(r), None)
                    clean = False
                elif ev == "drain":
                    draining = True
                    clean = False
                elif ev == "takeover":
                    generation = max(generation,
                                     int(rec.get("generation", 0)))
                elif ev == "shutdown":
                    _reset()
                    clean = True
        outstanding = {i: d for i, d in published.items()
                       if i not in finished and d.get("kind") != "noop"}
        published_job_ids = {d.get("id") for d in published.values()
                             if d.get("kind", "job") == "job"}
        queued, running, done = [], [], []
        for job in jobs.values():
            if job.get("state") in ("done", "failed"):
                done.append(job)
            elif job["id"] in {d.get("id") for d in outstanding.values()}:
                running.append(job)
            elif (job["id"] in published_job_ids
                    and not (job.get("state") == "queued"
                             and job["id"] in retried_ids)):
                # published AND finished but the finish event lost its
                # job payload — count it done with what we have.  A
                # job whose latest record is a retry re-queue is NOT
                # done: its published history belongs to the closed
                # attempt, and swallowing it here would eat the retry.
                done.append(dict(job, state=job.get("state", "done")))
            else:
                queued.append(job)
        cid_next = cid_floor
        for d in published.values():
            if "cid_base" in d:
                top = int(d["cid_base"]) + int(d.get("cid_span", 0))
                cid_next = top if cid_next is None else max(cid_next, top)
        return {
            "queued": queued, "running": running, "done": done,
            "published": dict(published), "outstanding": outstanding,
            "cursor": max(cursor_floor,
                          (max(published) + 1) if published else 0),
            "cid_next": cid_next, "pids": pids,
            "repairing": dict(repairing),
            "retired": sorted(retired), "draining": draining,
            "generation": generation, "clean": clean,
            "events": events,
        }
