"""Durable daemon state — pidfile lock + job-stream journal.

The tpud control plane's crash-safety substrate (ROADMAP tpud
follow-up (d)): everything the daemon process holds only in memory —
its identity, the job queue, the directive stream cursor, the worker
pids — dies with a SIGKILL, and PR 6's daemon orphaned every resident
worker when that happened.  Two small on-disk artifacts fix it:

* the **pidfile** (``serve_pidfile``) is a JSON record of the live
  daemon: pid, generation, and the three addresses a worker or
  operator needs to find it (KVS, ops HTTP URL, telemetry ingest).
  Acquisition implements *stale-lock takeover*: a pidfile whose pid is
  dead is reaped and its generation continued; a pidfile whose pid is
  alive refuses the second daemon.  Resident workers that lose their
  daemon poll this file for a higher generation — the re-adoption
  rendezvous;
* the **journal** (``serve_journal``, append-only JSONL next to the
  pidfile) records the job stream: submissions, published directives,
  directive completions, worker spawns/adoptions, clean shutdowns.
  :func:`Journal.replay` folds it back into the state a restarted
  daemon needs — queued jobs to re-admit, in-flight directives to
  re-publish at their ORIGINAL indices (workers dedup by cursor, so a
  replayed directive executes exactly once), the stream cursor, the
  CID-block high-water mark, and the last known pid per rank (the
  liveness test that decides re-adopt vs respawn).

Both are plain files, written atomically (tmp + rename) or
appended+flushed per event; no daemon state outlives a clean
shutdown (the pidfile is removed and a ``shutdown`` event resets the
journal's replay state).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any


def pid_alive(pid: int) -> bool:
    """Best-effort liveness: signal 0 probes existence (EPERM counts
    as alive — some other user's process holds the pid)."""
    if pid <= 0:
        return False
    try:
        os.kill(int(pid), 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


def read_pidfile(path: str) -> dict | None:
    """Parse the pidfile; None when absent or corrupt (a torn write is
    treated exactly like a stale lock — reaped on acquire)."""
    try:
        with open(path) as f:
            info = json.loads(f.read() or "{}")
    except (OSError, ValueError):
        return None
    return info if isinstance(info, dict) and "pid" in info else None


def write_pidfile(path: str, info: dict) -> None:
    """Atomic publish (tmp + rename): a reader never sees a torn
    record, and the rename is the commit point workers poll for."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(info, sort_keys=True))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class DaemonAlreadyRunning(RuntimeError):
    """A live daemon owns the pidfile; ``.info`` is its record."""

    def __init__(self, info: dict):
        super().__init__(
            f"tpud already running (pid {info.get('pid')}, ops "
            f"{info.get('url', '?')}) — pidfile {info.get('path', '')!r}")
        self.info = info


def acquire_pidfile(path: str) -> dict | None:
    """Take the pidfile lock.  Returns the STALE record we reaped
    (the restart-recovery cue, generation included) or None for a
    fresh start; raises :class:`DaemonAlreadyRunning` when the
    recorded pid is alive — including the loser of a concurrent
    takeover race: after reaping a stale record, the lock is CLAIMED
    with an ``O_CREAT|O_EXCL`` create (a provisional record carrying
    our live pid), so two simultaneously restarted daemons cannot
    both believe they own it.  The caller overwrites the claim with
    its full record once its sockets exist (addresses are part of the
    record)."""
    info = read_pidfile(path)
    if info is not None and pid_alive(int(info.get("pid", 0))):
        raise DaemonAlreadyRunning(dict(info, path=path))
    if info is not None:
        try:
            os.unlink(path)
        except OSError:
            pass
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
    except FileExistsError:
        # a racing daemon claimed between our unlink and create
        raise DaemonAlreadyRunning(
            dict(read_pidfile(path) or {"pid": -1}, path=path))
    with os.fdopen(fd, "w") as f:
        f.write(json.dumps({"pid": os.getpid(), "claiming": True,
                            "generation": int((info or {})
                                              .get("generation", 0))}))
        f.flush()
        os.fsync(f.fileno())
    return info


def remove_pidfile(path: str) -> None:
    """Release on clean shutdown — only if we still own it (a newer
    generation may have taken over a lock we wrongly held)."""
    info = read_pidfile(path)
    if info is not None and int(info.get("pid", -1)) != os.getpid():
        return
    try:
        os.unlink(path)
    except OSError:
        pass


class Journal:
    """Append-only JSONL event log of the job stream.

    Events (one JSON object per line, ``ev`` discriminates):

    ``submit``    a job admitted to the queue (full record)
    ``publish``   a directive appended to the stream (full directive,
                  ``idx`` inside)
    ``finish``    a directive completed (``idx``; job directives also
                  carry the final job record)
    ``spawn``     a worker process launched or re-adopted
                  (``rank``/``pid``/``incarnation``/``adopted``) —
                  also un-retires the rank (a /scale restore)
    ``retire``    ranks scaled down (``ranks``) — a restart must not
                  resurrect an operator's scale-down
    ``drain``     admission stopped — a restart must stay draining
    ``takeover``  a restarted daemon recovered this journal
    ``shutdown``  clean daemon shutdown — replay state resets here
    """

    def __init__(self, path: str):
        self.path = path
        # a SIGKILLed writer can leave a torn final line; terminate it
        # before appending, or the first post-takeover event glues to
        # the torn tail and BOTH lines are lost to replay
        try:
            with open(path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                torn = f.read(1) != b"\n"
        except (OSError, ValueError):
            torn = False
        self._f = open(path, "a")
        if torn:
            self._f.write("\n")
            self._f.flush()

    def append(self, ev: str, **fields: Any) -> None:
        rec = {"ev": ev, "ts_ns": time.time_ns(), **fields}
        self._f.write(json.dumps(rec, sort_keys=True) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass

    @staticmethod
    def replay(path: str) -> dict:
        """Fold the journal into restart state (empty state when the
        file is absent, unparseable lines skipped — a torn final line
        from the crash instant must not poison recovery):

        ``queued``       job records admitted but never published
        ``running``      job records whose directive is outstanding
        ``done``         finished job records (ops-surface history)
        ``published``    idx → directive, EVERY publish (finished
                         included — the restart must re-create the
                         whole stream: workers consume strictly in
                         order, so a hole below a finished index
                         would wedge any worker still beneath it)
        ``outstanding``  idx → directive, published but not finished
        ``cursor``       next directive index
        ``cid_next``     first CID block not yet handed out
        ``pids``         rank → {pid, incarnation} (last spawn/adopt)
        ``retired``      ranks scaled down and not since restored
        ``draining``     True when admission was stopped pre-crash
        ``generation``   takeover count recorded so far
        ``clean``        True when the tail is a clean shutdown
        """
        jobs: dict[str, dict] = {}
        published: dict[int, dict] = {}
        finished: dict[int, dict] = {}
        pids: dict[int, dict] = {}
        retired: set[int] = set()
        draining = False
        generation = 0
        clean = True

        def _reset() -> None:
            nonlocal draining
            jobs.clear()
            published.clear()
            finished.clear()
            pids.clear()
            retired.clear()
            draining = False

        try:
            f = open(path)
        except OSError:
            return {"queued": [], "running": [], "done": [],
                    "published": {}, "outstanding": {}, "cursor": 0,
                    "cid_next": None, "pids": {}, "retired": [],
                    "draining": False, "generation": 0,
                    "clean": True, "events": 0}
        events = 0
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail line from the crash instant
                events += 1
                ev = rec.get("ev")
                if ev == "submit":
                    job = rec.get("job") or {}
                    if job.get("id"):
                        jobs[job["id"]] = job
                    clean = False
                elif ev == "publish":
                    d = rec.get("d") or {}
                    if "idx" in d:
                        published[int(d["idx"])] = d
                    clean = False
                elif ev == "finish":
                    idx = int(rec.get("idx", -1))
                    finished[idx] = rec
                    job = rec.get("job")
                    if job and job.get("id"):
                        jobs[job["id"]] = job
                elif ev == "spawn":
                    rank = int(rec.get("rank", -1))
                    pids[rank] = {
                        "pid": int(rec.get("pid", 0)),
                        "incarnation": int(rec.get("incarnation", 0))}
                    retired.discard(rank)  # /scale restore
                    clean = False
                elif ev == "retire":
                    retired.update(int(r) for r in rec.get("ranks", ()))
                    clean = False
                elif ev == "drain":
                    draining = True
                    clean = False
                elif ev == "takeover":
                    generation = max(generation,
                                     int(rec.get("generation", 0)))
                elif ev == "shutdown":
                    _reset()
                    clean = True
        outstanding = {i: d for i, d in published.items()
                       if i not in finished}
        published_job_ids = {d.get("id") for d in published.values()
                             if d.get("kind", "job") == "job"}
        queued, running, done = [], [], []
        for job in jobs.values():
            if job.get("state") in ("done", "failed"):
                done.append(job)
            elif job["id"] in {d.get("id") for d in outstanding.values()}:
                running.append(job)
            elif job["id"] in published_job_ids:
                # published AND finished but the finish event lost its
                # job payload — count it done with what we have
                done.append(dict(job, state=job.get("state", "done")))
            else:
                queued.append(job)
        cid_next = None
        for d in published.values():
            if "cid_base" in d:
                top = int(d["cid_base"]) + int(d.get("cid_span", 0))
                cid_next = top if cid_next is None else max(cid_next, top)
        return {
            "queued": queued, "running": running, "done": done,
            "published": dict(published), "outstanding": outstanding,
            "cursor": (max(published) + 1) if published else 0,
            "cid_next": cid_next, "pids": pids,
            "retired": sorted(retired), "draining": draining,
            "generation": generation, "clean": clean,
            "events": events,
        }
