"""Resident rank worker — boot once, serve jobs until shutdown.

Launched by the daemon as ``python -m ompi_tpu.serve.worker``: runs the
normal boot rendezvous (``api.init`` → modex → DCN dials → engine
threads) exactly once, then long-polls the daemon's job stream
(``serve.job.<n>`` on the boot KVS) instead of running one script and
finalizing — the **job re-arm** that replaces finalize-teardown.

Per job directive:

* a fresh ``MPI_COMM_WORLD``-equivalent is carved from the warm world
  with **zero traffic**: the daemon assigned a disjoint CID block, so
  every member deterministically builds the same sub-communicator
  (``_make_sub``) at the block base — per-(comm, op) sequence counters
  start clean, nothing re-dials, and concurrent tenants' comm worlds
  can never collide in CID space;
* the job script runs **in this process** via ``runpy`` under a pushed
  world scope (``api.push_world``): the script's ``api.init()`` returns
  the job world; its ``api.finalize()`` pops the scope and leaves the
  mesh warm;
* a completion record (timings + transport dial counters — the
  warm-reuse proof) is published for the daemon.

``repair`` directives fire the elastic plane on demand: survivors run
``replace()`` (PR-4) to restore a respawned rank; a reborn worker
rejoins through the replace beacon and resumes the stream at the
cursor the daemon published for its incarnation.

**Daemon crash-safety** (the worker half): every KVS interaction goes
through :class:`DaemonLink`.  When the daemon dies (connection loss on
the long-poll or a failed completion put), the worker keeps serving
its in-flight job — the data plane is worker-to-worker — and **parks**
in a bounded re-attach window (``serve_reattach_timeout``), polling
the pidfile (``OMPI_TPU_SERVE_PIDFILE``) for a restarted daemon at a
higher generation.  Found one: re-dial its KVS, re-publish the modex
keys the old server took down, offer a ``serve.adopt.<r>`` record,
await the ack, re-put any completion records the crash orphaned, and
resume the stream at the local cursor (replayed directives dedup —
exactly once).  No daemon within the window (or no pidfile at all):
self-terminate with the full exit hygiene — crash-path telemetry
export, flight record, ``tdcn_destroy`` engine teardown.  **No
orphans, ever** — the same path a daemon-initiated SIGTERM stop takes.
"""

from __future__ import annotations

import collections
import os
import runpy
import signal
import sys
import threading
import time

from . import state as _state

#: KVS keys (shared with the daemon — keep in sync with serve/daemon.py)
K_JOB = "serve.job."
K_DONE = "serve.done."
K_RESUME = "serve.resume."
K_ADOPT = "serve.adopt."
K_ADOPTED = "serve.adopted."
K_START = "serve.start."
ENV_SERVE_PIDFILE = "OMPI_TPU_SERVE_PIDFILE"

#: transport counters proving warm reuse (flat across jobs = no
#: re-dials) and the per-job delivery/dedup picture; the schedule-cache
#: pair proves the OTHER warm asset — compiled persistent-collective
#: plans surviving across jobs like the mesh (hits climbing while
#: misses stay flat across same-signature jobs)
_DIAL_KEYS = ("reconnects", "retry_dials")
_REPORT_KEYS = ("delivered", "reconnects", "retry_dials", "dedup_drops",
                "sched_cache_hits", "sched_cache_misses")

#: completion records kept for re-publication after a daemon restart
_DONE_CACHE = 256


class _Stop(BaseException):
    """SIGTERM carrier — BaseException so the job scope's catch-all
    (a job must never kill the worker) cannot swallow a
    daemon-initiated stop."""


def _sigterm(signum, frame):  # pragma: no cover - signal delivery
    raise _Stop()


class _PipeSafe:
    """Stdio guard for the resident plane: the worker's stdout is a
    pipe into the daemon, and a SIGKILLed daemon turns every print —
    including the in-flight job script's — into BrokenPipeError.  The
    in-flight job must keep running through the daemon outage, so
    writes degrade to no-ops instead of raising (output during the
    outage is lost; the completion record is the durable artifact).

    Writes are serialized by a lock: the concurrent serving plane
    prints from the main loop (repair/revoke handling) and the
    per-job thread at once, and an interleaved-mid-line ``[rank N]``
    prefix would corrupt the daemon-side log forwarding the chaos
    soak parses."""

    def __init__(self, f):
        self._f = f
        self._wlock = threading.Lock()

    def retarget(self, f) -> None:
        """Re-aim at a NEW sink (adopted-worker stdio re-attach): the
        dead daemon's pipe is gone for good, so post-adoption output
        goes to the per-worker log file named in the restarted
        daemon's pidfile record instead of the bit bucket."""
        with self._wlock:
            self._f = f

    def write(self, s):
        with self._wlock:
            try:
                return self._f.write(s)
            except (OSError, ValueError):
                return len(s)

    def flush(self):
        with self._wlock:
            try:
                self._f.flush()
            except (OSError, ValueError):
                pass

    def __getattr__(self, name):
        return getattr(self._f, name)


def reaim_stdio(logdir: str, filename: str, banner: str) -> None:
    """The shared half of the worker/agent stdio re-attach protocol
    after a daemon crash: both processes' stdout/stderr still point at
    the DEAD daemon's pipe (_PipeSafe swallowed the breakage) — re-aim
    them at a per-process log file under the restarted daemon's logs
    dir so post-reattach output is durable instead of lost.  No-op on
    an empty logdir; an unusable one keeps the swallowing streams
    (staying alive outranks durable logs)."""
    if not logdir:
        return
    try:
        os.makedirs(logdir, exist_ok=True)
        path = os.path.join(logdir, filename)
        logf = open(path, "a", buffering=1)
        for stream in (sys.stdout, sys.stderr):
            rt = getattr(stream, "retarget", None)
            if rt is not None:
                rt(logf)
        print(f"{banner}: stdio re-aimed at {path}", flush=True)
    except OSError:
        pass  # log dir unusable: keep swallowing, stay alive


class DaemonLink:
    """The worker's resilient handle on the daemon: job-stream cursor,
    completion-record cache, and the crash→re-attach state machine."""

    def __init__(self, ctx, wsize: int, poll: float, window: float):
        self.ctx = ctx
        self.wsize = int(wsize)
        self.poll = poll
        self.window = float(window)
        self.pidfile = os.environ.get(ENV_SERVE_PIDFILE, "")
        info = (_state.read_pidfile(self.pidfile)
                if self.pidfile else None)
        #: the generation we booted under; re-attach requires a HIGHER
        #: one (a live daemon at our own generation is the one whose
        #: socket just broke — dial it again, don't adopt)
        self.generation = int((info or {}).get("generation", 0))
        #: next directive index to consume
        self.cursor = 0
        self._done: collections.OrderedDict[int, dict] = (
            collections.OrderedDict())
        #: main() installs the teardown closure (needs api + world)
        self.teardown = None

    # -- stream consumption ---------------------------------------------

    def wait_directive(self) -> tuple[int, dict]:
        """Long-poll the next directive; a dead daemon routes through
        the re-attach window (which either restores the link or exits
        the process — this loop never spins against a corpse)."""
        while True:
            try:
                jd = self.ctx.kvs.get(f"{K_JOB}{self.cursor}",
                                      timeout=max(self.poll, 2.0))
            except KeyError:
                time.sleep(self.poll)
                continue
            except (ConnectionError, OSError):
                self.reattach()
                continue
            idx, self.cursor = self.cursor, self.cursor + 1
            return idx, jd

    def get(self, key: str):
        """Resilient KVS read for non-stream keys (the reborn cursor
        beacon): same re-attach healing as the stream poll."""
        while True:
            try:
                return self.ctx.kvs.get(key, timeout=max(self.poll, 2.0))
            except KeyError:
                time.sleep(self.poll)
            except (ConnectionError, OSError):
                self.reattach()

    def report(self, idx: int, rec: dict) -> None:
        """Publish a completion record; cached regardless, so a record
        the daemon never saw (crash between execute and collect) is
        re-put on re-adoption — the daemon's collect is idempotent."""
        rec = dict(rec)
        rec["proc"] = self.ctx.proc
        self._done[idx] = rec
        while len(self._done) > _DONE_CACHE:
            self._done.popitem(last=False)
        try:
            self.ctx.kvs.put(f"{K_DONE}{idx}.{self.ctx.proc}", rec)
        except (ConnectionError, OSError):
            pass  # the re-attach path re-publishes the cache

    # -- crash → re-attach ----------------------------------------------

    def reattach(self) -> None:
        """The parked state: bounded poll of the pidfile for a
        restarted daemon, adoption on success, full-teardown exit on
        expiry.  Bounded by ``serve_reattach_timeout`` via the shared
        Deadline policy."""
        from ompi_tpu.core.errors import DeadlineExpiredError
        from ompi_tpu.core.var import Deadline

        if not self.pidfile:
            self._orphan_exit("daemon gone and no pidfile to re-attach "
                              "through (serve_pidfile off)")
        deadline = Deadline(self.window)
        print(f"serve: daemon lost; parking up to {self.window:.0f}s "
              f"for a restarted daemon ({self.pidfile})", flush=True)
        while True:
            info = _state.read_pidfile(self.pidfile)
            alive = bool(info) and _state.pid_alive(
                int(info.get("pid", 0)))
            # a restarting daemon's provisional O_EXCL claim (live
            # pid, no KVS address, the REAPED record's generation) is
            # not re-attachable — keep parking for the full-record
            # overwrite (found by the sigkill-restart soak: a worker
            # polling inside the claim window died on KeyError('kvs')
            # and the whole warm mesh cold-booted)
            ready = alive and _state.pidfile_ready(info)
            gen = int((info or {}).get("generation", 0))
            if ready and gen == self.generation:
                # transient socket break against the SAME daemon (it
                # never lost us): plain re-dial, no adoption handshake
                try:
                    self.ctx.kvs.reconnect(info["kvs"])
                    print("serve: KVS link re-dialed (daemon alive)",
                          flush=True)
                    return
                except OSError:
                    pass  # it may be dying; keep polling
            elif ready and gen > self.generation:
                try:
                    self._adopt(info, deadline)
                    return
                except (KeyError, OSError, TimeoutError,
                        DeadlineExpiredError) as e:
                    print(f"serve: re-attach attempt failed "
                          f"({type(e).__name__}: {e}); retrying",
                          flush=True)
            if deadline.expired():
                self._orphan_exit(
                    "no restarted daemon within serve_reattach_timeout"
                    f"={self.window:.0f}s")
            time.sleep(min(0.25, max(self.poll, 0.05)))

    def _adopt(self, info: dict, deadline) -> None:
        """One adoption attempt against a candidate daemon: re-dial
        its KVS, re-publish this rank's modex keys (the old server
        died with them; future respawns/repairs read them), offer the
        adopt record, await the ack, re-put cached completions."""
        ctx = self.ctx
        ctx.kvs.reconnect(info["kvs"])
        addr = ctx.engine.transport.address
        ctx.kvs.put(f"{ctx.ns}dcn.{ctx.proc}", addr)
        ctx.kvs.put(f"{ctx.ns}wsize.{ctx.proc}", self.wsize)
        if ctx.incarnation:
            ctx.kvs.put(f"{ctx.ns}dcn.{ctx.proc}.i{ctx.incarnation}",
                        addr)
            ctx.kvs.put(f"{ctx.ns}inc.{ctx.proc}", ctx.incarnation)
        gen = int(info["generation"])
        ctx.kvs.put(f"{K_ADOPT}{ctx.proc}", {
            "pid": os.getpid(), "incarnation": ctx.incarnation,
            "cursor": self.cursor, "generation": gen})
        while True:
            try:
                ack = ctx.kvs.get(f"{K_ADOPTED}{ctx.proc}",
                                  timeout=deadline.slice(1.0))
            except KeyError:
                ack = None
            if (ack and int(ack.get("pid", -1)) == os.getpid()
                    and int(ack.get("generation", 0)) == gen):
                break
            deadline.check("re-adoption ack")
            time.sleep(0.05)
        for idx, rec in list(self._done.items()):
            ctx.kvs.put(f"{K_DONE}{idx}.{ctx.proc}", rec)
        self.generation = gen
        from ompi_tpu.metrics import live

        live.repoint_publisher(info.get("ingest") or "")
        # stdio re-attach (PR 10 deferred edge): this worker's stdout/
        # stderr still point at the DEAD daemon's pipe (_PipeSafe
        # swallowed the breakage); re-aim them at the per-worker log
        # file the restarted daemon names in its pidfile record, so
        # post-adoption output is durable instead of lost.  The path
        # is surfaced on the daemon's /jobs procs table.
        reaim_stdio(str(info.get("logs") or ""),
                    f"worker.{ctx.proc}.log", "serve")
        print(f"serve: re-attached to daemon generation {gen} "
              f"(cursor {self.cursor})", flush=True)

    def _orphan_exit(self, reason: str) -> None:
        """The no-orphans guarantee: a worker that cannot find a
        daemon terminates ITSELF with the full exit hygiene — partial
        telemetry export, flight record, engine destroy — instead of
        serving nothing forever."""
        print(f"serve: {reason}; tearing down and exiting (no "
              "orphans)", flush=True)
        from ompi_tpu.metrics import export as _mexport
        from ompi_tpu.metrics import flight as _flight

        _flight.record("worker_orphaned", reason=reason,
                       cursor=self.cursor)
        _mexport.crash_dump("daemon_lost")
        if self.teardown is not None:
            self.teardown()
        raise SystemExit(0)


def _job_comm(world, jd: dict):
    """Deterministic job-world construction at the assigned CID block:
    every member reserves ``[base, base+1)`` (in-job derived comms draw
    from ``base+1`` upward via the normal CID agreement, staying inside
    the block) and builds the identical sub-communicator — no
    allgather, no dial, no traffic."""
    from ompi_tpu.api.comm import _reserve_cid_block

    base = int(jd["cid_base"])
    cid = _reserve_cid_block(base, 1)
    procs = [int(p) for p in jd["procs"]]
    members = [r for p in procs for r in range(*world.proc_range(p))]
    owners = [p for p in procs for _ in range(world.proc_sizes[p])]
    sub = world._make_sub(jd["id"], cid, members, owners, procs)
    sub.name = f"world.{jd['id']}"
    return sub


def _exec_script(jd: dict) -> None:
    """Run the job script in-process as ``__main__`` with its argv and
    extra env, both restored afterwards (the warm process serves many
    jobs; one job's argv/env must not leak into the next)."""
    argv0, env0 = sys.argv, {}
    sys.argv = [jd["script"]] + list(jd.get("args") or ())
    try:
        for k, v in (jd.get("env") or {}).items():
            env0[k] = os.environ.get(k)
            os.environ[k] = v
        runpy.run_path(jd["script"], run_name="__main__")
    finally:
        sys.argv = argv0
        for k, old in env0.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old


def _revoke_quietly(job) -> None:
    """ULFM hygiene for EVERY abort path (exception or nonzero
    sys.exit): poison the aborted job comm so fellow members parked in
    its collectives wake with the truth (MPIRevokedError) instead of
    timing out their recv deadlines and falsely escalating a LIVE peer
    — the false positive that wedged the multi-host repair behind a
    60 s wait for a "respawn" of a rank that never died."""
    if job is None:
        return
    try:
        job.revoke()
    except Exception:  # noqa: BLE001 — poisoned comm already
        pass


def _run_job(api, world, link: DaemonLink, jd: dict, idx: int,
             inflight: dict | None = None) -> None:
    import ompi_tpu.serve as serve
    from ompi_tpu.metrics import core as mcore
    from ompi_tpu.metrics import live

    rec: dict = {"ok": True, "id": jd["id"], "cid_base": jd["cid_base"],
                 "incarnation": link.ctx.incarnation}
    before = mcore.native_counters()
    rec["dials_before"] = {k: int(before.get(k, 0)) for k in _DIAL_KEYS}
    job = None
    rec["t_start_ns"] = time.time_ns()
    try:
        job = _job_comm(world, jd)
        rec["cid"] = int(job.cid)
        if inflight is not None:
            # expose the job comm to the main loop so a deadline
            # ``revoke`` directive can poison it mid-collective
            inflight["comm"] = job
        serve._set_current(dict(jd))
        live.set_job(jd["id"])
        api.push_world(job)
        _exec_script(jd)
    except SystemExit as e:
        if e.code not in (0, None):
            rec["ok"] = False
            rec["error"] = f"job script exited rc={e.code}"
            _revoke_quietly(job)  # a nonzero exit aborts the gang too
    except _Stop:
        raise  # daemon-initiated stop outranks the job guard
    except BaseException as e:  # noqa: BLE001 — a job must never kill
        # the resident worker; MPIProcFailedError lands here too (the
        # daemon sees the dead rank and queues the repair directive)
        rec["ok"] = False
        rec["error"] = f"{type(e).__name__}: {e}"
        _revoke_quietly(job)
    finally:
        if api.in_job_scope():
            api.pop_world()
        live.set_job(None)
        serve._set_current(None)
        if job is not None:
            try:
                job.free()
            except Exception:  # noqa: BLE001 — poisoned job comm
                pass
    rec["t_end_ns"] = time.time_ns()
    after = mcore.native_counters()
    rec["dials_after"] = {k: int(after.get(k, 0)) for k in _DIAL_KEYS}
    rec["counters"] = {k: int(after.get(k, 0)) for k in _REPORT_KEYS}
    link.report(idx, rec)


def _repair(api, world, link: DaemonLink, jd: dict, idx: int,
            timeout: float):
    """Survivor half of a repair directive: wait for the detector to
    surface every dead proc (gossip converges within a period), then
    ``replace()`` — the reborn incarnations rejoin through the beacon
    inside it — and adopt the healed world for future jobs."""
    dead = [int(d) for d in jd.get("dead", ())]
    dead_ranks = {r for p in dead for r in range(*world.proc_range(p))}
    deadline = time.monotonic() + timeout
    while True:
        failed = set(world.get_failed())
        missing = [p for p in dead
                   if not (set(range(*world.proc_range(p))) & failed)]
        # wait for the failed set to SETTLE to exactly the directive's
        # dead procs: a false-positive mark on a live survivor (an
        # aborted job's recv-deadline escalation) self-heals within
        # about a heartbeat period, and entering replace() while it
        # stands would await a respawn that never comes
        if not missing and failed <= dead_ranks:
            break
        if time.monotonic() > deadline:
            if not missing:
                break  # extras never healed: best-effort repair
            link.report(idx, {
                "ok": False,
                "error": f"repair: procs {missing} never surfaced as "
                         f"failed within {timeout}s"})
            return world
        time.sleep(0.05)
    t0 = time.monotonic()
    try:
        healed = world.replace()
    except BaseException as e:  # noqa: BLE001 — repair must report
        if isinstance(e, _Stop):
            raise
        link.report(idx, {"ok": False,
                          "error": f"{type(e).__name__}: {e}"})
        return world
    api.set_world(healed)
    link.report(idx, {"ok": True,
                      "heal_ms": round((time.monotonic() - t0) * 1e3, 3)})
    print(f"serve: repaired world (dead={dead})", flush=True)
    return healed


def _teardown_resident(api, world) -> None:
    """Raw teardown for a retired/stopped/orphaned rank (no finalize
    fence — the remaining ranks are not finalizing with us), ending in
    the FULL native engine teardown: ``tdcn_destroy`` frees every
    engine-owned allocation and joins the reader threads, so an
    operator ``kill`` never leaks shm rings or readers (the ASan/TSan
    ``--sanitize`` soak guards exactly this path in C)."""
    from ompi_tpu.metrics import live

    live.stop_publisher()
    try:
        world.procctx.close()
    except Exception:  # noqa: BLE001 — exiting anyway
        pass
    try:
        root = world.dcn._root_engine()
        destroy = getattr(root, "destroy", None)
        if destroy is not None:
            destroy()
    except Exception:  # noqa: BLE001 — exiting anyway
        pass


def main() -> int:
    import jax

    jax.config.update("jax_platforms",
                      os.environ.get("JAX_PLATFORMS", "cpu"))
    # stdio through the daemon pipe must survive the daemon's death
    sys.stdout = _PipeSafe(sys.stdout)
    sys.stderr = _PipeSafe(sys.stderr)
    import ompi_tpu.api as api
    from ompi_tpu.core import mca

    from ompi_tpu.boot.proc import respawn_timeout as _respawn_timeout

    world = api.init()
    ctx = world.procctx
    # warm compiled-schedule cache (ROADMAP serving item (b)): the
    # process-wide plan store (ompi_tpu/coll/sched.CACHE) lives exactly
    # as long as this resident worker — job 2's persistent collectives
    # of a job-1 signature replay already-compiled schedules, and its
    # hit/miss counters merge into the worker's native-counter exports
    # (the per-job completion records + /metrics scrapes above)
    from ompi_tpu.coll import sched as _sched

    _sched.register_metrics_provider()
    store = mca.default_context().store
    poll = max(0.02, int(store.get("serve_poll_ms", 50) or 50) / 1000.0)
    # rsh-aware (ft_remote_respawn_timeout under OMPI_TPU_RSH), like
    # every other await-respawn deadline
    respawn_timeout = _respawn_timeout(store)
    link = DaemonLink(
        ctx, wsize=world.local_size, poll=poll,
        window=float(store.get("serve_reattach_timeout", 30.0) or 30.0))
    current = {"world": world}
    link.teardown = lambda: _teardown_resident(api, current["world"])
    try:
        signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:  # pragma: no cover - non-main thread (tests)
        pass
    try:
        return _serve_loop(api, ctx, link, current, respawn_timeout)
    except _Stop:
        # operator/daemon SIGTERM: the same exit hygiene as job
        # completion — partial export, flight record, engine destroy
        print("serve: SIGTERM — crash-path export + full engine "
              "teardown", flush=True)
        from ompi_tpu.metrics import export as _mexport
        from ompi_tpu.metrics import flight as _flight

        _flight.record("worker_sigterm", cursor=link.cursor)
        _mexport.crash_dump("sigterm")
        _teardown_resident(api, current["world"])
        return 143


def _serve_loop(api, ctx, link: DaemonLink, current: dict,
                respawn_timeout: float) -> int:
    world = current["world"]
    if getattr(world, "respawned", False):
        # reborn incarnation: rejoin the warm world via the survivors'
        # replace round, then resume the stream where the daemon says
        world = world.replace()
        api.set_world(world)
        current["world"] = world
        link.cursor = int(link.get(
            f"{K_RESUME}{ctx.proc}.i{ctx.incarnation}"))
        print(f"serve: incarnation {ctx.incarnation} rejoined; "
              f"resuming at directive {link.cursor}", flush=True)
    else:
        try:
            # cold-boot after a daemon restart that lost the whole
            # mesh: the daemon's start beacon skips this fresh worker
            # past the re-published pre-crash stream
            link.cursor = int(ctx.kvs.get(f"{K_START}{ctx.proc}",
                                          wait=False))
        except (KeyError, ConnectionError, OSError):
            pass
        print(f"serve: resident worker up (proc {ctx.proc}/"
              f"{ctx.nprocs}, cursor {link.cursor})", flush=True)
    # concurrent serving plane: each admitted job runs on its OWN
    # thread so this loop keeps consuming directives mid-job — a
    # deadline ``revoke`` for the running gang, a ``repair`` for a
    # DISJOINT gang's dead rank (bystander-quiet: heals the base world
    # without touching the in-flight job), retire/shutdown.  A worker
    # proc is a member of at most one running gang at a time (the
    # daemon's scheduler books whole procs), so one inflight slot is
    # enough; the holder is written by both threads but every field
    # update is a single dict store under the GIL and both readers
    # tolerate staleness (a revoke for an already-finished job is a
    # no-op, a join on a finished thread returns immediately).
    inflight: dict = {"thread": None, "idx": None, "jd": None,
                      "comm": None}

    def _job_thread(jd: dict, idx: int, jworld) -> None:
        try:
            _run_job(api, jworld, link, jd, idx, inflight)
        finally:
            inflight["comm"] = None
            inflight["jd"] = None

    def _join_inflight() -> None:
        # called with NO locks held (the lockorder pass treats an
        # unbounded join under a lock as a blocking hazard)
        t = inflight["thread"]
        if t is not None:
            t.join()
        inflight["thread"] = None

    while True:
        idx, jd = link.wait_directive()
        kind = jd.get("kind", "job")
        if kind == "shutdown":
            _join_inflight()  # full-house finalize fences all ranks
            if len(jd.get("procs", ())) == ctx.nprocs:
                api.finalize()  # full house: the real fence + teardown
            else:
                _teardown_resident(api, world)
            print("serve: shutdown", flush=True)
            return 0
        if kind == "revoke":
            # deadline escalation (serve_job_deadline_s): poison the
            # named in-flight job's comm so its gang wakes out of any
            # parked collective with MPIRevokedError — never a wedged
            # gang — while concurrent disjoint gangs stay untouched
            if ctx.proc in jd.get("procs", ()):
                cur = inflight["jd"]
                ack = {"ok": True, "revoked": jd.get("id")}
                from ompi_tpu.trace import waitgraph as _waitgraph

                if _waitgraph._enabled:
                    if _waitgraph.busy():
                        # last look at this rank's blocked state before
                        # the poison wakes it (the waits unregister on
                        # wake-up — evidence for the hang report)
                        ack["waits"] = _waitgraph.snapshot()
                        from ompi_tpu.metrics import export as _mexp

                        # post-mortem leg: flush configured telemetry
                        # NOW, blocked state included, so trace_report
                        # --hangs can diagnose from the crash export
                        # after the gang is gone
                        _mexp.crash_dump("deadline_revoke")
                if cur is not None and cur.get("id") == jd.get("id"):
                    print(f"serve: revoking job {jd.get('id')} "
                          "(deadline)", flush=True)
                    _revoke_quietly(inflight["comm"])
                link.report(idx, ack)
            continue
        if kind == "repair":
            if ctx.proc in jd.get("procs", ()):
                cur = inflight["jd"]
                if cur is not None and (set(int(d) for d in
                                            jd.get("dead", ()))
                                        & set(int(p) for p in
                                              cur.get("procs", ()))):
                    # the in-flight gang lost a member: its script is
                    # failing on the dead rank right now — let it close
                    # out (revoke + completion record) before healing
                    # the base world under it
                    _join_inflight()
                # bystander-quiet: a disjoint gang's job thread keeps
                # running on its sub-comm while the base world heals
                world = _repair(api, world, link, jd, idx,
                                respawn_timeout)
                current["world"] = world
            continue
        if kind == "retire":
            if ctx.proc in jd.get("retire", ()):
                _join_inflight()
                link.report(idx, {"ok": True, "retired": True})
                _teardown_resident(api, world)
                print("serve: retired", flush=True)
                return 0
            if ctx.proc in jd.get("procs", ()):
                link.report(idx, {"ok": True})
            continue
        if ctx.proc in jd.get("procs", ()):
            _join_inflight()  # defensive: scheduler never double-books
            world = current["world"]
            inflight["idx"], inflight["jd"] = idx, jd
            inflight["comm"] = None
            t = threading.Thread(target=_job_thread,
                                 args=(jd, idx, world),
                                 name=f"serve-job-{jd['id']}",
                                 daemon=True)
            inflight["thread"] = t
            t.start()


if __name__ == "__main__":
    sys.exit(main())
