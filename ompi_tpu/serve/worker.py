"""Resident rank worker — boot once, serve jobs until shutdown.

Launched by the daemon as ``python -m ompi_tpu.serve.worker``: runs the
normal boot rendezvous (``api.init`` → modex → DCN dials → engine
threads) exactly once, then long-polls the daemon's job stream
(``serve.job.<n>`` on the boot KVS) instead of running one script and
finalizing — the **job re-arm** that replaces finalize-teardown.

Per job directive:

* a fresh ``MPI_COMM_WORLD``-equivalent is carved from the warm world
  with **zero traffic**: the daemon assigned a disjoint CID block, so
  every member deterministically builds the same sub-communicator
  (``_make_sub``) at the block base — per-(comm, op) sequence counters
  start clean, nothing re-dials, and concurrent tenants' comm worlds
  can never collide in CID space;
* the job script runs **in this process** via ``runpy`` under a pushed
  world scope (``api.push_world``): the script's ``api.init()`` returns
  the job world; its ``api.finalize()`` pops the scope and leaves the
  mesh warm;
* a completion record (timings + transport dial counters — the
  warm-reuse proof) is published for the daemon.

``repair`` directives fire the elastic plane on demand: survivors run
``replace()`` (PR-4) to restore a respawned rank; a reborn worker
rejoins through the replace beacon and resumes the stream at the
cursor the daemon published for its incarnation.
"""

from __future__ import annotations

import os
import runpy
import sys
import time

#: KVS keys (shared with the daemon — keep in sync with serve/daemon.py)
K_JOB = "serve.job."
K_DONE = "serve.done."
K_RESUME = "serve.resume."

#: transport counters proving warm reuse (flat across jobs = no
#: re-dials) and the per-job delivery/dedup picture
_DIAL_KEYS = ("reconnects", "retry_dials")
_REPORT_KEYS = ("delivered", "reconnects", "retry_dials", "dedup_drops")


def _kvs_wait(ctx, key: str, poll: float):
    """Long-poll one KVS key; a dead daemon (connection loss) exits
    the worker — the resident plane has nothing to serve without it."""
    while True:
        try:
            return ctx.kvs.get(key, timeout=max(poll, 2.0))
        except KeyError:
            time.sleep(poll)
        except (ConnectionError, OSError):
            print("serve: daemon gone; exiting", flush=True)
            raise SystemExit(0)


def _report(ctx, idx: int, rec: dict) -> None:
    rec = dict(rec)
    rec["proc"] = ctx.proc
    ctx.kvs.put(f"{K_DONE}{idx}.{ctx.proc}", rec)


def _job_comm(world, jd: dict):
    """Deterministic job-world construction at the assigned CID block:
    every member reserves ``[base, base+1)`` (in-job derived comms draw
    from ``base+1`` upward via the normal CID agreement, staying inside
    the block) and builds the identical sub-communicator — no
    allgather, no dial, no traffic."""
    from ompi_tpu.api.comm import _reserve_cid_block

    base = int(jd["cid_base"])
    cid = _reserve_cid_block(base, 1)
    procs = [int(p) for p in jd["procs"]]
    members = [r for p in procs for r in range(*world.proc_range(p))]
    owners = [p for p in procs for _ in range(world.proc_sizes[p])]
    sub = world._make_sub(jd["id"], cid, members, owners, procs)
    sub.name = f"world.{jd['id']}"
    return sub


def _exec_script(jd: dict) -> None:
    """Run the job script in-process as ``__main__`` with its argv and
    extra env, both restored afterwards (the warm process serves many
    jobs; one job's argv/env must not leak into the next)."""
    argv0, env0 = sys.argv, {}
    sys.argv = [jd["script"]] + list(jd.get("args") or ())
    try:
        for k, v in (jd.get("env") or {}).items():
            env0[k] = os.environ.get(k)
            os.environ[k] = v
        runpy.run_path(jd["script"], run_name="__main__")
    finally:
        sys.argv = argv0
        for k, old in env0.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old


def _run_job(api, world, ctx, jd: dict, idx: int) -> None:
    import ompi_tpu.serve as serve
    from ompi_tpu.metrics import core as mcore
    from ompi_tpu.metrics import live

    rec: dict = {"ok": True, "id": jd["id"], "cid_base": jd["cid_base"],
                 "incarnation": ctx.incarnation}
    before = mcore.native_counters()
    rec["dials_before"] = {k: int(before.get(k, 0)) for k in _DIAL_KEYS}
    job = None
    rec["t_start_ns"] = time.time_ns()
    try:
        job = _job_comm(world, jd)
        rec["cid"] = int(job.cid)
        serve._set_current(dict(jd))
        live.set_job(jd["id"])
        api.push_world(job)
        _exec_script(jd)
    except SystemExit as e:
        if e.code not in (0, None):
            rec["ok"] = False
            rec["error"] = f"job script exited rc={e.code}"
    except BaseException as e:  # noqa: BLE001 — a job must never kill
        # the resident worker; MPIProcFailedError lands here too (the
        # daemon sees the dead rank and queues the repair directive)
        rec["ok"] = False
        rec["error"] = f"{type(e).__name__}: {e}"
    finally:
        if api.in_job_scope():
            api.pop_world()
        live.set_job(None)
        serve._set_current(None)
        if job is not None:
            try:
                job.free()
            except Exception:  # noqa: BLE001 — poisoned job comm
                pass
    rec["t_end_ns"] = time.time_ns()
    after = mcore.native_counters()
    rec["dials_after"] = {k: int(after.get(k, 0)) for k in _DIAL_KEYS}
    rec["counters"] = {k: int(after.get(k, 0)) for k in _REPORT_KEYS}
    _report(ctx, idx, rec)


def _repair(api, world, ctx, jd: dict, idx: int, timeout: float):
    """Survivor half of a repair directive: wait for the detector to
    surface every dead proc (gossip converges within a period), then
    ``replace()`` — the reborn incarnations rejoin through the beacon
    inside it — and adopt the healed world for future jobs."""
    dead = [int(d) for d in jd.get("dead", ())]
    deadline = time.monotonic() + timeout
    while True:
        failed = set(world.get_failed())
        missing = [p for p in dead
                   if not (set(range(*world.proc_range(p))) & failed)]
        if not missing:
            break
        if time.monotonic() > deadline:
            _report(ctx, idx, {
                "ok": False,
                "error": f"repair: procs {missing} never surfaced as "
                         f"failed within {timeout}s"})
            return world
        time.sleep(0.05)
    t0 = time.monotonic()
    try:
        healed = world.replace()
    except BaseException as e:  # noqa: BLE001 — repair must report
        _report(ctx, idx, {"ok": False,
                           "error": f"{type(e).__name__}: {e}"})
        return world
    api.set_world(healed)
    _report(ctx, idx, {"ok": True,
                       "heal_ms": round((time.monotonic() - t0) * 1e3, 3)})
    print(f"serve: repaired world (dead={dead})", flush=True)
    return healed


def _teardown_resident(api, world) -> None:
    """Raw teardown for a retired rank (or a shutdown with ranks
    missing): no finalize fence — the remaining ranks are not
    finalizing with us."""
    from ompi_tpu.metrics import live

    live.stop_publisher()
    try:
        world.procctx.close()
    except Exception:  # noqa: BLE001 — exiting anyway
        pass


def main() -> int:
    import jax

    jax.config.update("jax_platforms",
                      os.environ.get("JAX_PLATFORMS", "cpu"))
    import ompi_tpu.api as api
    from ompi_tpu.core import mca

    world = api.init()
    ctx = world.procctx
    store = mca.default_context().store
    poll = max(0.02, int(store.get("serve_poll_ms", 50) or 50) / 1000.0)
    respawn_timeout = float(store.get("ft_respawn_timeout", 60.0) or 60.0)
    if getattr(world, "respawned", False):
        # reborn incarnation: rejoin the warm world via the survivors'
        # replace round, then resume the stream where the daemon says
        world = world.replace()
        api.set_world(world)
        n = int(_kvs_wait(
            ctx, f"{K_RESUME}{ctx.proc}.i{ctx.incarnation}", poll))
        print(f"serve: incarnation {ctx.incarnation} rejoined; "
              f"resuming at directive {n}", flush=True)
    else:
        n = 0
        print(f"serve: resident worker up (proc {ctx.proc}/"
              f"{ctx.nprocs})", flush=True)
    while True:
        jd = _kvs_wait(ctx, f"{K_JOB}{n}", poll)
        idx, n = n, n + 1
        kind = jd.get("kind", "job")
        if kind == "shutdown":
            if len(jd.get("procs", ())) == ctx.nprocs:
                api.finalize()  # full house: the real fence + teardown
            else:
                _teardown_resident(api, world)
            print("serve: shutdown", flush=True)
            return 0
        if kind == "repair":
            if ctx.proc in jd.get("procs", ()):
                world = _repair(api, world, ctx, jd, idx,
                                respawn_timeout)
            continue
        if kind == "retire":
            if ctx.proc in jd.get("retire", ()):
                _report(ctx, idx, {"ok": True, "retired": True})
                _teardown_resident(api, world)
                print("serve: retired", flush=True)
                return 0
            if ctx.proc in jd.get("procs", ()):
                _report(ctx, idx, {"ok": True})
            continue
        if ctx.proc in jd.get("procs", ()):
            _run_job(api, world, ctx, jd, idx)


if __name__ == "__main__":
    sys.exit(main())
