"""Persistent serving plane — ``tpud`` (≈ orted/prted, SURVEY.md §3.1).

The reference runtime keeps a daemon alive across jobs (PAPER.md §1:
ORTE/PRRTE ``orted``/``prted`` — plm/odls/rmaps exist so launches reuse
a standing infrastructure); our ``tpurun`` boots a full world per
invocation — rendezvous, endpoint dials, engine threads — and tears it
down at exit.  This package promotes that per-job world into a
long-lived serving plane:

* :mod:`~ompi_tpu.serve.daemon` — ``TpuDaemon``: hosts the boot KVS and
  the live-telemetry aggregator (its HTTP endpoint doubles as the ops
  surface: submit/status/drain/shutdown/scale), spawns N **resident**
  rank workers, gang-schedules a multi-tenant job queue onto them
  (FIFO + per-tenant round-robin; a job runs when its full rank-set is
  free), enforces per-tenant admission quotas (``serve_max_pending``),
  and fires the elastic plane itself — a dead worker is respawned and
  restored via ``replace()`` (scale-up), ``/scale`` retires ranks
  (scale-down) — instead of recovery running only on failure;
* :mod:`~ompi_tpu.serve.worker` — the resident rank loop: boot once,
  then serve jobs forever; each job gets a disjoint CID block and a
  fresh ``MPI_COMM_WORLD``-equivalent carved from the warm mesh with
  **zero traffic and zero re-dials**, runs its script in-process
  (``api.init()`` inside the script returns the job world, its
  ``finalize()`` re-arms instead of tearing down), and reports a
  completion record;
* :mod:`~ompi_tpu.serve.client` — the attach-to-daemon HTTP client
  (``ompi_tpu.api.tpud_submit`` and ``tools/tpud_ctl.py`` ride it).

Start one with ``tpurun --daemon -np N`` or ``python tools/tpud.py``;
knobs live in the centrally registered ``SERVING_VARS``
(``core/var.py``) like the observability/robustness sets.
"""

from __future__ import annotations

#: the job record the resident worker is currently serving (None when
#: idle) — job scripts can introspect it via :func:`current_job`
_current: dict | None = None


def current_job() -> dict | None:
    """The job descriptor this process is serving right now (tenant,
    id, cid_base, args) — None outside a served job."""
    return _current


def _set_current(job: dict | None) -> None:
    global _current
    _current = job
