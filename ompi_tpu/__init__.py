"""ompi_tpu — a TPU-native MPI framework.

A brand-new message-passing framework with the capability surface of Open
MPI (reference: ``sadhananeo/ompi``), designed TPU-first: collectives
dispatch to ``jax.lax`` collectives (``psum``, ``all_gather``,
``psum_scatter``, ``ppermute``, ``all_to_all``) executed over a
persistent ICI mesh, non-blocking operations map to async XLA dispatch,
and component/tunable selection uses Open-MPI-compatible ``--mca``
semantics (``OMPI_MCA_*`` env vars, mca-params.conf files, priorities).

Layer map (≈ SURVEY.md §7):

========  =====================================================  =========================
package   role                                                   reference equivalent
========  =====================================================  =========================
core/     MCA var system + component registry + errors           opal/mca/base, opal/class
boot/     rendezvous, launch (tpurun), KVS/fence                 PMIx + PRRTE subset
mesh/     persistent device mesh, submeshes, HBM staging arena   opal/mca/accelerator
ddt/      datatype engine: derived types, pack/unpack convertor  opal/datatype, ompi/datatype
op/       reduction kernels (op × dtype), bit-exact ordered SUM  ompi/mca/op
coll/     collective components: xla, base algorithms, nbc, han  ompi/mca/coll
p2p/      point-to-point engine                                  ompi/mca/pml
api/      communicators, groups, requests, MPI entry points      ompi/communicator, mpi/c
========  =====================================================  =========================
"""

__version__ = "0.1.0"
