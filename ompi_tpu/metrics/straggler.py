"""Collective straggler profiler — who showed up late, and why.

The question the ROADMAP's osu_bw item and every production training
stack ask first: *which rank is the straggler, and is it arrival skew
or transport stall?*  This module records the per-rank half of the
answer; the cross-rank half (joining one collective's records across
all ranks) runs wherever records from every rank meet — the live
telemetry aggregator (:mod:`ompi_tpu.metrics.live`), a bench worker's
final allgather, or a post-mortem report.

Per collective call (api dispatch, :meth:`MultiProcComm._lookup`),
gated on the module ``_enabled`` bool (one test per call when off):

* **arrival** — wall-clock ns at entry, BEFORE any traffic.  Keyed
  ``(comm, op, seq)`` with a per-(comm, op) issue counter — identical
  on every rank by MPI's same-issue-order rule, so one collective's
  records align across ranks (the trace subsystem's merge key, reused);
* **exit** — wall-clock ns at completion; ``exit - arrival`` is this
  rank's total wait+wire time inside the op.

The cross-rank join decomposes a rank's in-op wait into **arrival
skew** (``last_arrival - my_arrival``: how long the early ranks idled
for the stragglers — :func:`instance_skew` / :func:`join_skew`) vs
**transport stall** (the metrics plane's ``ring_stall_ns`` /
``cts_wait_ns`` deltas over the same window — PR 2's cause counters).
A rolling per-rank straggler score (EWMA of arrival lateness) names
the culprit; the live aggregator maintains it continuously.

Aggregates follow the subsystem's grow-only pvar contract: per-op keys
appear in first-seen order and only ever append (reset zeroes in
place) — ``straggler_<op>_count`` / ``straggler_<op>_wait_ns`` MPI_T
pvars index into them.

Respawn/replace invariant: a reborn incarnation starts its per-
(comm, op) counters at zero, which is safe because post-recovery
collectives run on the freshly-named ``<comm>.replaced`` communicator
(``MultiProcComm._replace_build`` derives the same name on survivors
and the reborn rank), so EVERY participant's counter for the new comm
starts at zero together — keys stay aligned.  The dead rank's
unmatched pre-failure keys age out of the live aggregator's bounded
pending window; they are never guessed at.
"""

from __future__ import annotations

import collections
import threading
import time

#: the in-path gate — the api dispatch hook reads this directly
_enabled = False

#: recent completed-collective records awaiting publication:
#: (key, arrive_wall_ns, exit_wall_ns).  Drained by the telemetry
#: publisher each frame; bounded so an unscraped job cannot grow it.
_RECENT_CAP = 512

_lock = threading.Lock()
_seqs: dict[tuple[str, str], int] = {}
#: per-op aggregates, insertion-ordered and grow-only while profiling
#: runs (reset zeroes in place — the pvar namespace must not shrink)
_ops: dict[str, dict] = {}
_recent: collections.deque = collections.deque(maxlen=_RECENT_CAP)
#: op → winning coll component (CollTable dispatch notes it; the live
#: dashboard shows which algorithm a slow op is running)
_providers: dict[str, str] = {}
#: native per-op timing sources (the C collective fast path, PR 12's
#: observability edge): weakref → callable returning {op: {count,
#: wait_ns, max_wait_ns, lat_hist}} — C-served collectives never
#: cross Python, so without this merge the straggler_<op> pvar/prom
#: surfaces only see their merged SPC counts.  Same weakref-anchored
#: lifetime rules as metrics.core.register_provider.
_native_providers: list = []
#: MPI_T reset baselines for the native rows, keyed PER PROVIDER
#: (id(weakref) → {op: totals}): the C block is append-only so Python
#: owns reset semantics, and a per-op global baseline would let a
#: dead engine's lifetime totals suppress a respawned engine's fresh
#: counts after a pvar_reset — baselines must die with their source.
#: max_wait_ns stays raw, like the *_hwm counters in the metrics core.
_native_base: dict[int, dict[str, dict]] = {}
#: native op names ever observed, first-seen order — the grow-only
#: pvar-namespace contract holds even after the engine that produced
#: a row closes (its counts read 0; the NAME never disappears)
_native_ops_seen: list[str] = []


def enabled() -> bool:
    return _enabled


def enable(flag: bool = True) -> None:
    global _enabled
    _enabled = flag


def sync_from_store(store) -> None:
    """Armed by ``--mca metrics_enable 1`` OR ``--mca telemetry_enable
    1`` — the profiler is part of the metrics plane, and the live
    endpoint's straggler table needs it even when nobody asked for a
    finalize export."""
    enable(bool(store.get("metrics_enable", False))
           or bool(store.get("telemetry_enable", False)))


def reset() -> None:
    """Test hook: drop all state."""
    global _enabled
    with _lock:
        _seqs.clear()
        _ops.clear()
        _recent.clear()
        _providers.clear()
        _native_providers.clear()
        _native_base.clear()
        _native_ops_seen.clear()
        _enabled = False


# -- native per-op timing merge (the C collective fast path) ------------


def register_native(obj, fn) -> None:
    """Register a native per-op timing source (a live C engine).
    ``obj`` anchors the registration lifetime (weakref, like
    metrics.core.register_provider); closed engines drop out."""
    import weakref

    try:
        wfn = weakref.WeakMethod(fn)
    except TypeError:  # plain function/closure
        wfn = (lambda f=fn: f)
    with _lock:
        _native_providers.append((weakref.ref(obj), wfn))


def _provider_rows() -> list[tuple[int, dict[str, dict]]]:
    """(id(weakref), raw rows) per LIVE native source — the shared
    sweep the merge and the reset both run; prunes dead
    registrations (and their baselines — a respawned engine must not
    inherit its dead predecessor's reset baseline)."""
    with _lock:
        live = list(_native_providers)
    out: list[tuple[int, dict[str, dict]]] = []
    dead = False
    for ref, wfn in live:
        fn = wfn()
        if ref() is None or fn is None:
            dead = True
            continue
        try:
            rows = fn()
        except Exception:  # engine torn down mid-read
            continue
        if rows:
            out.append((id(ref), rows))
    if dead:
        with _lock:
            gone = [id(r) for r, f in _native_providers
                    if r() is None or f() is None]
            _native_providers[:] = [
                (r, f) for r, f in _native_providers
                if r() is not None and f() is not None]
            for k in gone:  # baselines die with their source
                _native_base.pop(k, None)
    return out


def _native_rows() -> dict[str, dict]:
    """Merged {op: {count, wait_ns, max_wait_ns, lat_hist}} across
    live native sources, baseline-adjusted PER PROVIDER (reset
    semantics live here; the C block only grows, and a per-op global
    baseline would let a dead engine's lifetime totals suppress a
    respawned engine's fresh counts after a pvar_reset)."""
    out: dict[str, dict] = {}
    with _lock:
        base = {k: {op: dict(v) for op, v in b.items()}
                for k, b in _native_base.items()}
    for key, rows in _provider_rows():
        pb = base.get(key, {})
        for op, st in rows.items():
            b = pb.get(op, {})
            count = max(0, int(st.get("count", 0))
                        - int(b.get("count", 0)))
            if not count:
                continue
            cur = out.setdefault(op, {"count": 0, "wait_ns": 0,
                                      "max_wait_ns": 0, "lat_hist": []})
            cur["count"] += count
            cur["wait_ns"] += max(0, int(st.get("wait_ns", 0))
                                  - int(b.get("wait_ns", 0)))
            cur["max_wait_ns"] = max(cur["max_wait_ns"],
                                     int(st.get("max_wait_ns", 0)))
            hist = [int(v) for v in st.get("lat_hist") or []]
            bh = b.get("lat_hist") or []
            for i, v in enumerate(hist):
                hist[i] = max(0, v - (bh[i] if i < len(bh) else 0))
            if len(hist) > len(cur["lat_hist"]):
                cur["lat_hist"] += [0] * (len(hist)
                                          - len(cur["lat_hist"]))
            for i, v in enumerate(hist):
                cur["lat_hist"][i] += v
    with _lock:
        for op in out:
            if op not in _native_ops_seen:
                _native_ops_seen.append(op)
    return out


def _next_seq(comm: str, op: str) -> int:
    key = (comm, op)
    with _lock:
        s = _seqs.get(key, 0)
        _seqs[key] = s + 1
        return s


def note_provider(op: str, provider: str) -> None:
    """Coll dispatch tells us which component serves the op (one dict
    store per lookup when enabled; callers gate on ``_enabled``)."""
    _providers[op] = provider


def record(comm: str, op: str, arrive_ns: int, exit_ns: int) -> None:
    """One completed collective: fold into the per-op aggregate and
    queue the instance record for the next telemetry frame."""
    wait = max(0, exit_ns - arrive_ns)
    key = f"{comm}/{op}/{_next_seq(comm, op)}"
    with _lock:
        st = _ops.get(op)
        if st is None:
            st = _ops[op] = {"count": 0, "wait_ns": 0, "max_wait_ns": 0}
        st["count"] += 1
        st["wait_ns"] += wait
        if wait > st["max_wait_ns"]:
            st["max_wait_ns"] = wait
        _recent.append((key, int(arrive_ns), int(exit_ns)))


def wrap_call(op: str, fn, comm: str = ""):
    """Closure recording one collective around each call — the api
    dispatch hook (sits INSIDE the trace wrap so trace spans cover
    the same interval).  Timestamps are wall-clock ns: records from
    different ranks must land on one comparable timeline (the clock-
    offset estimate in the merge corrects residual host skew)."""

    def profiled(*a, **k):
        t0 = time.time_ns()
        try:
            return fn(*a, **k)
        finally:
            record(comm, op, t0, time.time_ns())

    profiled.__name__ = f"straggler_{op}"
    profiled.__wrapped__ = fn
    return profiled


# -- introspection (pvars, snapshots, frames) ---------------------------


def ops(refresh: bool = True) -> list[str]:
    """Op names with ≥1 record, FIRST-SEEN order — the
    ``straggler_<op>_*`` pvar namespace (grow-only while profiling
    runs; reset zeroes in place).  C-fast-path ops append after the
    Python-recorded ones; once seen they never drop out (a closed
    engine's counts read 0, but cached pvar indices stay valid).

    ``refresh=False`` skips the native-provider sweep and lists only
    already-seen ops — the pvar READ path uses it (name→index lookup
    per read must not pay a ctypes sweep per live engine; discovery
    entry points like ``pvar_get_num`` refresh)."""
    if refresh:
        _native_rows()  # refresh the grow-only seen list
    out = list(_ops)
    for op in _native_ops_seen:
        if op not in out:
            out.append(op)
    return out


def native_rows() -> dict[str, dict]:
    """One merged native sweep — pass to the per-op accessors below
    to read many ops from a single snapshot."""
    return _native_rows()


def op_count(op: str, rows: dict | None = None) -> int:
    st = _ops.get(op)
    n = st["count"] if st else 0
    nat = (rows if rows is not None else _native_rows()).get(op)
    return n + (nat["count"] if nat else 0)


def op_wait_ns(op: str, rows: dict | None = None) -> int:
    st = _ops.get(op)
    n = st["wait_ns"] if st else 0
    nat = (rows if rows is not None else _native_rows()).get(op)
    return n + (nat["wait_ns"] if nat else 0)


def summary() -> dict[str, dict]:
    """Per-op aggregates (+ serving component when known) — the
    snapshot/frame section.  C-fast-path rows (per-op duration
    emitted from tdcn_coll_start) merge in under the same op keys,
    carrying their log2-µs latency histogram; a row served by BOTH
    planes sums counts/waits and keeps the max."""
    with _lock:
        out = {
            op: dict(st, provider=_providers.get(op, ""))
            for op, st in _ops.items()
        }
    for op, nat in _native_rows().items():
        st = out.get(op)
        if st is None:
            out[op] = dict(nat, provider="cfp")
            continue
        st["count"] += nat["count"]
        st["wait_ns"] += nat["wait_ns"]
        st["max_wait_ns"] = max(st["max_wait_ns"], nat["max_wait_ns"])
        st["lat_hist"] = list(nat.get("lat_hist") or [])
    return out


def drain_recent() -> list[list]:
    """Pop every queued instance record (JSON-able ``[key, arrive_ns,
    exit_ns]`` rows) — one consumer, the telemetry publisher."""
    out = []
    with _lock:
        while _recent:
            k, a, x = _recent.popleft()
            out.append([k, a, x])
    return out


def recent() -> list[list]:
    """Non-destructive view of the queued records (finalize export,
    bench workers that join skew themselves)."""
    with _lock:
        return [[k, a, x] for k, a, x in _recent]


def zero_stats() -> None:
    """Session-wide pvar_reset: zero aggregates IN PLACE (keys and seq
    counters survive — cross-rank keys must not desync mid-run).  The
    native C rows re-baseline (the C block only grows; max_wait_ns
    stays raw, the *_hwm convention)."""
    with _lock:
        for st in _ops.values():
            st["count"] = 0
            st["wait_ns"] = 0
            st["max_wait_ns"] = 0
    # native rows re-baseline PER PROVIDER: the baseline is a raw-
    # total snapshot keyed by the provider registration, so it dies
    # with its engine and can never suppress a respawned successor
    snaps = _provider_rows()
    with _lock:
        for key, rows in snaps:
            pb = _native_base.setdefault(key, {})
            for op, st in rows.items():
                pb[op] = {
                    "count": int(st.get("count", 0)),
                    "wait_ns": int(st.get("wait_ns", 0)),
                    "lat_hist": [int(v)
                                 for v in st.get("lat_hist") or []],
                }


def reset_op(op: str) -> None:
    """Per-handle pvar_reset: zero ONE op — including its native
    C-fast-path rows, which re-baseline per provider exactly like
    :func:`zero_stats` (the session-wide path), so a per-handle
    MPI_T_pvar_reset honors the same reset contract."""
    with _lock:
        st = _ops.get(op)
        if st is not None:
            st["count"] = 0
            st["wait_ns"] = 0
            st["max_wait_ns"] = 0
    snaps = _provider_rows()
    with _lock:
        for key, rows in snaps:
            row = rows.get(op)
            if row is None:
                continue
            pb = _native_base.setdefault(key, {})
            pb[op] = {
                "count": int(row.get("count", 0)),
                "wait_ns": int(row.get("wait_ns", 0)),
                "lat_hist": [int(v)
                             for v in row.get("lat_hist") or []],
            }


# -- cross-rank skew (pure helpers shared by aggregator/bench/report) ---


def instance_skew(arrivals: dict[int, int]) -> tuple[int, dict[int, int]]:
    """One collective instance across ranks: ``arrivals[proc] =
    arrive_ns`` (clock-aligned).  Returns ``(slowest_proc, {proc:
    lateness_ns})`` where lateness is the gap behind the FIRST
    arrival — the time every earlier rank spent waiting for that
    rank (0 for the first arrival)."""
    first = min(arrivals.values())
    skews = {p: a - first for p, a in arrivals.items()}
    slowest = max(skews, key=lambda p: (skews[p], p))
    return slowest, skews


def join_skew(records_by_proc: dict[int, list],
              offsets_ns: dict[int, int] | None = None) -> dict:
    """Post-hoc join of per-rank instance records (``[key, arrive_ns,
    exit_ns]`` rows, as :func:`drain_recent`/:func:`recent` emit).
    ``offsets_ns[proc]`` (peer_clock − reference_clock, the handshake
    estimate) aligns arrivals before comparison.  Returns::

        {"instances": N,                      # keys seen on every rank
         "per_op":  {op: {"n", "skew_ns", "max_skew_ns", "slowest": {proc: count}}},
         "per_proc": {proc: {"skew_ns", "slowest", "n"}}}
    """
    offsets_ns = offsets_ns or {}
    by_key: dict[str, dict[int, int]] = {}
    for proc, rows in records_by_proc.items():
        off = int(offsets_ns.get(proc, 0))
        for key, a, _x in rows:
            by_key.setdefault(key, {})[int(proc)] = int(a) - off
    nprocs = len(records_by_proc)
    per_op: dict[str, dict] = {}
    per_proc: dict[int, dict] = {
        int(p): {"skew_ns": 0, "slowest": 0, "n": 0}
        for p in records_by_proc
    }
    instances = 0
    for key, arrivals in by_key.items():
        if len(arrivals) < nprocs:
            continue  # a rank's record rolled off — skip, never guess
        instances += 1
        op = key.split("/")[-2] if key.count("/") >= 2 else key
        slowest, skews = instance_skew(arrivals)
        st = per_op.setdefault(
            op, {"n": 0, "skew_ns": 0, "max_skew_ns": 0, "slowest": {}})
        st["n"] += 1
        worst = skews[slowest]
        st["skew_ns"] += worst
        if worst > st["max_skew_ns"]:
            st["max_skew_ns"] = worst
        st["slowest"][slowest] = st["slowest"].get(slowest, 0) + 1
        for p, s in skews.items():
            pp = per_proc[p]
            pp["skew_ns"] += s
            pp["n"] += 1
            if p == slowest:
                pp["slowest"] += 1
    return {"instances": instances, "per_op": per_op,
            "per_proc": per_proc}
