"""Snapshot exporters — Prometheus text format + JSONL.

Written at finalize (``api.finalize`` → :func:`write`) when ``--mca
metrics_enable 1`` is on and ``--mca metrics_output <path>`` names a
base path; every process writes

* ``<path>.<proc>.prom``  — Prometheus text exposition format
  (``ompi_tpu_``-prefixed counters + cumulative ``_bucket{le=…}``
  histograms), scrapeable by pointing a node-exporter textfile
  collector at the directory;
* ``<path>.<proc>.jsonl`` — one JSON object per line: every flight
  record in order, then the final snapshot — the
  ``tools/metrics_report.py`` input.

Stdlib-only on purpose: the report tool imports this module on hosts
with no jax.
"""

from __future__ import annotations

import json

from ompi_tpu.metrics import core as _core
from ompi_tpu.metrics import flight as _flight

PREFIX = "ompi_tpu"


def _size_bucket_edges() -> list[int]:
    """Upper bucket edges in bytes: 1, 2, 4, … (last is +Inf)."""
    return [1 << i for i in range(_core.SIZE_BUCKETS - 1)]


def _lat_bucket_edges_us() -> list[int]:
    return [1 << i for i in range(_core.LAT_BUCKETS - 1)]


def _prom_hist(lines: list[str], name: str, labels: str, hist: list[int],
               edges: list[int], total: int | None = None) -> None:
    """Cumulative Prometheus _bucket series from a fixed-bucket log2
    histogram (our buckets are disjoint; Prometheus wants cumulative)."""
    cum = 0
    for i, edge in enumerate(edges):
        cum += hist[i] if i < len(hist) else 0
        lines.append(f'{name}_bucket{{{labels}le="{edge}"}} {cum}')
    cum += hist[len(edges)] if len(hist) > len(edges) else 0
    lines.append(f'{name}_bucket{{{labels}le="+Inf"}} {cum}')
    lines.append(f"{name}_count{{{labels.rstrip(',')}}} {cum}"
                 if labels else f"{name}_count {cum}")
    if total is not None:
        lines.append(f"{name}_sum{{{labels.rstrip(',')}}} {total}"
                     if labels else f"{name}_sum {total}")


def to_prometheus(snap: dict) -> str:
    """Render one snapshot as Prometheus text exposition format."""
    proc = snap.get("proc")
    plabel = f'proc="{proc}",' if proc is not None else ""
    lines: list[str] = []
    # native transport counters: each is its OWN metric family, so the
    # TYPE line must name it (the exposition-format contract promtool
    # enforces); gauges/high-waters are typed gauge — rate() over a
    # decreasing rndv_depth would fabricate counter resets
    for k, v in (snap.get("native") or {}).items():
        gauge = k in _core.GAUGES or k.endswith("_hwm")
        lines.append(f"# HELP {PREFIX}_dcn_{k} Native DCN transport "
                     f"{'gauge' if gauge else 'counter'} {k} "
                     "(libtpudcn TdcnStats block)")
        lines.append(f"# TYPE {PREFIX}_dcn_{k} "
                     f"{'gauge' if gauge else 'counter'}")
        if plabel:
            lines.append(f"{PREFIX}_dcn_{k}{{{plabel.rstrip(',')}}} {int(v)}")
        else:
            lines.append(f"{PREFIX}_dcn_{k} {int(v)}")
    # per-op size/latency histograms
    lines.append(f"# HELP {PREFIX}_op_size_bytes Per-op payload size "
                 "histogram (log2 buckets)")
    lines.append(f"# TYPE {PREFIX}_op_size_bytes histogram")
    for op, st in (snap.get("ops") or {}).items():
        labels = f'{plabel}op="{op}",'
        _prom_hist(lines, f"{PREFIX}_op_size_bytes", labels,
                   st["size_hist"], _size_bucket_edges(),
                   total=st.get("bytes"))
    lines.append(f"# HELP {PREFIX}_op_latency_us Per-op latency "
                 "histogram (log2 µs buckets)")
    lines.append(f"# TYPE {PREFIX}_op_latency_us histogram")
    for op, st in (snap.get("ops") or {}).items():
        if not any(st["lat_hist"]):
            continue
        labels = f'{plabel}op="{op}",'
        _prom_hist(lines, f"{PREFIX}_op_latency_us", labels,
                   st["lat_hist"], _lat_bucket_edges_us(),
                   total=(st.get("total_ns", 0) + 999) // 1000)
    # SPC counters ride along (one scrape = the whole tool stack)
    spc = snap.get("spc") or {}
    if spc:
        lines.append(f"# HELP {PREFIX}_spc_total SPC software "
                     "performance counters")
        lines.append(f"# TYPE {PREFIX}_spc_total counter")
        for k, v in sorted(spc.items()):
            lines.append(f'{PREFIX}_spc_total{{{plabel}counter="{k}"}} '
                         f"{int(v)}")
    lines.append("")
    return "\n".join(lines)


def write(path_base: str, proc: int = 0) -> list[str]:
    """Export the final snapshot (+ accumulated flight records) for
    one process.  Returns the paths written."""
    snap = _core.snapshot(reason="finalize", proc=proc)
    paths = []
    prom_path = f"{path_base}.{proc}.prom"
    with open(prom_path, "w") as f:
        f.write(to_prometheus(snap))
    paths.append(prom_path)
    jsonl_path = f"{path_base}.{proc}.jsonl"
    with open(jsonl_path, "w") as f:
        for rec in _flight.records():
            f.write(json.dumps(rec) + "\n")
        f.write(json.dumps(snap) + "\n")
    paths.append(jsonl_path)
    return paths
