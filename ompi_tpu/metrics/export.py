"""Snapshot exporters — Prometheus text format + JSONL.

Written at finalize (``api.finalize`` → :func:`write`) when ``--mca
metrics_enable 1`` is on and ``--mca metrics_output <path>`` names a
base path; every process writes

* ``<path>.<proc>.prom``  — Prometheus text exposition format
  (``ompi_tpu_``-prefixed counters + cumulative ``_bucket{le=…}``
  histograms), scrapeable by pointing a node-exporter textfile
  collector at the directory;
* ``<path>.<proc>.jsonl`` — one JSON object per line: every flight
  record in order, then the final snapshot — the
  ``tools/metrics_report.py`` input.

Stdlib-only on purpose: the report tool imports this module on hosts
with no jax.
"""

from __future__ import annotations

import json

from ompi_tpu.metrics import core as _core
from ompi_tpu.metrics import flight as _flight

PREFIX = "ompi_tpu"


def _size_bucket_edges() -> list[int]:
    """Upper bucket edges in bytes: 1, 2, 4, … (last is +Inf)."""
    return [1 << i for i in range(_core.SIZE_BUCKETS - 1)]


def _lat_bucket_edges_us() -> list[int]:
    return [1 << i for i in range(_core.LAT_BUCKETS - 1)]


def _prom_hist(lines: list[str], name: str, labels: str, hist: list[int],
               edges: list[int], total: int | None = None) -> None:
    """Cumulative Prometheus _bucket series from a fixed-bucket log2
    histogram (our buckets are disjoint; Prometheus wants cumulative)."""
    cum = 0
    for i, edge in enumerate(edges):
        cum += hist[i] if i < len(hist) else 0
        lines.append(f'{name}_bucket{{{labels}le="{edge}"}} {cum}')
    cum += hist[len(edges)] if len(hist) > len(edges) else 0
    lines.append(f'{name}_bucket{{{labels}le="+Inf"}} {cum}')
    lines.append(f"{name}_count{{{labels.rstrip(',')}}} {cum}"
                 if labels else f"{name}_count {cum}")
    if total is not None:
        lines.append(f"{name}_sum{{{labels.rstrip(',')}}} {total}"
                     if labels else f"{name}_sum {total}")


def dcn_kind(k: str) -> str:
    """gauge-vs-counter classification for a ``dcn_<k>`` family —
    ONE rule shared by the finalize ``.prom`` exporter and the live
    endpoint, so both type a family identically (a family typed
    counter on one and gauge on the other breaks ``rate()`` queries
    spanning both; a gauge like a decreasing rndv_depth typed counter
    would fabricate resets)."""
    return "gauge" if k in _core.GAUGES or k.endswith("_hwm") else "counter"


def dcn_family(lines: list[str], k: str, samples: list[tuple[str, int]],
               origin: str = "Native", suffix: str = "") -> None:
    """Append one ``{PREFIX}_dcn_<k>`` metric family: HELP/TYPE header
    (each counter is its OWN family, so the TYPE line must name it —
    the exposition-format contract promtool enforces) plus one sample
    per ``(labels, value)`` row (``labels`` pre-rendered, may be '')."""
    kind = dcn_kind(k)
    lines.append(f"# HELP {PREFIX}_dcn_{k} {origin} DCN transport "
                 f"{kind} {k}{suffix}")
    lines.append(f"# TYPE {PREFIX}_dcn_{k} {kind}")
    for labels, v in samples:
        lines.append(f"{PREFIX}_dcn_{k}{labels} {int(v)}")


def to_prometheus(snap: dict) -> str:
    """Render one snapshot as Prometheus text exposition format."""
    proc = snap.get("proc")
    plabel = f'proc="{proc}",' if proc is not None else ""
    lines: list[str] = []
    for k, v in (snap.get("native") or {}).items():
        labels = f'{{{plabel.rstrip(",")}}}' if plabel else ""
        dcn_family(lines, k, [(labels, int(v))],
                   suffix=" (libtpudcn TdcnStats block)")
    # per-op size/latency histograms
    lines.append(f"# HELP {PREFIX}_op_size_bytes Per-op payload size "
                 "histogram (log2 buckets)")
    lines.append(f"# TYPE {PREFIX}_op_size_bytes histogram")
    for op, st in (snap.get("ops") or {}).items():
        labels = f'{plabel}op="{op}",'
        _prom_hist(lines, f"{PREFIX}_op_size_bytes", labels,
                   st["size_hist"], _size_bucket_edges(),
                   total=st.get("bytes"))
    lines.append(f"# HELP {PREFIX}_op_latency_us Per-op latency "
                 "histogram (log2 µs buckets)")
    lines.append(f"# TYPE {PREFIX}_op_latency_us histogram")
    for op, st in (snap.get("ops") or {}).items():
        if not any(st["lat_hist"]):
            continue
        labels = f'{plabel}op="{op}",'
        _prom_hist(lines, f"{PREFIX}_op_latency_us", labels,
                   st["lat_hist"], _lat_bucket_edges_us(),
                   total=(st.get("total_ns", 0) + 999) // 1000)
    # straggler profiler: per-op call/wait totals (the cross-rank skew
    # attribution lives on the LIVE endpoint / merge tools — this is
    # the rank-local leg)
    strag = snap.get("straggler") or {}
    if strag:
        lines.append(f"# HELP {PREFIX}_coll_wait_ns_total In-collective "
                     "wall time by op (arrival wait + wire)")
        lines.append(f"# TYPE {PREFIX}_coll_wait_ns_total counter")
        for op, st in strag.items():
            lines.append(f'{PREFIX}_coll_wait_ns_total{{{plabel}op="{op}"'
                         f'}} {int(st.get("wait_ns", 0))}')
    # causal-tracing counters (trace_causal_* pvar twins): rank-local
    # record/edge totals — the cross-rank blame itself lives in the
    # snapshot's "causal" records (joined offline) and on /critical
    causal_c = snap.get("causal_counters") or {}
    if causal_c:
        for k in sorted(causal_c):
            lines.append(f"# HELP {PREFIX}_trace_causal_{k} causal "
                         f"tracing {k} (trace/causal.py)")
            lines.append(f"# TYPE {PREFIX}_trace_causal_{k} counter")
            labels = f'{{{plabel.rstrip(",")}}}' if plabel else ""
            lines.append(f"{PREFIX}_trace_causal_{k}{labels} "
                         f"{int(causal_c[k])}")
    # SPC counters ride along (one scrape = the whole tool stack)
    spc = snap.get("spc") or {}
    if spc:
        lines.append(f"# HELP {PREFIX}_spc_total SPC software "
                     "performance counters")
        lines.append(f"# TYPE {PREFIX}_spc_total counter")
        for k, v in sorted(spc.items()):
            lines.append(f'{PREFIX}_spc_total{{{plabel}counter="{k}"}} '
                         f"{int(v)}")
    lines.append("")
    return "\n".join(lines)


def write(path_base: str, proc: int = 0,
          partial: bool = False) -> list[str]:
    """Export the final snapshot (+ accumulated flight records) for
    one process.  Returns the paths written.  ``partial=True`` marks a
    crash-path dump (the rank died or aborted before finalize): the
    snapshot carries ``"partial": true`` so report tools know the
    counters stop mid-run rather than at a clean shutdown."""
    snap = _core.snapshot(reason="crash" if partial else "finalize",
                          proc=proc)
    if partial:
        snap["partial"] = True
    from ompi_tpu.trace import causal as _causal

    if _causal.enabled():
        # the finalize causal export: this rank's recent causal
        # records (the offline cross-rank join's per-rank input — the
        # adaptive-selection item's training data) + the pvar counters
        snap["causal"] = _causal.recent()
        snap["causal_counters"] = _causal.counters_snapshot()
    paths = []
    prom_path = f"{path_base}.{proc}.prom"
    with open(prom_path, "w") as f:
        f.write(to_prometheus(snap))
    paths.append(prom_path)
    jsonl_path = f"{path_base}.{proc}.jsonl"
    with open(jsonl_path, "w") as f:
        for rec in _flight.records():
            f.write(json.dumps(rec) + "\n")
        f.write(json.dumps(snap) + "\n")
    paths.append(jsonl_path)
    return paths


#: crash-path once-latch: a dying rank flushes at most once — the
#: escalation sites AND the atexit hook may both fire on one death
_crashed = False


def crash_dump(reason: str = "crash") -> list[str]:
    """Crash-path export: flush whatever telemetry is configured RIGHT
    NOW, marked ``partial: true`` — called from ULFM escalation paths
    and the api-layer atexit hook so a dying or aborting rank still
    leaves its metrics/trace files behind (a clean finalize later
    simply overwrites them with the full export).  Never raises; no-op
    when nothing is enabled, when no output path is configured, or on
    a second call."""
    global _crashed
    if _crashed:
        return []
    paths: list[str] = []
    try:
        from ompi_tpu.core import mca

        store = mca.default_context().store
        import os

        proc = int(os.environ.get("OMPI_TPU_PROC", "0"))
        mout = store.get("metrics_output", "") if _core._enabled else ""
        from ompi_tpu.trace import chrome as _tchrome, core as _tcore

        tout = store.get("trace_output", "") if _tcore.enabled() else ""
        if not mout and not tout:
            return []  # nothing configured: do NOT burn the latch
        _crashed = True
        if mout:
            _flight.record("crash_export", cause=reason)
            paths += write(str(mout), proc=proc, partial=True)
        if tout:
            paths.append(_tchrome.dump(f"{tout}.{proc}.json", pid=proc,
                                       partial=True))
    except Exception:  # noqa: BLE001 — the dump rides failure paths
        pass
    return paths


def reset_crash_latch() -> None:
    """Test hook (and finalize): re-arm the crash-path once-latch."""
    global _crashed
    _crashed = False
