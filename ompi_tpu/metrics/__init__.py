"""``ompi_tpu.metrics`` — transport telemetry (the quantitative leg of
the observability stack; the PR-1 tracer is the qualitative leg).

Four pieces:

* :mod:`.core`   — counter/histogram aggregation over both planes
  (native ``TdcnStats`` via ctypes + Python transport/op hooks);
* :mod:`.export` — Prometheus text-format + JSONL snapshot writers
  (``--mca metrics_output`` at finalize);
* :mod:`.flight` — flight recorder: counter snapshots on
  request-timeout/abort and stall-watermark crossings;
* :mod:`.straggler` — collective straggler profiler: per-rank
  arrival/exit timestamps keyed ``(comm, op, seq)`` + the cross-rank
  arrival-skew join;
* :mod:`.live`   — the live telemetry plane: per-rank frame pump →
  aggregator in ``tpurun`` serving a mid-job Prometheus scrape
  endpoint, the ``tools/top.py`` JSON feed, and the straggler
  attribution (``--mca telemetry_enable 1``);
* MPI_T pvars (``dcn_stall_ns``, ``dcn_doorbells``, ``dcn_ring_hwm``,
  per-op ``metrics_size_<op>_hist`` and ``straggler_<op>_*``) through
  :mod:`ompi_tpu.tool.mpit`.

Enable with ``--mca metrics_enable 1``; analyze with
``tools/metrics_report.py`` (``--correlate`` joins counter snapshots
with PR-1 trace spans on the shared wall-clock timeline) or watch a
RUNNING job with ``tools/top.py`` over the live endpoint.
"""

from .core import (  # noqa: F401
    GAUGES,
    LAT_BUCKETS,
    NATIVE_COUNTERS,
    SIZE_BUCKETS,
    enable,
    enabled,
    native_counters,
    native_value,
    observe,
    observe_size,
    op_stats,
    register_provider,
    register_vars,
    reset,
    size_histogram,
    size_ops,
    snapshot,
    sync_from_store,
    zero_stats,
)
