"""Metrics core — quantitative transport telemetry spanning both planes.

≈ the reference's SPC counter block (``ompi_spc.c``) plus the MPI_T
pvar surface, extended down into the native data plane: ``libtpudcn``
keeps a versioned, cache-line-aligned block of relaxed-atomic counters
(doorbell rings, backpressure stall nanoseconds, ring occupancy
high-water, eager/rendezvous/chunked traffic, rendezvous queue depth
— ``native/src/dcn.cc`` ``TdcnStats``), and this module reads it
through one ctypes call with zero effect on the hot path.  The Python
transports (:mod:`ompi_tpu.dcn.tcp`) contribute the same counter
names, so a ``--mca btl tcp`` job and a native job export one schema.

Recording discipline (the trace/SPC pattern): every Python in-path
hook is guarded by the module-level ``_enabled`` boolean — a disabled
run pays exactly one attribute test per hook.  The native counters
accumulate unconditionally (one relaxed atomic per event; the C plane
cannot see the Python gate and does not need to — the cost is below
measurement noise), but nothing reads them unless metrics are on.

Aggregation model:

* **native counters** — monotone totals merged from every registered
  provider (live engines / transports), surfaced as ``dcn_*`` MPI_T
  pvars with a reset-baseline so ``MPI_T_pvar_reset`` works without
  touching the C plane;
* **per-op histograms** — fixed-bucket log2 size (bytes) and latency
  (µs) histograms per operation, grow-only key order (the pvar
  index-stability contract :mod:`ompi_tpu.trace.core` established);
* **snapshots** — one JSON-able dict combining both planes plus the
  SPC counters, consumed by the Prometheus/JSONL exporter
  (:mod:`ompi_tpu.metrics.export`), the flight recorder
  (:mod:`ompi_tpu.metrics.flight`), and ``tools/metrics_report.py``.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Callable

#: the in-path gate — hooks read this attribute directly
_enabled = False

#: log2-bytes size buckets: bucket i holds 2**(i-1) < nbytes <= 2**i
#: (bucket 0: zero/one byte); upper-INCLUSIVE so a power-of-two
#: payload — the dominant case (osu sweeps, page-sized buffers) —
#: lands AT its own edge, matching Prometheus's inclusive ``le``
#: semantics.  The last bucket is open-ended (> 4 MiB lands in 23
#: with 24 buckets — covers the osu sweep).
SIZE_BUCKETS = 24
#: log2-µs latency buckets — same bucket COUNT/scale as
#: trace.HIST_BUCKETS, but upper-inclusive edges like the size buckets
#: (the Prometheus ``le`` contract; the tracer's pvar histograms keep
#: their original half-open convention)
LAT_BUCKETS = 16

#: native counter names, index order of the C block MINUS the version
#: slot (``tdcn_stats_names``).  FIXED — these are the stable MPI_T
#: pvar names (``dcn_<name>``); new counters append at the tail only.
NATIVE_COUNTERS = (
    "doorbells", "stall_ns", "ring_stall_ns", "ring_stalls", "ring_hwm",
    "cts_wait_ns", "cts_waits", "rndv_depth", "rndv_hwm", "slot_waits",
    "eager_msgs", "eager_bytes", "chunked_msgs", "chunked_bytes",
    "rndv_msgs", "rndv_bytes", "delivered", "unexpected_hwm",
    # robustness tail (appended — cached pvar indices stay valid):
    # transport self-healing activity and ULFM-grade escalations
    "reconnects", "retry_dials", "retry_sends", "deadline_expired",
    "injected_faults",
    # elastic-recovery tail: duplicates dropped by the exactly-once
    # rx seq filter, and peers restored by replace() after a respawn
    "dedup_drops", "respawns",
    # streaming-send-engine tail: doorbell wakes skipped because no
    # consumer was parked (doorbells + doorbells_suppressed = every
    # record published), messages/bytes routed through the pipelined
    # sender, its live depth / queued-unsent-bytes gauges (+ HWMs),
    # adaptive chunk halvings under ring stall, full-ring turns the
    # sender yielded to other peers' work, and enqueues that blocked
    # on dcn_inflight_limit
    "doorbells_suppressed", "stream_msgs", "stream_bytes",
    "stream_depth", "stream_depth_hwm", "stream_inflight",
    "stream_inflight_hwm", "chunk_shrinks", "sender_yields",
    "enqueue_waits",
    # dispatch-floor tail: collectives served entirely by the C fast
    # path, compiled-schedule cache hits/misses (the C plan cache AND
    # the Python sched.CACHE merge into the same two names), and
    # receives landed straight in a posted buffer (in-place eager
    # memcpy or streamed RTS fill — either plane)
    "coll_fastpath_ops", "sched_cache_hits", "sched_cache_misses",
    "recv_into_placed",
    # sharded-modex tail: peer addresses installed eagerly (bulk boot
    # installs + replace() refreshes) vs resolved lazily on first use
    # (the AddressTable resolver, either plane) — the np>=16 native
    # boot proof reads addr_installs <= group size instead of P-1
    "addr_installs", "addr_lazy_resolved",
    # device-plane tail (the third DCN plane, dcn/device.py — the
    # ``dcn_device_*`` pvar family): transfers sent/received through
    # device windows, bytes a DMA placed, recv-semaphore waits that
    # actually blocked (+ their ns), per-message plane-arbitration
    # decisions, and eligible sends that degraded to the host plane.
    # Maintained by the Python DevicePlane provider on every engine;
    # the C block keeps zeroed slots so the two name tables stay the
    # single source of schema truth
    "device_sends", "device_recvs", "device_bytes_placed",
    "device_dma_waits", "device_dma_wait_ns",
    "device_arb_device", "device_arb_host", "device_fallbacks",
    # device-window reclaim tail: windows force-retired because the
    # receiver was marked failed between RTS and consume (the PR-14
    # leak edge, closed) — each reclaim is also flight-recorded
    "device_window_reclaimed",
    # plane-health tail: the per-(peer, plane) failover state machine
    # (dcn/device.py PlaneHealth) — peers demoted off a sick plane
    # after dcn_plane_strikes consecutive failures, peers promoted
    # back after a successful heal probe, and the probe sends routed
    # through a demoted plane to test it.  Every transition is also
    # flight-recorded; the C block keeps zeroed slots (schema truth
    # stays TDCN_STAT_NAMES)
    "plane_demotions", "plane_promotions", "plane_heal_probes",
    # serving-plane tail: tpud overload/concurrency counters — gang
    # concurrency high-water (``_hwm`` suffix → max-merge, baseline
    # exempt), submits shed 429 by the telemetry-driven admission
    # controller, jobs whose Deadline expiry revoked their comm, and
    # jobs re-enqueued by the repair retry budget.  Maintained by the
    # daemon-process provider (serve/daemon.py); the C block keeps
    # zeroed slots so TDCN_STAT_NAMES stays the single schema truth
    "jobs_concurrent_hwm", "jobs_shed", "jobs_deadline_expired",
    "jobs_retried",
    # hang-diagnosis tail: blocked-state snapshots taken (on demand —
    # telemetry frames, /waitgraph, crash exports) and cross-rank hang
    # reports assembled by the wait-graph solver (trace/waitgraph.py,
    # which owns the Python provider); the C block keeps zeroed slots
    # so TDCN_STAT_NAMES stays the single schema truth
    "hang_snapshots", "hang_reports",
)

#: counters that are gauges (instantaneous), not monotone totals —
#: excluded from monotonicity assertions and baseline subtraction
GAUGES = frozenset({"rndv_depth", "stream_depth", "stream_inflight"})

NATIVE_STATS_VERSION = 1

_lock = threading.Lock()
#: per-op aggregates, insertion-ordered and grow-only while metrics
#: run (reset zeroes in place — the pvar namespace must not shrink)
_ops: dict[str, dict] = {}
#: live native-counter providers: weakref → callable returning a
#: dict[str, int] (or None when the provider is gone/closed)
_providers: list = []
#: MPI_T reset baselines for the native counters (reset = remember the
#: current total; reads subtract — the C plane stays untouched)
_native_base: dict[str, int] = {}
#: wall-clock anchor captured at enable: (time_ns, perf_counter_ns) —
#: snapshot timestamps join the trace timeline on this base
_epoch: tuple[int, int] = (0, 0)
#: per-peer clock-offset providers (live engines): weakref → callable
#: returning {root_proc: (offset_ns, rtt_ns)} — the HELLO→SEQACK
#: handshake estimate the cross-rank merge aligns timelines with
_clock_providers: list = []


def enabled() -> bool:
    return _enabled


def enable(flag: bool = True) -> None:
    """Turn the Python-side hooks on/off (production jobs go through
    ``--mca metrics_enable 1`` → :func:`sync_from_store`)."""
    global _enabled, _epoch
    if flag and not _enabled:
        _epoch = (time.time_ns(), time.perf_counter_ns())
    _enabled = flag


def epoch() -> tuple[int, int]:
    """(wall-clock ns, perf_counter ns) anchor captured at enable."""
    return _epoch


def size_bucket(nbytes: int) -> int:
    """log2 bucket for a payload size (shared with the SPC byte-counter
    routing — one bucket convention across the subsystem).  ``n-1``
    before bit_length makes the bucket edge upper-inclusive: exactly
    2**i counts under ``le="2**i"``, not in the bucket above it."""
    return min(max(0, int(nbytes) - 1).bit_length(), SIZE_BUCKETS - 1)


def lat_bucket(dur_ns: int) -> int:
    return min(max(0, int(dur_ns) // 1000 - 1).bit_length(),
               LAT_BUCKETS - 1)


def observe(op: str, nbytes: int, dur_ns: int | None = None) -> None:
    """Record one operation: size histogram always, latency histogram
    when a duration is supplied.  Callers gate on ``_enabled``."""
    if not _enabled:
        return
    with _lock:
        st = _ops.get(op)
        if st is None:
            st = _ops[op] = {
                "count": 0, "bytes": 0, "total_ns": 0, "max_ns": 0,
                "size_hist": [0] * SIZE_BUCKETS,
                "lat_hist": [0] * LAT_BUCKETS,
            }
        st["count"] += 1
        st["bytes"] += int(nbytes)
        st["size_hist"][size_bucket(nbytes)] += 1
        if dur_ns is not None:
            st["total_ns"] += int(dur_ns)
            if dur_ns > st["max_ns"]:
                st["max_ns"] = int(dur_ns)
            st["lat_hist"][lat_bucket(dur_ns)] += 1


def observe_size(op: str, nbytes: int) -> None:
    """Size-only observation (the SPC payload-bytes routing)."""
    observe(op, nbytes, None)


# -- native counter providers ------------------------------------------


def register_provider(obj, fn: Callable[[], dict | None]) -> None:
    """Register a native-counter source (a live engine/transport).

    ``obj`` anchors the registration lifetime: the provider drops out
    when ``obj`` is collected, so closed engines never pin themselves
    through the global list.  Bound methods are held weakly too — a
    strong reference to ``obj.method`` would keep ``obj`` alive and
    defeat the anchor."""
    try:
        wfn: Callable = weakref.WeakMethod(fn)  # type: ignore[assignment]
    except TypeError:  # plain function/closure: no self to leak
        wfn = (lambda f=fn: f)
    with _lock:
        _providers.append((weakref.ref(obj), wfn))


def native_counters() -> dict[str, int]:
    """Merged raw totals from every live provider (no baseline).

    Totals sum across providers; gauges and ``*_hwm`` counters take
    the max — summing high-waters across engines would fabricate an
    occupancy no ring ever reached."""
    out: dict[str, int] = {k: 0 for k in NATIVE_COUNTERS}
    with _lock:
        live = list(_providers)
    dead = False
    for ref, wfn in live:
        fn = wfn()
        if ref() is None or fn is None:
            dead = True
            continue
        try:
            d = fn()
        except Exception:  # provider torn down mid-read
            continue
        if not d:
            continue
        for k, v in d.items():
            if k not in out:
                continue
            if k in GAUGES or k.endswith("_hwm"):
                out[k] = max(out[k], int(v))
            else:
                out[k] += int(v)
    if dead:
        with _lock:
            _providers[:] = [(r, f) for r, f in _providers
                             if r() is not None and f() is not None]
    return out


def register_clock_provider(obj, fn: Callable[[], dict | None]) -> None:
    """Register a clock-offset source (a live engine mapping peer
    addresses to root procs).  Same weakref-anchored lifetime rules as
    :func:`register_provider`."""
    try:
        wfn: Callable = weakref.WeakMethod(fn)  # type: ignore[assignment]
    except TypeError:
        wfn = (lambda f=fn: f)
    with _lock:
        _clock_providers.append((weakref.ref(obj), wfn))


def clock_offsets() -> dict[int, tuple[int, int]]:
    """Merged ``{root_proc: (offset_ns, rtt_ns)}`` across live engines
    — offset is (peer_clock − my_clock), the NTP-style single-sample
    estimate from the connection handshake; the smallest-RTT sample
    wins when several transports measured the same peer."""
    out: dict[int, tuple[int, int]] = {}
    with _lock:
        live = list(_clock_providers)
    dead = False
    for ref, wfn in live:
        fn = wfn()
        if ref() is None or fn is None:
            dead = True
            continue
        try:
            d = fn()
        except Exception:  # provider torn down mid-read
            continue
        for p, (off, rtt) in (d or {}).items():
            cur = out.get(int(p))
            if cur is None or rtt < cur[1]:
                out[int(p)] = (int(off), int(rtt))
    if dead:
        with _lock:
            _clock_providers[:] = [(r, f) for r, f in _clock_providers
                                   if r() is not None and f() is not None]
    return out


def native_value(name: str) -> int:
    """One counter, baseline-adjusted — the MPI_T pvar read."""
    raw = native_counters().get(name, 0)
    if name in GAUGES or name.endswith("_hwm"):
        return raw
    return max(0, raw - _native_base.get(name, 0))


def reset_native(name: str | None = None) -> None:
    """MPI_T pvar_reset: remember current totals as the baseline (the
    C block is append-only; Python owns reset semantics).  Gauges and
    high-water marks are exempt — baselining ``ring_hwm`` would make a
    still-pegged ring read 0 after a reset, the exact condition the
    counter exists to expose."""
    cur = native_counters()
    with _lock:
        for k in ([name] if name else NATIVE_COUNTERS):
            if k in cur and k not in GAUGES and not k.endswith("_hwm"):
                _native_base[k] = cur[k]


# -- pvar namespace (grow-only, like trace.span_ops) -------------------


def size_ops() -> list[str]:
    """Op names with ≥1 observation, FIRST-SEEN order — the
    ``metrics_size_<op>_hist`` pvar namespace.  Grow-only while
    metrics run (reset zeroes in place), so cached pvar indices stay
    valid — the same contract trace.span_ops keeps."""
    return list(_ops)


def size_histogram(op: str) -> list[int]:
    st = _ops.get(op)
    return list(st["size_hist"]) if st else [0] * SIZE_BUCKETS


def op_stats() -> dict[str, dict]:
    """Deep-copied per-op aggregates (report/export input)."""
    with _lock:
        return {
            k: dict(v, size_hist=list(v["size_hist"]),
                    lat_hist=list(v["lat_hist"]))
            for k, v in _ops.items()
        }


def zero_stats() -> None:
    """Zero every per-op aggregate IN PLACE (keys survive — cached
    pvar indices keep naming the same variable) and re-baseline the
    native counters — the session-wide MPI_T pvar_reset."""
    with _lock:
        for st in _ops.values():
            st["count"] = 0
            st["bytes"] = 0
            st["total_ns"] = 0
            st["max_ns"] = 0
            st["size_hist"] = [0] * SIZE_BUCKETS
            st["lat_hist"] = [0] * LAT_BUCKETS
    reset_native()


def reset_op(op: str) -> None:
    """Zero ONE op aggregate in place (single-handle pvar_reset)."""
    with _lock:
        st = _ops.get(op)
        if st is not None:
            st["count"] = 0
            st["bytes"] = 0
            st["total_ns"] = 0
            st["max_ns"] = 0
            st["size_hist"] = [0] * SIZE_BUCKETS
            st["lat_hist"] = [0] * LAT_BUCKETS


def reset(full: bool = True) -> None:
    """Test hook: drop all state (``full=False`` keeps providers)."""
    global _enabled
    with _lock:
        _ops.clear()
        _native_base.clear()
        if full:
            _providers.clear()
            _clock_providers.clear()
            _enabled = False
    from ompi_tpu.metrics import flight, straggler

    flight.reset()
    if full:
        straggler.reset()


# -- snapshots ---------------------------------------------------------


def snapshot(reason: str = "periodic", proc: int | None = None) -> dict:
    """One JSON-able view of both planes right now — the exporter,
    flight-recorder, and report-tool input."""
    snap = {
        "ts_ns": time.time_ns(),
        "reason": reason,
        "proc": proc,
        "native": native_counters(),
        "ops": op_stats(),
        "spc": _spc_snapshot(),
    }
    from ompi_tpu.faultsim import core as _fsim

    if _fsim._enabled:
        snap["faultsim"] = _fsim.counters()
    from ompi_tpu.metrics import straggler as _straggler

    if _straggler._enabled:
        snap["straggler"] = _straggler.summary()
    clock = clock_offsets()
    if clock:
        # {proc: [offset_ns, rtt_ns]} — the correlate/merge tools read
        # this to align cross-rank timelines against host clock skew
        snap["clock"] = {str(p): [o, r] for p, (o, r) in clock.items()}
    from ompi_tpu.trace import waitgraph as _waitgraph

    if _waitgraph._enabled:
        w = _waitgraph.snapshot(stacks=False)
        if w.get("waits"):
            # blocked-wait sites at snapshot time: crash exports carry
            # them so trace_report --hangs can diagnose post-mortem
            snap["waits"] = w["waits"]
    return snap


def _spc_snapshot() -> dict[str, int]:
    from ompi_tpu.tool import spc

    return spc.snapshot()


# -- MCA wiring --------------------------------------------------------


def register_vars(store) -> None:
    """Idempotent (the central registration in core.var already ran
    for the default context; private test stores call this directly)."""
    from ompi_tpu.core.var import register_observability_vars

    register_observability_vars(store)


def sync_from_store(store) -> None:
    # telemetry_enable implies the metrics hooks: the live endpoint
    # scrapes the same counters the finalize export writes
    enable(bool(store.get("metrics_enable", False))
           or bool(store.get("telemetry_enable", False)))
    from ompi_tpu.metrics import flight

    flight.configure(
        output=str(store.get("metrics_output", "") or ""),
        max_records=int(store.get("metrics_flight_records", 64)),
    )
