"""Flight recorder — counter snapshots at the moment something wedges.

The failure mode this exists for (ROADMAP open item): a windowed send
collapses or hangs, the process is killed, and the ring/rendezvous
state that explains it vanishes.  The recorder snapshots ALL counters
(both planes) on the events that precede that outcome —

* **request timeout / abort** — the DCN recv deadline expiring, a
  transport-level connection failure surfacing;
* **watermark crossings** — first time the native stall counters show
  real backpressure (stall time, rendezvous slot exhaustion), checked
  opportunistically from the Python hooks (cheap: every N events).

Records land in a bounded in-memory ring AND — when ``--mca
metrics_output`` is set — are appended immediately to
``<output>.flight.<proc>.jsonl`` (one JSON object per line), so a
process that dies mid-run still leaves its last ring state on disk.
``tools/metrics_report.py`` folds flight records into the stall
breakdown and the trace correlation.
"""

from __future__ import annotations

import collections
import json
import threading

_lock = threading.Lock()
_records: collections.deque = collections.deque(maxlen=64)
_output = ""
_proc: int | None = None
#: watermark thresholds: (name, level) crossed-once latches
_WATERMARKS = (
    ("stall_ns", 1_000_000),      # ≥1 ms cumulative send-side stall
    ("stall_ns", 1_000_000_000),  # ≥1 s — the wedge precursor
    ("slot_waits", 1),            # rendezvous slot table saturated
    ("ring_stalls", 1),           # first ring-backpressure block
)
_crossed: set = set()
#: opportunistic check cadence (every Nth observe-side call)
_CHECK_EVERY = 64
_check_tick = 0


def configure(output: str = "", max_records: int = 64,
              proc: int | None = None) -> None:
    global _output, _records, _proc
    with _lock:
        _output = output
        if proc is not None:
            _proc = proc
        if max_records != _records.maxlen:
            _records = collections.deque(_records,
                                         maxlen=max(1, int(max_records)))


def set_proc(proc: int) -> None:
    global _proc
    _proc = proc


def reset() -> None:
    global _check_tick
    with _lock:
        _records.clear()
        _crossed.clear()
        _check_tick = 0


def records() -> list[dict]:
    with _lock:
        return list(_records)


def record(reason: str, **extra) -> dict | None:
    """Snapshot both planes now, tagged with why.  No-op when metrics
    are disabled — the recorder must never add cost to an untelemetered
    run."""
    from ompi_tpu.metrics import core

    if not core._enabled:
        return None
    snap = core.snapshot(reason=reason, proc=_proc)
    if extra:
        snap["detail"] = {k: v for k, v in extra.items()
                         if isinstance(v, (str, int, float, bool))}
    with _lock:
        _records.append(snap)
        out = _output
    if out:
        # append NOW (crash-robust), never raise into the caller's
        # failure path — the recorder rides error handling
        try:
            path = f"{out}.flight.{_proc if _proc is not None else 0}.jsonl"
            with open(path, "a") as f:
                f.write(json.dumps(snap) + "\n")
        except OSError:
            pass
    return snap


def check_watermarks(force: bool = False) -> None:
    """Opportunistic watermark check — called from in-path hooks every
    ``_CHECK_EVERY`` events (one counter compare otherwise).  Each
    (counter, level) threshold latches once per run: the latch set
    mutates under the lock so two sender threads crossing a threshold
    on the same tick cannot both record it (duplicates would evict
    real records from the bounded ring); the snapshots themselves are
    taken outside the lock — :func:`record` re-acquires it."""
    global _check_tick
    from ompi_tpu.metrics import core

    if not core._enabled:
        return
    with _lock:
        _check_tick += 1
        if not force and _check_tick % _CHECK_EVERY:
            return
    native = core.native_counters()
    claimed = []
    with _lock:
        for name, level in _WATERMARKS:
            key = (name, level)
            if key not in _crossed and native.get(name, 0) >= level:
                _crossed.add(key)
                claimed.append((name, level))
    for name, level in claimed:
        record("watermark", counter=name, level=level,
               value=int(native.get(name, 0)))
